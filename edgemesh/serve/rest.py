"""REST gateway — the surviving host-side front door.

Parity: the reference exposes a FastAPI/uvicorn hello endpoint on port 8000
(``Code/gRPC/rest_api.py:9-15``) next to its gRPC fabric. In edgemesh the
data plane is XLA collectives (SURVEY.md §5.8), so REST remains only as the
human/programmatic entry point: health, one-question generate, batch eval
kick-off. Stdlib ``http.server`` — zero extra dependencies, threaded.

Endpoints:
- ``GET  /``          → health + device inventory (the "edge cluster map")
- ``GET  /healthz``   → liveness: 200 while the process serves at all —
  stays 200 through a drain (the fleet must not kill a draining replica)
- ``GET  /readyz``    → readiness: 200 only while accepting NEW work; 503
  (with the live in-flight count) once draining — what the fleet router's
  health prober and drain poll actually watch; carries the load digest
  under ``"load"`` so the prober refreshes it for free on its probe cadence
- ``GET  /loadz``     → the load digest alone: in-flight count, engine
  queue depth, queue/prefill/decode latency EWMAs from the span tracker,
  SLO goodput, and a recent-compile flag — what the fleet's telemetry
  balancer weighs replicas by (docs/OBSERVABILITY.md "Load digests")
- ``GET  /metrics``   → Prometheus text exposition (edgemesh.obs registry:
  request/TTFT/inter-token histograms, KV page + device-memory gauges)
- ``GET  /stats``     → the legacy JSON status blob (phases, supervisor
  health, batcher/engine stats) — what ``/metrics`` served pre-obs
- ``GET  /statusz``   → human-readable one-page status (plain text)
- ``POST /generate``  → {"question": str} → ensemble answer JSON
- ``POST /generate_stream`` → Server-Sent Events: ``data: {"delta": ...}``
  per decoded chunk, then ``data: {"answer": ..., "done": true}``
- ``POST /drain``     → flip to draining (readyz → 503, new generates →
  503) and finish in-flight work; the fleet's pre-stop hook
- ``POST /incident``  → {"id": ...}: dump the flight-recorder ring under a
  router-propagated incident id (obs/flight.py; the fleet's incident
  fan-out — docs/OBSERVABILITY.md "The flight recorder")
- ``POST /kv/export`` → {"question": str}: prefill the prompt's prefix and
  return its committed KV pages serialized (base64 wire payload,
  runtime/paged_kv.py) — the prefill half of tiered serving
- ``POST /kv/import`` → {"question", "kv", "max_new"?}: admit a request
  whose prefill ran on another replica by splicing the shipped pages;
  answers like ``/generate``. Both need a paged continuous engine; a
  corrupt/mismatched payload is a structured 400 (docs/FLEET.md "Tiered
  serving and KV streaming")
- ``GET  /debug/profile?seconds=N`` → opt-in (``profile_dir=`` /
  ``--profile-dir``) ``jax.profiler`` capture; returns the trace path

Distributed tracing: ``/generate*`` honors the ``X-Edgemesh-Trace``
context header (obs/trace.py) — the continuous engine's span record joins
the sender's trace (its spans become children of the fleet router's
attempt span) and compile events fired while handling the request are
stamped with it.

Robustness semantics (what the fleet router relies on): malformed bodies
are structured 400s (never 500), overload and draining answer 503 +
``Retry-After``, an already-expired propagated deadline
(``X-Edgemesh-Deadline-S`` ≤ 0) is refused with 504 before any model work,
and every connection carries a socket timeout so a stalled client costs a
bounded read, not a pinned ThreadingHTTPServer thread.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from edgemesh.serve import httputil

log = logging.getLogger("edgemesh.serve")

#: A backend compile within this window flags ``recent_compile`` in the
#: load digest: the replica is warming up (or churning shapes), and the
#: telemetry balancer should expect a latency cliff, not steady state.
RECENT_COMPILE_WINDOW_S = 30.0


#: Every route this gateway answers, by method — the dispatch tables the
#: handlers consult for the unknown-path 404, and what the wire dryrun
#: (analysis/wire.py, EM506) cross-checks against ``httputil.WIRE_CONTRACT``
#: in the fast tier: a route added here without a contract row (or vice
#: versa) fails in seconds, no sockets.
SERVED_ROUTES: dict[str, tuple[str, ...]] = {
    "GET": ("/", "/health", "/healthz", "/readyz", "/loadz", "/metrics",
            "/stats", "/statusz", "/debug/profile"),
    "POST": ("/drain", "/incident", httputil.KV_EXPORT_PATH,
             httputil.KV_IMPORT_PATH, "/generate", "/generate_stream"),
}


class GatewayServer(ThreadingHTTPServer):
    """ThreadingHTTPServer + serving lifecycle: in-flight request tracking
    and a ``drain()`` hook (what the fleet router calls — over ``POST
    /drain`` — before stopping a replica).

    Draining is one-way: new ``/generate*`` work is refused with 503,
    ``/readyz`` flips to 503 so the prober removes us from rotation, and
    in-flight requests run to completion. ``drain(wait=True)`` blocks until
    the in-flight count reaches zero (or ``timeout_s``), after which
    ``shutdown()`` + ``batcher.close()`` are guaranteed drop-free."""

    def __init__(self, addr, handler):
        super().__init__(addr, handler)
        self.batcher = None
        self.max_inflight = 0  # 0 = unbounded; serve_rest overrides
        self.profile_dir = None  # opt-in /debug/profile target
        self.anomaly = None  # AnomalyMonitor when the flight triggers are armed
        # jax profiles cannot nest: the lock guards only the ACTIVE flag
        # (edgelint EM303 — sleeping through the capture window while
        # holding a lock would convoy every other /debug/profile thread).
        self.profile_lock = threading.Lock()
        self.profile_active = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def inflight(self) -> int:
        with self._inflight_cv:
            return self._inflight

    def begin_request(self) -> str:
        """Admit one generate request: ``"ok"`` admits; ``"draining"`` /
        ``"overloaded"`` refuse (the handler answers 503 and must NOT call
        end_request). Check-and-increment is one atomic step under the
        lock — a burst of N+1 concurrent requests against
        ``max_inflight=N`` must shed exactly one, not all of them."""
        with self._inflight_cv:
            if self._draining:
                return "draining"
            if self.max_inflight and self._inflight >= self.max_inflight:
                return "overloaded"
            self._inflight += 1
            return "ok"

    def end_request(self) -> None:
        with self._inflight_cv:
            self._inflight = max(0, self._inflight - 1)
            self._inflight_cv.notify_all()

    def drain(self, wait: bool = True, timeout_s: float = 60.0) -> dict:
        with self._inflight_cv:
            self._draining = True
            if wait:
                self._inflight_cv.wait_for(
                    lambda: self._inflight == 0, timeout=timeout_s
                )
            inflight = self._inflight
        log.info("gateway draining (inflight=%d)", inflight)
        return {"draining": True, "drained": inflight == 0, "inflight": inflight}


def _make_handler(ensemble, supervisor=None, batcher=None, registry=None,
                  request_timeout_s=None):
    from edgemesh.obs import get_registry

    # Whether the batcher speaks trace contexts is fixed for the server's
    # lifetime — decide once, not per request. Only the engines do; the
    # DynamicBatcher coalesces requests and has no per-request span tree.
    batcher_speaks_trace = False
    if batcher is not None:
        from edgemesh.serve.continuous import ContinuousEngine

        batcher_speaks_trace = isinstance(batcher, ContinuousEngine)

    class Handler(BaseHTTPRequestHandler):
        # Per-connection socket timeout (StreamRequestHandler.setup applies
        # it to the request socket): a client that stalls mid-body or never
        # reads its response costs one bounded read/write, not a pinned
        # ThreadingHTTPServer thread.
        timeout = request_timeout_s

        def _send(self, code: int, payload: dict, extra: dict | None = None):
            httputil.send_json(self, code, payload, extra=extra)

        def _send_text(self, code: int, text: str,
                       content_type: str = "text/plain; charset=utf-8"):
            httputil.send_text(self, code, text, content_type=content_type)

        def _stats_payload(self) -> dict:
            from edgemesh.utils.tracing import phase_report

            payload = {"phases": phase_report()}
            if supervisor is not None:
                payload["supervisor"] = supervisor.health()
            if batcher is not None:
                payload["batcher"] = batcher.stats()
            return payload

        def _load_digest(self) -> dict:
            """The replica's live load digest (docs/OBSERVABILITY.md):
            everything the fleet's telemetry balancer needs, cheap enough
            to ride every health probe. Engines contribute queue depth +
            latency EWMAs + SLO goodput; non-continuous gateways degrade
            to in-flight count alone (the EWMA keys stay, as null)."""
            from edgemesh.obs.trace import (
                compile_cache_state,
                seconds_since_last_compile,
            )

            digest: dict = {
                "inflight": self.server.inflight(),
                "queue_depth": None,
                "ewma_queue_s": None, "ewma_prefill_s": None,
                "ewma_decode_s": None, "ewma_service_s": None,
                # Phase-volume split (prefill vs decode tokens): what the
                # fleet's tier manager scores replicas by for prefill/
                # decode disaggregation (docs/FLEET.md "Tiered serving").
                "ewma_prefill_tokens": None, "ewma_decode_tokens": None,
                # Arrival-rate side + the capacity model (docs/
                # OBSERVABILITY.md "The capacity model"): the autoscaler's
                # demand/supply signals. Null on non-continuous gateways.
                "ewma_arrival_s": None,
                # capacity/pool/mem: the capacity model, the coarse pool
                # gauges, and the memory observatory's attributed block
                # (obs/memory.py — tenants, fragmentation, leak rows, the
                # exhaustion forecast). Null on non-paged gateways.
                "capacity": None, "pool": None, "mem": None,
                "slo_goodput_ratio": None,
            }
            if batcher is not None and hasattr(batcher, "load_digest"):
                digest.update(batcher.load_digest())
            since = seconds_since_last_compile()
            digest["recent_compile"] = (
                since is not None and since < RECENT_COMPILE_WINDOW_S
            )
            # Persistent compilation-cache state: whether this replica was
            # spawned against the fleet's shared cache and how its compiles
            # resolved — the autoscaler's warm-start proof rides here.
            digest["compile_cache"] = compile_cache_state()
            # Incident propagation seam (obs/anomaly.py): the newest
            # locally-fired incident {id, kind, ts} rides the digest, so
            # the fleet prober sees it on its existing cadence and the
            # router can fan the id out to sibling replicas (/fleetz,
            # docs/FLEET.md "Incident propagation").
            anomaly = getattr(self.server, "anomaly", None)
            digest["incident"] = (
                anomaly.last_incident() if anomaly is not None else None
            )
            return digest

        def do_GET(self):
            # Unknown paths 404 through the declared dispatch table, so the
            # table (what the wire dryrun checks) is load-bearing: a handler
            # branch added without a SERVED_ROUTES entry is immediately 404.
            if not httputil.route_matches(httputil.route_base(self.path),
                                          SERVED_ROUTES["GET"]):
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            if self.path in ("/", "/health"):
                import jax

                self._send(
                    200,
                    {
                        "status": "ok",
                        "service": "edgemesh",
                        "time": time.strftime("%Y-%m-%d %H:%M:%S"),
                        "devices": [str(d) for d in jax.devices()],
                        "agents": [a.role for a in ensemble.qa_agents]
                        + ([ensemble.refiner.role] if ensemble.refiner else []),
                    },
                )
            elif self.path == "/healthz":
                # Liveness only: a DRAINING replica is still alive (it must
                # finish in-flight work before the fleet stops it).
                self._send(200, {"status": "ok"})
            elif self.path == "/readyz":
                # Readiness: what rotation membership keys on. Carries the
                # live in-flight count — the fleet's drain poll reads it to
                # know when this replica is safe to stop — and piggybacks
                # the load digest so the prober refreshes telemetry for
                # free on its existing probe cadence.
                draining = self.server.draining
                self._send(
                    503 if draining else 200,
                    {"ready": not draining, "draining": draining,
                     "inflight": self.server.inflight(),
                     "load": self._load_digest()},
                )
            elif self.path == "/loadz":
                self._send(200, self._load_digest())
            elif self.path == "/metrics":
                # Prometheus text exposition from the obs registry (device
                # gauges sample inside render() via the registered
                # collector). The pre-obs JSON blob moved to /stats.
                self._send_text(
                    200, (registry or get_registry()).render(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )
            elif self.path == "/stats":
                self._send(200, self._stats_payload())
            elif (self.path == "/debug/profile"
                  or self.path.startswith("/debug/profile?")):
                self._profile()
            elif self.path == "/statusz":
                self._send_text(200, _render_statusz(
                    ensemble, self._stats_payload(),
                    registry or get_registry(),
                ))
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def _profile(self):
            """Opt-in ``GET /debug/profile?seconds=N``: capture a
            ``jax.profiler`` device/host trace under the configured
            ``profile_dir`` and return its path. Disabled (403) unless the
            gateway was started with a profile dir — captures cost real CPU,
            write to disk, and expose program structure, so this must never
            be reachable by default (docs/OBSERVABILITY.md security note).
            One capture at a time: ``jax.profiler`` traces cannot nest."""
            from pathlib import Path
            from urllib.parse import parse_qs, urlparse

            prof_dir = getattr(self.server, "profile_dir", None)
            if not prof_dir:
                self._send(403, {"error": "profiling disabled (opt in with "
                                          "--profile-dir / profile_dir=)"})
                return
            q = parse_qs(urlparse(self.path).query)
            try:
                seconds = float(q.get("seconds", ["2"])[0])
            except ValueError:
                self._send(400, {"error": "'seconds' must be a number"})
                return
            if not 0 < seconds <= 60:
                self._send(400, {"error": "'seconds' must be in (0, 60]"})
                return
            # One capture at a time, WITHOUT holding the lock through the
            # capture window: the lock guards only the check-and-set of the
            # active flag (EM303 — a lock held across the sleep would make
            # every concurrent profile request convoy instead of 409ing).
            with self.server.profile_lock:
                busy = self.server.profile_active
                if not busy:
                    self.server.profile_active = True
            if busy:
                # Answer OUTSIDE the lock: _send is socket I/O, and a
                # stalled client must not extend the critical section.
                self._send(409, {"error": "a profile capture is already "
                                          "running"}, extra={httputil.RETRY_AFTER_HEADER: "1"})
                return
            try:
                from edgemesh.utils.tracing import capture_profile

                out = Path(prof_dir) / time.strftime("profile-%Y%m%d-%H%M%S")
                with capture_profile(out):
                    time.sleep(seconds)
                self._send(200, {"path": str(out), "seconds": seconds})
            except Exception as exc:
                log.exception("profile capture failed")
                self._send(500, {"error": str(exc), "kind": "internal"})
            finally:
                with self.server.profile_lock:
                    self.server.profile_active = False

        def _stream(self, question: str):
            """SSE: one `data:` line per streamed item (text/event-stream).

            Owns ALL error handling past this point — once the 200 header is
            out, do_POST's JSON _send(500) would corrupt the event stream.
            Client disconnects stop the stream quietly (not a backend
            failure); generation errors surface as a final ``error`` event
            and count against the supervisor's failure budget."""
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()

            def produce():
                # Stream from the supervisor's (restartable) backend when it
                # can stream — after a restart this picks up the REBUILT
                # ensemble, so restarts triggered by stream failures actually
                # heal the stream path too.
                source = ensemble
                if supervisor is not None and hasattr(
                    getattr(supervisor, "backend", None), "answer_stream"
                ):
                    source = supervisor.backend
                for item in source.answer_stream(question):
                    try:
                        self.wfile.write(f"data: {json.dumps(item)}\n\n".encode())
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionError):
                        log.info("stream client disconnected")
                        return

            try:
                if supervisor is not None:
                    supervisor.track(produce)
                else:
                    produce()
            except Exception as exc:
                log.exception("stream generation failed")
                try:
                    self.wfile.write(
                        f"data: {json.dumps({'error': str(exc), 'done': True})}\n\n".encode()
                    )
                    self.wfile.flush()
                except OSError:
                    pass

        def _read_json(self) -> dict | None:
            """Parse the request body; answers the 400 itself on bad input —
            a client-input problem is always a structured 400, never a 500
            (shared with the fleet frontend via serve/httputil.py)."""
            return httputil.read_json_body(self)

        def do_POST(self):
            try:
                self._post()
            except TimeoutError:
                # Stalled client mid-read/write: drop the connection — the
                # per-connection socket timeout exists precisely so this
                # thread is reclaimed instead of pinned forever.
                log.warning("client socket timeout on %s", self.path)
                self.close_connection = True

        def _post(self):
            # Same table-driven 404 as do_GET: SERVED_ROUTES is the one
            # dispatch inventory the wire dryrun cross-checks.
            if not httputil.route_matches(httputil.route_base(self.path),
                                          SERVED_ROUTES["POST"]):
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            if self.path == "/drain":
                # The fleet's pre-stop hook: flip to draining NOW (readyz →
                # 503, new generates → 503) without blocking the admin call
                # on in-flight work — the caller polls /readyz for
                # inflight == 0 (fleet/router.drain_replica).
                self._send(200, self.server.drain(wait=False))
                return
            if self.path == "/incident":
                # The router's incident broadcast (fleet/router.py): dump
                # this replica's flight ring under the propagated id so the
                # whole fleet's rings land in ONE incident directory.
                # Idempotent per id; a replica without a recorder answers
                # honestly instead of 404ing the fleet's fan-out.
                payload = self._read_json()
                if payload is None:
                    return
                incident_id = payload.get("id")
                if not incident_id or not isinstance(incident_id, str):
                    self._send(400, {"error": "missing 'id' field"})
                    return
                anomaly = getattr(self.server, "anomaly", None)
                if anomaly is None:
                    self._send(200, {"accepted": False,
                                     "error": "no flight recorder armed"})
                    return
                rec = anomaly.note_incident(
                    incident_id,
                    detail={"origin_kind": payload.get("kind"),
                            "source": payload.get("source")},
                )
                self._send(200, {
                    "accepted": True, "dumped": rec is not None,
                    "path": None if rec is None else rec.get("path"),
                })
                return
            if self.path in (httputil.KV_EXPORT_PATH, httputil.KV_IMPORT_PATH):
                # Cross-replica KV transfer (docs/FLEET.md "Tiered serving
                # and KV streaming"): export serializes a prompt prefix's
                # committed pages, import admits a request whose prefill
                # ran elsewhere. Deadline/trace/tenant propagation and the
                # draining/overload admission gate match /generate.
                self._kv_transfer()
                return
            if self.path not in ("/generate", "/generate_stream"):
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            ok, deadline_s = httputil.read_deadline_header(self)
            if not ok:
                return
            if deadline_s is not None and deadline_s <= 0:
                # The router's budget is already spent: refuse before any
                # model work — the answer could only arrive dead.
                self._send(504, {"error": "propagated deadline already expired",
                                 "kind": "deadline"})
                return
            # Distributed-trace context (the router's attempt span): the
            # engine's spans join it, and compile events fired while this
            # request is being handled get stamped with it. The tenant
            # identity propagated alongside it attributes the engine's
            # span record and per-tenant SLO metrics (obs/slo.py).
            trace_ctx = httputil.read_trace_header(self)
            tenant = httputil.read_tenant_header(self)
            session = httputil.read_session_header(self)
            payload = self._read_json()
            if payload is None:
                return
            # Bounded admission: draining and overload both shed with an
            # honest 503 + Retry-After instead of queueing every thread on
            # the engine (the fleet router retries elsewhere).
            verdict = self.server.begin_request()
            if verdict == "draining":
                self._send(503, {"error": "draining: not accepting new requests",
                                 "kind": "draining"},
                           extra={httputil.RETRY_AFTER_HEADER: "1"})
                return
            if verdict == "overloaded":
                self._send(503, {"error": "overloaded", "kind": "overloaded",
                                 "max_inflight": self.server.max_inflight},
                           extra={httputil.RETRY_AFTER_HEADER: "1"})
                return
            try:
                from edgemesh.obs.trace import use_trace

                with use_trace(trace_ctx):
                    self._generate(payload, trace_ctx, tenant, session)
            finally:
                self.server.end_request()

        def _kv_transfer(self):
            """``POST /kv/export`` and ``POST /kv/import`` — the replica
            half of prefill/decode disaggregation. Capability-gated: only
            a paged continuous engine can speak the wire format, and a
            corrupted / version-mismatched / wrong-geometry payload is a
            structured 400 (``kind: "kv_wire"``), never a 500 — the fleet
            router treats any non-200 as a graceful fallback signal."""
            if not getattr(batcher, "supports_kv_transfer", False):
                self._send(400, {
                    "error": "KV transfer needs --continuous with a paged "
                    "kv_backend (and a non-speculative engine)",
                    "kind": "kv_capability",
                })
                return
            ok, deadline_s = httputil.read_deadline_header(self)
            if not ok:
                return
            if deadline_s is not None and deadline_s <= 0:
                self._send(504, {"error": "propagated deadline already expired",
                                 "kind": "deadline"})
                return
            trace_ctx = httputil.read_trace_header(self)
            tenant = httputil.read_tenant_header(self)
            session = httputil.read_session_header(self)
            payload = self._read_json()
            if payload is None:
                return
            question = payload.get("question")
            if not question or not isinstance(question, str):
                self._send(400, {"error": "missing 'question' field"})
                return
            verdict = self.server.begin_request()
            if verdict == "draining":
                self._send(503, {"error": "draining: not accepting new requests",
                                 "kind": "draining"},
                           extra={httputil.RETRY_AFTER_HEADER: "1"})
                return
            if verdict == "overloaded":
                self._send(503, {"error": "overloaded", "kind": "overloaded",
                                 "max_inflight": self.server.max_inflight},
                           extra={httputil.RETRY_AFTER_HEADER: "1"})
                return
            try:
                from edgemesh.obs.trace import use_trace

                with use_trace(trace_ctx):
                    if self.path == httputil.KV_EXPORT_PATH:
                        self._kv_export(question, trace_ctx, tenant, session)
                    else:
                        self._kv_import(payload, question, trace_ctx,
                                        tenant, session)
            finally:
                self.server.end_request()

        def _kv_export(self, question, trace_ctx, tenant, session):
            try:
                result = batcher.submit_export(
                    question, trace_ctx=trace_ctx, tenant=tenant,
                    session=session,
                ).result()
            except ValueError as exc:
                # A prompt the wire cannot carry (too short, over-capacity)
                # is the caller's input problem, answered structurally.
                self._send(400, {"error": str(exc), "kind": "kv_wire"})
                return
            except Exception as exc:
                log.exception("kv export failed")
                self._send(500, {"error": str(exc), "kind": "internal"})
                return
            self._send(200, {
                "kv": httputil.encode_kv_b64(result["kv_bytes"]),
                "tokens": result["tokens"],
                "prompt_tokens": result["prompt_tokens"],
                "bytes": len(result["kv_bytes"]),
                "cached": result["cached"],
            })

        def _kv_import(self, payload, question, trace_ctx, tenant, session):
            from edgemesh.runtime.paged_kv import KVWireError

            max_new = payload.get("max_new")
            if max_new is not None and (
                isinstance(max_new, bool)
                or not isinstance(max_new, int)
                or max_new < 1
            ):
                self._send(400, {"error": "'max_new' must be a positive int"})
                return
            try:
                buf = httputil.decode_kv_b64(payload.get("kv"))
                # Header + geometry gate on THIS thread: a bad payload is
                # refused before it ever queues behind real admissions.
                batcher.check_kv_payload(buf)
            except (ValueError, KVWireError) as exc:
                self._send(400, {"error": f"bad KV payload: {exc}",
                                 "kind": "kv_wire"})
                return
            try:
                result = batcher.answer(
                    question, max_new=max_new, trace_ctx=trace_ctx,
                    tenant=tenant, session=session, kv_import=buf,
                )
            except KVWireError as exc:
                self._send(400, {"error": f"bad KV payload: {exc}",
                                 "kind": "kv_wire"})
                return
            except Exception as exc:
                log.exception("kv import failed")
                self._send(500, {"error": str(exc), "kind": "internal"})
                return
            self._send(200, result)

        def _generate(self, payload: dict, trace_ctx=None, tenant=None,
                      session=None):
            try:
                question = payload.get("question")
                if not question:
                    self._send(400, {"error": "missing 'question' field"})
                    return
                # Per-request "max_new" caps one request's budget. ONE
                # validation + capability gate up front, shared by every
                # arm: a client-input problem is always a 400 (never a
                # silent ignore or a 500). bool is an int subtype in
                # Python — reject it explicitly.
                max_new = payload.get("max_new")
                if max_new is not None and (
                    isinstance(max_new, bool)
                    or not isinstance(max_new, int)
                    or max_new < 1
                ):
                    self._send(400, {"error": "'max_new' must be a positive int"})
                    return
                if max_new is not None:
                    from edgemesh.serve.continuous import (
                        ContinuousEngine,
                        SpeculativeContinuousEngine,
                    )

                    # The spec engine's submit() raises on max_new (one
                    # uniform budget per pool); the stream path never
                    # reaches the engine submit with a budget at all.
                    if (
                        self.path == "/generate_stream"
                        or not isinstance(batcher, ContinuousEngine)
                        or isinstance(batcher, SpeculativeContinuousEngine)
                    ):
                        self._send(400, {
                            "error": "'max_new' needs non-streaming "
                            "--continuous serving with a non-speculative "
                            "engine (uniform budget per pool)"
                        })
                        return
                if self.path == "/generate_stream":
                    self._stream(question)
                    return
                if batcher is not None:
                    # Concurrent requests coalesce into one batched decode
                    # (serve/batcher.py) — the ThreadingHTTPServer gives each
                    # request its own thread, so under load the batcher sees
                    # them simultaneously.
                    kwargs = {}
                    if batcher_speaks_trace:
                        kwargs["trace_ctx"] = trace_ctx
                        # Tenant/session ride only the engines that speak
                        # spans — the DynamicBatcher coalesces requests and
                        # has no per-request record to attribute.
                        kwargs["tenant"] = tenant
                        kwargs["session"] = session
                    if max_new is not None:
                        kwargs["max_new"] = max_new
                    result = batcher.answer(question, **kwargs)
                elif supervisor is not None:
                    result = supervisor.call(question)
                else:
                    result = ensemble.answer(question)
                self._send(200, result)
            except Exception as exc:  # serving loop must survive bad requests
                log.exception("generate failed")
                self._send(500, {"error": str(exc), "kind": "internal"})

        def log_message(self, fmt, *args):  # route through logging, not stderr
            log.info("%s %s", self.address_string(), fmt % args)

    return Handler


def _render_statusz(ensemble, stats: dict, registry) -> str:
    """One human-readable page: who is serving, how it is doing. Plain text
    — statusz is for a person mid-incident, not a scraper."""
    lines = ["edgemesh statusz", "================", ""]
    agents = [a.role for a in ensemble.qa_agents] + (
        [ensemble.refiner.role] if ensemble.refiner else []
    )
    lines.append(f"agents: {', '.join(agents) or '(none)'}")
    sup = stats.get("supervisor")
    if sup:
        lines.append(
            f"supervisor: {'healthy' if sup.get('healthy') else 'DEGRADED'} "
            f"requests={sup.get('total_requests')} "
            f"failures={sup.get('total_failures')} "
            f"restarts={sup.get('restarts')}"
        )
    eng = stats.get("batcher")
    if eng:
        lines.append("engine: " + " ".join(
            f"{k}={v}" for k, v in eng.items() if not isinstance(v, dict)
        ))
    phases = stats.get("phases") or {}
    if phases:
        lines.append("")
        lines.append("phases (trace() regions):")
        for name, rep in sorted(phases.items()):
            lines.append(
                f"  {name}: n={rep['count']} total={rep['total_s']:.3f}s "
                f"mean={rep['mean_s'] * 1e3:.1f}ms"
            )
    summary = registry.summary()
    goodput = sorted(
        (k, v) for k, v in summary.items()
        if k.startswith("edgemesh_slo_goodput_ratio") and not isinstance(v, dict)
    )
    if goodput:
        lines.append("")
        lines.append("slo goodput (fraction meeting TTFT+TPOT targets):")
        for key, v in goodput:
            lines.append(f"  {key}: {v:.3f}")
    # Per-tenant goodput (tenant labels bounded via bounded_label): only
    # present once tenant-tagged traffic has arrived — single-tenant
    # deployments keep the exact pre-tenant page.
    tenant_goodput = sorted(
        (k, v) for k, v in summary.items()
        if k.startswith("edgemesh_slo_tenant_goodput_ratio")
        and not isinstance(v, dict)
    )
    if tenant_goodput:
        lines.append("")
        lines.append("per-tenant slo goodput:")
        for key, v in tenant_goodput:
            lines.append(f"  {key}: {v:.3f}")
    if summary:
        lines.append("")
        lines.append("metrics (obs registry):")
        for key in sorted(summary):
            v = summary[key]
            if isinstance(v, dict):
                lines.append(
                    f"  {key}: count={v['count']} mean={v['mean'] * 1e3:.1f}ms"
                )
            else:
                lines.append(f"  {key}: {v:g}")
    return "\n".join(lines) + "\n"


def serve_rest(ensemble, host: str = "0.0.0.0", port: int = 8000, block: bool = True,
               supervisor=None, batch: int = 0, batch_wait_s: float = 0.02,
               continuous: bool = False, kv_backend: str = "dense",
               kv_page_size: int = 64, admission: str = "fifo",
               span_log=None, registry=None, max_inflight: int = 0,
               request_timeout_s: float | None = 300.0,
               trace_sample: float = 1.0, profile_dir=None,
               tp: int = 0, collective_mode: str = "psum",
               collective_dtype: str = "int8",
               flight_capacity: int | None = None, flight_dir=None,
               compile_cache_dir=None):
    """Start the gateway (reference binds 0.0.0.0:8000, rest_api.py:15).

    With a ``supervisor`` (serve/supervisor.py), /generate routes through its
    failure-tracked call path and /metrics exposes its health, giving the
    gateway crash-recovery the reference's fabric never had. ``batch > 1``
    adds a DynamicBatcher: concurrent /generate requests coalesce into one
    batched decode (serve/batcher.py). With BOTH, each coalesced batch routes
    through ``supervisor.call`` as one request (failure tracking and restarts
    stay engaged) — the supervisor's handler must accept a list of questions
    and return a list of results.

    ``continuous=True`` (single-QA-agent ensembles only) swaps the batch-
    then-drain batcher for the chunk-granular ContinuousEngine
    (serve/continuous.py): requests join/leave the resident decode loop at
    segment boundaries; ``batch`` sizes the slot pool. ``kv_backend``
    ("dense" | "dense_int8" | "paged" | "paged_int8") picks the engine's KV
    memory model — the paged pool gives zero-copy admission and page
    reclamation (serve/continuous.py module docstring). ``admission``
    ("fifo" | "sjf") picks the engine's queue policy; /generate accepts an
    optional per-request ``max_new`` budget under continuous serving.

    ``span_log`` (a JSONL path, continuous only) flushes one request-span
    record per retirement — replayable offline via ``edgemesh obs``.
    ``registry`` overrides the process-default obs registry that /metrics
    and /statusz read (tests isolate through it).

    ``trace_sample`` (continuous only) is the span-I/O sampling rate for
    locally-originated requests — sampled-out requests write no span
    record but still count in every metric; requests carrying an
    ``X-Edgemesh-Trace`` header use the router's sampling bit instead.
    ``profile_dir`` opts in ``GET /debug/profile?seconds=N`` captures
    (disabled when None — see the security note in docs/OBSERVABILITY.md).

    ``tp > 1`` (continuous only) serves through the tensor-parallel
    shard_map engine (parallel/tp_infer.py) on a dp=1 × tp mesh:
    ``collective_mode`` ("psum" | "qpsum" | "qpsum_overlap") and
    ``collective_dtype`` ("int8" | "fp8" | "bf16") pick the cross-chip
    join for the row-sharded projections (parallel/collectives.py — the
    quantized/overlapped wire is how tp8 serving earns its chips).

    ``flight_capacity`` (continuous only) sizes the always-on flight
    recorder ring — full-fidelity span records regardless of
    ``trace_sample``, dumped as JSONL only when an anomaly trigger fires
    (obs/flight.py; None = the default capacity, 0 disables).
    ``flight_dir`` arms the anomaly triggers (obs/anomaly.py): SLO-miss
    burst, queue collapse, error spike, compile storm each dump the ring
    into ``<flight_dir>/<incident_id>/``, and ``POST /incident`` dumps
    under a router-propagated id so a fleet's rings land in one incident
    directory (docs/OBSERVABILITY.md "The flight recorder").

    ``max_inflight`` bounds concurrently-admitted generate requests (past
    it: 503 + Retry-After; 0 = unbounded). ``request_timeout_s`` is the
    per-connection socket timeout (None disables). The returned server is a
    :class:`GatewayServer`: ``srv.drain()`` (or ``POST /drain``) stops
    admission, flips ``/readyz`` to 503, and lets in-flight work finish —
    the fleet router's pre-stop contract (edgemesh/fleet/).

    ``compile_cache_dir`` points jax's persistent compilation cache at a
    directory shared across replica spawns (utils/compat.py
    ``enable_compilation_cache``): a scale-up replica's compiles become
    disk-cache hits and cold-start-to-first-token drops from compile time
    to load time (docs/FLEET.md "Autoscaling with warm starts"). Must be
    set BEFORE the engine's first compile — which this placement
    guarantees. The ``compile_cache`` block in the load digest reports the
    live hit/miss tally."""
    from edgemesh.obs import register_device_gauges

    if compile_cache_dir is not None:
        from edgemesh.utils.compat import enable_compilation_cache

        if not enable_compilation_cache(compile_cache_dir):
            log.warning("compile_cache_dir=%s: this jax cannot persist its "
                        "compilation cache; serving cold", compile_cache_dir)
    register_device_gauges(registry)
    batcher = None
    if span_log is not None and not continuous:
        raise ValueError(
            "span_log requires continuous=True (request-lifecycle spans "
            "live in the ContinuousEngine)"
        )
    if kv_backend != "dense" and not continuous:
        raise ValueError(
            f"kv_backend={kv_backend!r} requires continuous=True (the paged "
            "pool lives in the ContinuousEngine); add --continuous, or drop "
            "the flag for the dense batched paths"
        )
    if admission != "fifo" and not continuous:
        raise ValueError(
            f"admission={admission!r} requires continuous=True (the queue "
            "policy lives in the ContinuousEngine); add --continuous, or "
            "drop the flag for the batched paths"
        )
    if (flight_dir is not None or flight_capacity is not None) and not continuous:
        raise ValueError(
            "flight_dir/flight_capacity require continuous=True (the "
            "flight recorder rides the ContinuousEngine's span tracker)"
        )
    if flight_dir is not None and flight_capacity == 0:
        raise ValueError(
            "flight_dir needs a flight recorder — drop flight_capacity=0, "
            "or drop the dump directory"
        )
    if tp and int(tp) > 1 and not continuous:
        raise ValueError(
            f"tp={tp} requires continuous=True (tensor-parallel serving "
            "runs through the ContinuousEngine over the shard_map engine); "
            "add --continuous, or drop the flag — silently serving "
            "single-chip would misreport the deployment"
        )
    if continuous:
        from edgemesh.serve.continuous import make_engine

        if supervisor is not None:
            raise ValueError(
                "continuous batching does not route through the supervisor "
                "(its failure tracking would be silently bypassed); use "
                "--batch with a supervisor, or continuous without one"
            )
        if len(ensemble.qa_agents) != 1 or ensemble.refiner is not None:
            raise ValueError(
                "continuous batching serves a single-QA-agent ensemble "
                f"(got {len(ensemble.qa_agents)} agents"
                f"{' + refiner' if ensemble.refiner else ''}); use --batch "
                "for multi-agent ensembles"
            )
        tp_engine = None
        if tp and int(tp) > 1:
            from edgemesh.parallel.mesh import build_mesh
            from edgemesh.parallel.tp_infer import TPInferenceEngine

            if kv_backend != "dense":
                raise ValueError(
                    f"tp={tp} serving runs on kv_backend='dense' "
                    f"(got {kv_backend!r})"
                )
            agent = ensemble.qa_agents[0]
            tp_engine = TPInferenceEngine(
                agent.cfg, agent.params, build_mesh(dp=1, tp=int(tp)),
                collective_mode=collective_mode, comm_dtype=collective_dtype,
            )
        elif collective_mode != "psum":
            raise ValueError(
                f"collective_mode={collective_mode!r} needs tp > 1 (the "
                "collective joins live in the tensor-parallel engine); add "
                "--tp N, or drop the flag"
            )
        # A draft-carrying agent on the paged backend gets the speculative
        # engine (pool-wide draft→verify rounds); otherwise the plain one.
        batcher = make_engine(
            ensemble.qa_agents[0], slots=batch or 8, kv_backend=kv_backend,
            page_size=kv_page_size, admission=admission, span_log=span_log,
            registry=registry, trace_sample=trace_sample,
            tp_engine=tp_engine,
        )
        # Flight recorder: always-on by default (bounded ring, one deque
        # append per retirement — cheap enough to never turn off;
        # recorder_overhead_* in the bench pins the claim). flight_dir
        # additionally arms the anomaly triggers that dump it.
        if flight_capacity is None or flight_capacity > 0:
            from edgemesh.obs.flight import FlightRecorder

            flight_kwargs = {}
            if flight_capacity is not None:
                flight_kwargs["capacity"] = int(flight_capacity)
            flight = FlightRecorder(registry=batcher.obs.registry,
                                    snapshot_source=batcher.load_digest,
                                    **flight_kwargs)
            batcher.obs.flight = flight
            if flight_dir is not None:
                from edgemesh.obs.anomaly import AnomalyMonitor

                anomaly = AnomalyMonitor(flight, flight_dir,
                                         registry=batcher.obs.registry)
                batcher.obs.anomaly = anomaly
    elif batch > 1:
        from edgemesh.serve.batcher import DynamicBatcher

        backend = ensemble.answer_batch if supervisor is None else supervisor.call
        batcher = DynamicBatcher(backend, max_batch=batch, max_wait_s=batch_wait_s)
    server = GatewayServer(
        (host, port),
        _make_handler(ensemble, supervisor, batcher, registry,
                      request_timeout_s=request_timeout_s),
    )
    # Expose the batcher/engine for lifecycle management: srv.shutdown()
    # stops only the HTTP loop — an engine's resident worker thread and
    # KV pools need srv.batcher.close() (tests and embedders rely on it).
    server.batcher = batcher
    server.max_inflight = max_inflight
    server.profile_dir = profile_dir
    if batcher is not None:
        server.anomaly = getattr(getattr(batcher, "obs", None), "anomaly", None)
    log.info("edgemesh REST gateway on %s:%d", host, port)
    if block:
        server.serve_forever()
        return server
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
