"""Failure detection + deterministic restart for the serving loop.

The reference's failure story is a troubleshooting table in a README
(``Code/gRPC/README.md:59-66``) and per-sample try/except zero-fill
(``combiner_fp.py:448-454``); a crashed model process stays crashed
(SURVEY.md §5.3). Here the serving path gets a real supervisor:

- every request is health-tracked (consecutive-failure counter, last
  success/failure timestamps, rolling latency);
- after ``max_consecutive_failures`` the supervisor declares the backend
  unhealthy and rebuilds it from its factory — for model backends that means
  re-materializing params from the serving snapshot
  (runtime/checkpoint.snapshot_for_serving), which is deterministic:
  inference-only state is params + config, nothing else to lose;
- restarts are bounded (``max_restarts``) so a poisoned snapshot cannot
  flap forever; past the budget the supervisor reports permanently degraded
  and surfaces the last error instead of looping.

Events are appended to a JSONL log (one object per line — the same
structured-log convention as the eval harness) for offline inspection.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable

from edgemesh.utils.tracing import JsonlLogger

log = logging.getLogger("edgemesh.supervisor")


class Supervisor:
    """Wraps a request handler with health tracking and restart-from-factory.

    ``factory`` builds (or rebuilds) the backend; ``handler(backend, request)``
    serves one request. The supervisor owns the backend instance.
    """

    def __init__(
        self,
        factory: Callable[[], Any],
        handler: Callable[[Any, Any], Any],
        max_consecutive_failures: int = 3,
        max_restarts: int = 5,
        event_log: str | Path | None = None,
        latency_window: int = 100,
        registry=None,
    ):
        from edgemesh.obs import get_registry

        self._factory = factory
        self._handler = handler
        self._max_fail = max_consecutive_failures
        self._max_restarts = max_restarts
        self._logger = JsonlLogger(event_log) if event_log else None
        self._lock = threading.Lock()
        self._restart_in_progress = False
        # Lifecycle events as labeled counters (start/request_failed/restart/
        # restart_ok/restart_failed/degraded) + a request-latency histogram —
        # the /metrics view of the health dict below.
        reg = registry or get_registry()
        self._events_counter = reg.counter(
            "edgemesh_supervisor_events_total",
            "Supervisor lifecycle events by kind", ("kind",),
        )
        self._latency_hist = reg.histogram(
            "edgemesh_supervisor_request_seconds",
            "Supervised request wall time (successes only)",
        )

        self.backend = factory()
        self.consecutive_failures = 0
        self.total_failures = 0
        self.total_requests = 0
        self.restarts = 0
        self.degraded = False
        self.last_error: str | None = None
        self.last_success_ts: float | None = None
        self.last_failure_ts: float | None = None
        self._latencies: deque[float] = deque(maxlen=latency_window)
        self._event("start")

    # -- health ------------------------------------------------------------

    def health(self) -> dict[str, Any]:
        with self._lock:
            lat = sorted(self._latencies)
            p50 = lat[len(lat) // 2] if lat else None
            return {
                "healthy": not self.degraded,
                "degraded": self.degraded,
                "total_requests": self.total_requests,
                "total_failures": self.total_failures,
                "consecutive_failures": self.consecutive_failures,
                "restarts": self.restarts,
                "last_error": self.last_error,
                "last_success_ts": self.last_success_ts,
                "last_failure_ts": self.last_failure_ts,
                "p50_latency_s": p50,
            }

    def _event(self, kind: str, **extra):
        self._events_counter.labels(kind=kind).inc()
        if self._logger is not None:
            self._logger.log(kind, **extra)

    # -- serving -----------------------------------------------------------

    def call(self, request: Any) -> Any:
        """Serve one request; raises the backend's exception to the caller
        after recording it (the HTTP layer turns it into a 5xx)."""
        return self.track(lambda: self._handler(self.backend, request))

    def track(self, fn: Callable[[], Any]) -> Any:
        """Run one unit of serving work under the same failure tracking and
        restart policy as ``call`` — for work that doesn't fit the
        one-request handler shape (e.g. consuming a whole SSE stream)."""
        with self._lock:
            self.total_requests += 1
        # Feeds the obs request-latency histogram below (EM107: this clock
        # IS the obs instrumentation, not a bypass of it).
        t0 = time.perf_counter()  # edgelint: disable=EM107
        try:
            result = fn()
        except Exception as exc:
            with self._lock:
                self.total_failures += 1
                self.consecutive_failures += 1
                # Local copy: the post-lock _event/restart below must log THIS
                # request's error even if a concurrent failure overwrites
                # self.last_error in the meantime.
                error = self.last_error = f"{type(exc).__name__}: {exc}"
                self.last_failure_ts = time.time()  # edgelint: disable=EM107
                # One restart per incident: the thread that trips the
                # threshold claims the restart; concurrent failures while it
                # is rebuilding must not burn extra budget.
                need_restart = (
                    self.consecutive_failures >= self._max_fail
                    and not self.degraded
                    and not self._restart_in_progress
                )
                if need_restart:
                    self._restart_in_progress = True
            self._event("request_failed", error=error)
            if need_restart:
                try:
                    self.restart(reason=error)
                finally:
                    with self._lock:
                        self._restart_in_progress = False
            raise
        latency = time.perf_counter() - t0  # edgelint: disable=EM107
        with self._lock:
            self.consecutive_failures = 0
            self.last_success_ts = time.time()  # edgelint: disable=EM107
            self._latencies.append(latency)
        self._latency_hist.observe(latency)
        return result

    def restart(self, reason: str = "manual") -> bool:
        """Rebuild the backend from the factory. Returns True on success."""
        with self._lock:
            if self.restarts >= self._max_restarts:
                self.degraded = True
                self._event("degraded", reason=reason)
                log.error("supervisor degraded (restart budget spent): %s", reason)
                return False
            self.restarts += 1
        log.warning("restarting backend (restart %d): %s", self.restarts, reason)
        self._event("restart", reason=reason, attempt=self.restarts)
        try:
            new_backend = self._factory()
        except Exception as exc:
            with self._lock:
                self.last_error = f"restart failed: {type(exc).__name__}: {exc}"
                self.degraded = self.restarts >= self._max_restarts
            self._event("restart_failed", error=self.last_error)
            return False
        with self._lock:
            self.backend = new_backend
            self.consecutive_failures = 0
        self._event("restart_ok", attempt=self.restarts)
        return True
