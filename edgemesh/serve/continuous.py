"""Continuous batching: requests join and leave the decode loop mid-flight.

The DynamicBatcher (serve/batcher.py) forms a batch, runs it to COMPLETION,
then forms the next — a request arriving one token after dispatch waits out
the whole previous batch. Real serving engines instead keep one resident
decode loop whose batch composition changes as requests arrive/finish
(vLLM-style continuous batching). A statically-shaped jitted TPU loop cannot
admit rows mid-program, but the segmented decode (runtime/stream.py) already
re-enters the host every ``chunk`` tokens — so edgemesh does continuous
batching at CHUNK granularity:

- A fixed pool of ``slots`` rows shares one KV cache and one compiled
  ``_decode_loop`` program (static shapes: one compile, reused forever).
- Between segments, free slots admit queued requests: the prompt prefills
  as a batch-of-1 (its own small compiled program) and its cache rows /
  logits / repetition mask SPLICE into the shared state at the slot index.
- Rows that hit EOS or their token budget retire at the segment boundary:
  their text resolves the caller's Future and the slot frees. Inactive
  slots ride along masked as ``finished`` (the loop writes nothing for
  them) — the standard static-shape tax.

Worst-case admission latency is one segment (``chunk`` tokens ≈ tens of ms)
instead of a full answer (hundreds of tokens).

``kv_backend="paged"`` (or ``"paged_int8"``) runs the pool over the paged KV
cache (runtime/paged_kv.py) — the vLLM-style serving memory model on TPU:

- Pages are BATCH-AGNOSTIC, so admission is zero-copy for KV: the request
  prefills through a one-row VIEW of the shared pool (its slot's page-table
  row + the shared page arrays, donated in place); no multi-GB row splice.
- Retirement RECLAIMS pages: at the segment boundary (host re-entry) the
  slot's physical pages push back onto the free stack and its table row
  resets to trash — one preallocated pool serves an unbounded request
  stream.
- Admission control is reservation-based: a request is admitted only when
  its worst-case page count (ceil((prompt+budget)/page_size)) fits beside
  the reservations of every in-flight request, so mid-decode pool overflow
  cannot happen; ``total_pages`` below the slots×max_seq worst case trades
  HBM for queueing instead of crashing.
- The prompt template's prefix is SHARED across rows (vLLM/RadixAttention
  style, natural on a paged design): its KV prefills into pool pages once,
  each admitted row's table maps those pages read-only (the partial
  boundary page copies on write), and only the question suffix prefills
  (runtime/paged_generate.forward_prefill_paged_at). Matching is on token
  ids; sub-page matches fall back to the cold path.

Interface-compatible with DynamicBatcher (submit/answer/close/stats), so
``serve_rest`` takes either.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from functools import partial

import numpy as np

from edgemesh.models.transformer import KVCache, forward_decode, forward_prefill, init_kv_cache
from edgemesh.ops.sampling import TokenMaskState
from edgemesh.runtime.generate import _decode_loop
from edgemesh.runtime.paged_generate import (
    forward_decode_paged,
    forward_prefill_paged,
    forward_prefill_paged_at,
)
from edgemesh.runtime.paged_kv import init_paged_cache, init_quant_paged_cache

log = logging.getLogger("edgemesh.serve")

# Donated variants of the paged prefills: admission runs them on a one-row
# view of the SHARED page pool, so without donation every admission would
# copy the whole pool to apply a few page writes.
_prefill_paged_donated = partial(
    jax.jit, static_argnums=(0,), donate_argnums=(4,)
)(forward_prefill_paged.__wrapped__)
_prefill_paged_at_donated = partial(
    jax.jit, static_argnums=(0,), donate_argnums=(4,)
)(forward_prefill_paged_at.__wrapped__)

# Donated variant of the speculative round loop for the speculative engine:
# the _SpecState carry holds BOTH page pools — without donation every
# segment would copy them. Same static args as the original jit
# (runtime/speculative._spec_rounds); arg 10 is the state.
from edgemesh.runtime.speculative import _spec_rounds  # noqa: E402

_spec_rounds_donated = partial(
    jax.jit, static_argnums=(0, 1, 4, 5, 6, 7, 8, 9, 12, 13),
    donate_argnums=(10,),
)(_spec_rounds.__wrapped__)


def _splice_row_entries(cache, row, idx: int):
    """Graft a one-row prefill result's table/length entries back into the
    shared pool at slot ``idx`` — THE definition of the splice half of the
    donation contract (cold and warm admissions, both spec pools)."""
    return row._replace(
        page_table=cache.page_table.at[idx].set(row.page_table[0]),
        lengths=cache.lengths.at[idx].set(row.lengths[0]),
    )


def _prefill_into_row(cfg, params, tokens, lengths, cache, idx: int):
    """Cold zero-copy paged admission: prefill through a donated one-row
    VIEW of the shared pool (slot ``idx``'s page-table row + the shared
    pages) and splice the resulting table/length entries back. Used by the
    base engine's cold path and by BOTH of the speculative engine's pools —
    one definition of the donation/splice contract."""
    row_view = cache._replace(
        page_table=cache.page_table[idx : idx + 1],
        lengths=jnp.zeros((1,), jnp.int32),
    )
    logits1, row = _prefill_paged_donated(cfg, params, tokens, lengths, row_view)
    return logits1, _splice_row_entries(cache, row, idx)


@partial(jax.jit, donate_argnums=(0,))
def _copy_page(pages, src, dst):
    """In-place physical-page copy inside a [L, P, ...] pool array."""
    return pages.at[:, dst].set(pages[:, src])


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _splice_slot(
    pool_k, pool_v, pool_len, pool_logits, pool_mask, pool_finished,
    row_k, row_v, row_len, row_logits, row_mask, idx,
):
    """In-place (donated) insertion of one prefilled request into the shared
    pool state at slot ``idx`` — an eager .at[].set here would copy the whole
    multi-GB pool per admission."""
    return (
        pool_k.at[:, idx].set(row_k[:, 0]),
        pool_v.at[:, idx].set(row_v[:, 0]),
        pool_len.at[idx].set(row_len),
        pool_logits.at[idx].set(row_logits.astype(pool_logits.dtype)),
        pool_mask.at[idx].set(row_mask),
        pool_finished.at[idx].set(False),
    )


@dataclass
class _Slot:
    future: Future | None = None
    question: str = ""
    emitted: list[int] = field(default_factory=list)
    remaining: int = 0
    t_submit: float = 0.0
    t_start: float = 0.0
    pages_reserved: int = 0  # paged backends: worst-case pages held
    # Speculative engine: how many of the row's accumulated out-tokens have
    # already been emitted (the spec state's `out` grows in place; the
    # dense loop's per-segment buffers need no such cursor).
    taken: int = 0

    @property
    def active(self) -> bool:
        return self.future is not None


class ContinuousEngine:
    """Chunk-granular continuous batcher over one Agent's model."""

    def __init__(
        self,
        agent,
        slots: int = 8,
        chunk: int = 16,
        idle_wait_s: float = 0.005,
        kv_backend: str = "dense",
        page_size: int = 64,
        total_pages: int | None = None,
    ):
        self.agent = agent
        self.cfg = agent.cfg
        self.chunk = int(chunk)
        self.n_slots = int(slots)
        if self.chunk < 1 or self.n_slots < 1:
            raise ValueError("slots and chunk must be >= 1")
        if kv_backend not in ("dense", "paged", "paged_int8"):
            raise ValueError(f"unknown kv_backend {kv_backend!r}")
        if kv_backend != "dense" and int(page_size) < 1:
            raise ValueError("page_size must be >= 1")
        self.kv_backend = kv_backend
        self._queue: deque[tuple[str, Future, float]] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._slots = [_Slot() for _ in range(self.n_slots)]
        cap = self.cfg.max_seq_len
        if kv_backend == "dense":
            self._cache = init_kv_cache(self.cfg, self.n_slots, cap)
            self._decode_fn = None  # _decode_loop default (forward_decode)
        else:
            self.page_size = int(page_size)
            per_row = -(-cap // self.page_size)  # ceil: table slots per row
            # Default sizing covers every slot's worst-case RESERVATION (max
            # context + segment overshoot, _admit), not just its table
            # capacity — overshoot pops are transient but real until the
            # boundary rebuild reclaims them.
            per_row_worst = -(-(cap + self.chunk) // self.page_size) + 1
            self.total_pages = int(total_pages or 1 + self.n_slots * per_row_worst)
            init = init_quant_paged_cache if kv_backend == "paged_int8" else init_paged_cache
            self._init_pool = lambda: init(
                self.cfg, self.n_slots, total_pages=self.total_pages,
                page_size=self.page_size, max_pages=per_row,
            )
            self._cache = self._init_pool()
            self._decode_fn = forward_decode_paged
            self._reserved_pages = 0
            self._auto_sized = total_pages is None
            # Prefix sharing (lazy, _ensure_template): the prompt template's
            # KV prefilled ONCE into pool pages that every admitted row's
            # table maps read-only (vLLM-style prefix caching on the paged
            # design — sharing is just table entries).
            self._template_ids: np.ndarray | None = None
            self._template_pages: list[int] = []
            self._template_capacity_added = False
            self.shared_prefix_hits = 0
        # fp32, NOT activation dtype: sampling must see the same logits the
        # solo decode path sees, or bf16 rounding flips near-tied greedy
        # tokens versus agent.answer.
        self._logits = jnp.zeros((self.n_slots, self.cfg.vocab_size), jnp.float32)
        self._mask = TokenMaskState.init(self.n_slots, self.cfg.vocab_size).mask
        self._finished = jnp.ones((self.n_slots,), bool)  # all slots idle
        self._rng = jax.random.PRNGKey(agent.sampling.seed)
        # Stats for /metrics and tests.
        self.requests = 0
        self.segments = 0
        self.admitted_mid_flight = 0
        self.max_concurrent = 0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- public interface (DynamicBatcher-compatible) -----------------------

    def submit(self, question: str) -> Future:
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._queue.append((question, fut, time.perf_counter()))
            self.requests += 1
            self._cond.notify()
        return fut

    def answer(self, question: str) -> dict[str, Any]:
        return self.submit(question).result()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=10)

    def stats(self) -> dict[str, Any]:
        out = {
            "requests": self.requests,
            "segments": self.segments,
            "admitted_mid_flight": self.admitted_mid_flight,
            "max_concurrent": self.max_concurrent,
            "slots": self.n_slots,
            "chunk": self.chunk,
            "kv_backend": self.kv_backend,
        }
        if self.kv_backend != "dense":
            out["total_pages"] = self.total_pages
            out["reserved_pages"] = self._reserved_pages
            out["template_pages"] = len(self._template_pages)
            out["shared_prefix_hits"] = self.shared_prefix_hits
        return out

    # -- engine loop --------------------------------------------------------

    def _admit(self, idx: int, question: str, fut: Future, t_submit: float, mid_flight: bool) -> bool:
        """Prefill one request and splice its state into slot ``idx``.

        Returns False when a paged backend lacks free pages for the request's
        worst case (the caller re-queues it — capacity, not failure)."""
        agent = self.agent
        prompt = agent.format_prompt(question)
        tokens, lengths, _ = agent._prepare_batch([prompt])
        plen = int(lengths[0])
        budget = int(agent.sampling.max_new_tokens)
        budget = min(budget, int(self.cfg.max_seq_len) - plen)

        if self.kv_backend == "dense":
            cap = self._cache.k.shape[2]
            row_cache = init_kv_cache(self.cfg, 1, cap)
            logits1, row_cache = forward_prefill(self.cfg, agent.params, tokens, lengths, row_cache)
            valid = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
            mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(tokens, valid).mask

            k, v, ln, self._logits, self._mask, self._finished = _splice_slot(
                self._cache.k, self._cache.v, self._cache.lengths,
                self._logits, self._mask, self._finished,
                row_cache.k, row_cache.v, lengths[0], logits1[0], mask1[0],
                jnp.asarray(idx, jnp.int32),
            )
            self._cache = KVCache(k=k, v=v, lengths=ln)
            reserved = 0
        else:
            self._ensure_template()
            # Shared-prefix match: longest common token prefix with the
            # template pages, leaving at least one suffix token to prefill
            # (same matcher as the dense warm path, runtime/prefix_cache.py).
            from edgemesh.runtime.prefix_cache import common_token_prefix

            match = 0
            if self._template_ids is not None and self._template_ids.size:
                match = common_token_prefix(self._template_ids, tokens[0, :plen])
            shared_full = match // self.page_size  # read-only shared pages
            if shared_full == 0:
                match = 0  # below one page: sharing buys nothing, go cold

            # Worst-case PRIVATE pages this row can ever hold (shared pages
            # are permanent pool residents, not per-request consumption): the
            # loop advances EVERY row to the segment boundary, so a row that
            # EOSes or exhausts its budget mid-segment overshoots by < chunk
            # tokens, + 1 bridge token (the overshoot tokens are garbage,
            # trimmed host-side, but their page allocations are real until
            # retirement reclaims them).
            need = -(-(plen + budget + self.chunk) // self.page_size) + 1 - shared_full
            idle_after = sum(1 for s in self._slots if not s.active) - 1
            headroom = idle_after * self._segment_pages
            avail = self.total_pages - 1 - len(self._template_pages)
            if need + (self.n_slots - 1) * self._segment_pages > avail:
                raise ValueError(
                    f"request needs {need} pages (prompt {plen} + budget "
                    f"{budget} + segment overshoot); the pool holds "
                    f"{avail} minus idle-slot headroom"
                )
            if self._reserved_pages + need + headroom > avail:
                return False  # capacity — re-queue, admit at a later boundary
            # Zero-copy KV admission: prefill through a one-row VIEW of the
            # shared pool (slot's table row + shared pages, donated). Only
            # the slot's own page-table/length entries change host-side; no
            # KV row splice exists in the paged world. With a template match,
            # the row warm-starts: its table maps the shared pages read-only
            # (boundary page copy-on-write) and only the suffix prefills.
            try:
                if match:
                    row_table = np.zeros((self._cache.max_pages,), np.int32)
                    row_table[:shared_full] = self._template_pages[:shared_full]
                    if match % self.page_size:
                        fresh = self._pop_page()
                        self._cow_copy(self._template_pages[shared_full], fresh)
                        row_table[shared_full] = fresh
                    row_view = self._cache._replace(
                        page_table=jnp.asarray(row_table)[None, :],
                        lengths=jnp.zeros((1,), jnp.int32),
                    )
                    suffix = tokens[:, match:]
                    logits1, row = _prefill_paged_at_donated(
                        self.cfg, agent.params, suffix,
                        jnp.asarray([plen - match], jnp.int32), row_view,
                        jnp.asarray([match], jnp.int32),
                    )
                    self.shared_prefix_hits += 1
                    cache = _splice_row_entries(self._cache, row, idx)
                else:
                    logits1, cache = _prefill_into_row(
                        self.cfg, agent.params, tokens, lengths, self._cache, idx
                    )
            except Exception:
                # The donated pool buffers may already be invalidated — a
                # fail-only-this-request recovery is impossible. Rebuild the
                # pool and fail the in-flight rows (their KV lived in it),
                # then re-raise so the caller fails THIS request too.
                self._reset_pool(
                    RuntimeError("page pool reset after a failed admission prefill")
                )
                raise
            self._cache = cache
            valid = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
            mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(tokens, valid).mask
            self._logits = self._logits.at[idx].set(logits1[0].astype(self._logits.dtype))
            self._mask = self._mask.at[idx].set(mask1[0])
            self._finished = self._finished.at[idx].set(False)
            self._reserved_pages += need
            reserved = need

        self._slots[idx] = _Slot(
            future=fut, question=question, emitted=[], remaining=budget,
            t_submit=t_submit, t_start=time.perf_counter(),
            pages_reserved=reserved,
        )
        if mid_flight:
            self.admitted_mid_flight += 1
        return True

    def _ensure_template(self) -> None:
        """Lazily prefill the prompt template's shared prefix into pool pages
        (once per pool lifetime). Sharing is pure table bookkeeping: admitted
        rows map these pages read-only; the boundary page copies on write."""
        if self._template_ids is not None:
            return
        self._template_ids = np.zeros((0,), np.int32)  # default: no sharing
        if not getattr(self.agent, "prefix_cache", True):
            return
        tpl = self.agent.prompt_template
        if "{question}" not in tpl:
            return
        ids = np.asarray(
            self.agent.tokenizer.encode(tpl.split("{question}")[0]), np.int32
        )
        if ids.size < self.page_size or ids.size > self.cfg.max_seq_len - 8:
            return
        n_pages = -(-int(ids.size) // self.page_size)
        if self._auto_sized and not self._template_capacity_added:
            # Grow the (still-empty) pool so the permanent template pages
            # don't eat the per-slot reservation margin the default sizing
            # guarantees. Runs before any admission; one-time.
            self.total_pages += n_pages
            self._template_capacity_added = True
            self._cache = self._init_pool()
        # A user-sized pool must still be able to SERVE after the template
        # moves in permanently — including a max-context COLD request (no
        # template match gets no page discount), the same hard bound the
        # admission path enforces. Otherwise sharing is a net loss (or,
        # worse, allocate() would overflow onto the trash page and every
        # warm row would read garbage). Skip sharing, don't fail: it is an
        # optimization.
        per_row_worst = -(-(self.cfg.max_seq_len + self.chunk) // self.page_size) + 1
        post_avail = self.total_pages - 1 - n_pages
        if per_row_worst + (self.n_slots - 1) * self._segment_pages > post_avail:
            log.warning(
                "prefix sharing disabled: installing the %d-page template "
                "would leave %d pages, below the max-request bound %d",
                n_pages, post_avail,
                per_row_worst + (self.n_slots - 1) * self._segment_pages,
            )
            return
        row_view = self._cache._replace(
            page_table=jnp.zeros((1, self._cache.max_pages), jnp.int32),
            lengths=jnp.zeros((1,), jnp.int32),
        )
        try:
            _, row = _prefill_paged_donated(
                self.cfg, self.agent.params, jnp.asarray(ids)[None, :],
                jnp.asarray([int(ids.size)], jnp.int32), row_view,
            )
        except Exception:
            # Donated pool buffers may be invalidated — same recovery as a
            # failed admission prefill (template retried after the reset).
            self._reset_pool(
                RuntimeError("page pool reset after a failed template prefill")
            )
            raise
        from edgemesh.runtime.paged_kv import pool_overflowed

        if pool_overflowed(row):  # pragma: no cover — pre-checked above
            raise RuntimeError("template prefill overflowed the page pool")
        self._cache = row._replace(
            page_table=self._cache.page_table, lengths=self._cache.lengths
        )
        self._template_pages = [int(p) for p in np.asarray(row.page_table[0])[:n_pages]]
        self._template_ids = ids

    def _pop_page(self) -> int:
        """Host-side single-page pop (copy-on-write boundary allocation)."""
        top = int(self._cache.free_top)
        if top >= self.total_pages:
            raise RuntimeError("page pool exhausted during COW admission")
        page = int(self._cache.free_stack[top])
        self._cache = self._cache._replace(free_top=jnp.asarray(top + 1, jnp.int32))
        return page

    def _cow_copy(self, src: int, dst: int) -> None:
        """Copy physical page src → dst across all layers (donated, in
        place): the suffix will overwrite dst's tail slots, so the shared
        original stays pristine for other rows."""
        c = self._cache
        upd = dict(
            k=_copy_page(c.k, src, dst), v=_copy_page(c.v, src, dst)
        )
        if hasattr(c, "k_scale"):
            upd["k_scale"] = _copy_page(c.k_scale, src, dst)
            upd["v_scale"] = _copy_page(c.v_scale, src, dst)
        self._cache = c._replace(**upd)

    @property
    def _segment_pages(self) -> int:
        """Worst-case pages ONE IDLE slot can allocate across a segment +
        bridge: idle rows always restart from length 0 (reset at retire /
        sweep), so chunk + 1 garbage tokens need exactly this many pages."""
        return -(-(self.chunk + 1) // self.page_size)

    def _reclaim_pages(self, idx: int, pages_reserved: int = 0) -> None:
        """Reset slot ``idx``'s table row and release its reservation. The
        free stack itself is REBUILT from the table at the segment boundary
        (_rebuild_free_stack) — the stack is derivable state, and rebuilding
        also recovers pages the masked loop popped but whose table writes
        clamped/dropped at capacity (they are referenced by no row)."""
        self._cache = self._cache._replace(
            page_table=self._cache.page_table.at[idx].set(0),
            lengths=self._cache.lengths.at[idx].set(0),
        )
        self._reserved_pages -= pages_reserved

    def _rebuild_free_stack(self) -> None:
        """Host half of the allocator contract (runtime/paged_kv.PagedKVCache
        docstring: 'the host rebuilds the stack between serving batches'):
        free = every physical page no table row references. Runs at every
        segment boundary — O(total_pages) numpy work."""
        self._cache = _with_rebuilt_stack(
            self._cache, self.total_pages, self._template_pages
        )

    def _reset_pool(self, exc: Exception) -> None:
        """Fail every in-flight request and rebuild the KV state from scratch
        — fresh zeroed arrays for EVERY donated buffer (cache + repetition
        mask), safe even when the old ones were invalidated by a failed
        donated prefill or segment. One recovery path for both backends."""
        for i, s in enumerate(self._slots):
            if s.active:
                if not s.future.done():
                    s.future.set_exception(exc)
                self._slots[i] = _Slot()
        self._finished = jnp.ones((self.n_slots,), bool)
        if self.kv_backend == "dense":
            self._cache = init_kv_cache(self.cfg, self.n_slots, self.cfg.max_seq_len)
        else:
            self._cache = self._init_pool()
            self._reserved_pages = 0
            # Template pages died with the pool; rebuild lazily on the next
            # admission (the capacity bump is one-time and survives).
            self._template_ids = None
            self._template_pages = []
        self._mask = TokenMaskState.init(self.n_slots, self.cfg.vocab_size).mask

    def _maybe_sweep(self, active: list[int], retired: bool) -> None:
        """Run the page sweep only when page garbage can exist: an idle row
        rode this segment (its masked advance allocates up to
        ``_segment_pages``, which admission holds as headroom) or a
        retirement just freed pages the stack doesn't know about. The
        steady-state full-pool segment (all slots active, none finished)
        creates neither, and the sweep's bulk table fetch + stack rebuild
        are pure host-round-trip cost on the tunneled platform. ONE
        definition of the invariant — the speculative engine calls this
        too (its sweep covers both pools)."""
        if self.kv_backend != "dense" and (retired or len(active) < self.n_slots):
            self._sweep_idle_pages()

    def _sweep_idle_pages(self) -> None:
        """Idle slots ride the static-shape decode loop masked, but their
        garbage lengths still cross page boundaries and ALLOCATE — reset
        their table rows (their count is bounded by ``_segment_pages`` per
        idle slot, which admission holds as headroom), then rebuild the
        free stack from the table. Runs at every segment boundary where an
        idle row rode the segment or a retirement occurred (_maybe_sweep);
        full-pool no-retirement segments skip it."""
        table = np.asarray(self._cache.page_table)
        for i, s in enumerate(self._slots):
            if not s.active and (table[i] > 0).any():
                self._reclaim_pages(i)
        self._rebuild_free_stack()

    def _retire(self, idx: int):
        slot = self._slots[idx]
        tokenizer = self.agent.tokenizer
        # slot.emitted is already a host-side list of ints — hand it to the
        # tokenizer as-is. Round-tripping it through a device array made
        # decode's per-element int() a device readback EACH (~0.13s over the
        # tunnel): ~4s per retired request, 33s of a 36s serving wave.
        text = tokenizer.decode(slot.emitted) if slot.emitted else ""
        now = time.perf_counter()
        wall = max(now - slot.t_start, 1e-9)
        slot.future.set_result(
            {
                "answer": text.strip(),
                "role": self.agent.role,
                "tps": len(slot.emitted) / wall,
                "generated": len(slot.emitted),
                "queue_s": slot.t_start - slot.t_submit,
                "t_start": slot.t_start,
                "t_end": now,
            }
        )
        if self.kv_backend != "dense":
            self._reclaim_pages(idx, slot.pages_reserved)
        self._slots[idx] = _Slot()
        self._finished = self._finished.at[idx].set(True)

    def _run_segment(self, active: list[int], eos_id: int) -> None:
        """One pool-wide decode segment + emit/retire bookkeeping. Segment
        length is ALWAYS ``chunk`` so _decode_loop compiles exactly once; a
        row whose budget ends mid-segment overshoots by < chunk forwards
        and the extras are trimmed host-side. Overridden by the speculative
        engine with draft→verify rounds."""
        agent = self.agent
        self._rng, seg_rng = jax.random.split(self._rng)
        out, counts, self._cache, _, self._mask, prev, fin = _decode_loop(
            self.cfg, agent.params, agent.sampling, self.chunk, eos_id,
            self._logits, self._cache, self._mask, seg_rng,
            self._decode_fn, self._finished,
        )
        self.segments += 1
        # Single pytree fetch: one blocking round trip per segment
        # instead of three (each ~0.13s on the tunneled platform).
        counts_h, out_h, fin_h = jax.device_get((counts, out, fin))
        self._finished = fin
        retired = False
        for i in active:
            slot = self._slots[i]
            n = min(int(counts_h[i]), max(slot.remaining, 0))
            toks = [int(t) for t in out_h[i][:n]]
            if toks and toks[-1] == eos_id:
                toks = toks[:-1]
            slot.emitted.extend(toks)
            slot.remaining -= n
            if bool(fin_h[i]) or slot.remaining <= 0:
                self._retire(i)
                retired = True

        # Bridge into the next segment for rows still going (the loop
        # stops before a wasted trailing forward; run it for the batch).
        # This whole-batch step also advances lengths / writes one KV
        # row for retired and idle slots — garbage BY DESIGN: idle-slot
        # state is meaningless until _splice_slot resets lengths on
        # admission, and writes clamp at capacity. Do not read idle
        # rows' lengths as if they tracked anything.
        if any(s.active for s in self._slots):
            decode_fn = self._decode_fn or forward_decode
            logits, self._cache = decode_fn(self.cfg, agent.params, prev, self._cache)
            self._logits = logits.astype(self._logits.dtype)
        self._maybe_sweep(active, retired)

    def _run(self) -> None:
        agent = self.agent
        eos_id = int(getattr(agent.tokenizer, "eos_id", -1))
        any_active_before = False
        while True:
            # Admit as many queued requests as there are free slots.
            with self._cond:
                while not self._queue and not any(s.active for s in self._slots):
                    if self._closed:
                        return
                    self._cond.wait()
                pending: list[tuple[str, Future, float]] = []
                free = [i for i, s in enumerate(self._slots) if not s.active]
                while self._queue and free and len(pending) < len(free):
                    pending.append(self._queue.popleft())
            free_now = [i for i, s in enumerate(self._slots) if not s.active]
            for pos, ((q, fut, ts), idx) in enumerate(zip(pending, free_now)):
                try:
                    ok = self._admit(idx, q, fut, ts, mid_flight=any_active_before)
                except Exception as exc:
                    # Fail only THIS request: already-admitted slots keep
                    # their pending futures (poisoning them would make the
                    # later _retire set_result raise InvalidStateError and
                    # kill the worker).
                    log.exception("admission failed for %r", q[:80])
                    if not fut.done():
                        fut.set_exception(exc)
                    continue
                if not ok:
                    # Page-pool capacity: re-queue this and the rest of the
                    # batch (order preserved); they admit at a later segment
                    # boundary once retirements reclaim pages. Reservations
                    # imply active rows exist, so the loop cannot spin.
                    with self._cond:
                        for item in reversed(pending[pos:]):
                            self._queue.appendleft(item)
                    break

            active = [i for i, s in enumerate(self._slots) if s.active]
            self.max_concurrent = max(self.max_concurrent, len(active))
            any_active_before = bool(active)
            if not active:
                continue

            # One decode segment over the whole pool; idle rows are finished.
            # A failure anywhere in the segment must not kill the worker —
            # fail the in-flight futures, reset the pool, keep serving.
            try:
                self._run_segment(active, eos_id)
            except Exception as exc:
                log.exception("decode segment failed; failing %d in-flight requests", len(active))
                self._reset_pool(exc)

            # Give stragglers a brief window to queue before the next segment
            # (they join at the boundary either way; this just batches admits).
            with self._cond:
                if not self._queue and any(s.active for s in self._slots):
                    self._cond.wait(timeout=0.001)


def _with_rebuilt_stack(cache, total_pages: int, permanent, table=None) -> "PagedKVCache":
    """free = every physical page referenced by no table row (and not
    permanent, e.g. template pages). Shared by the target and draft pools.
    ``table`` lets a caller that already fetched (and host-side mutated)
    the page table skip a second blocking device readback."""
    if table is None:
        table = np.asarray(cache.page_table)
    used = np.unique(np.concatenate([
        table[table > 0].astype(np.int32),
        np.asarray(list(permanent), np.int32),
    ]))
    free = np.setdiff1d(np.arange(1, total_pages, dtype=np.int32), used)
    stack = np.zeros((total_pages,), np.int32)
    top = total_pages - free.size
    stack[top:] = free
    return cache._replace(
        free_stack=jnp.asarray(stack),
        free_top=jnp.asarray(top, jnp.int32),
    )


class SpeculativeContinuousEngine(ContinuousEngine):
    """Continuous batching WITH speculative decoding over the paged pool.

    Each segment runs up to ``chunk // (gamma+1)`` pool-wide draft→verify
    rounds in ONE jitted program (``runtime.speculative._spec_rounds`` — the
    same body the standalone and streaming speculative paths use), so every
    request in flight gets draft acceleration while requests still join and
    leave at segment boundaries. Both models' KV live as page pools; the
    verify rewind is a lengths rollback, safe on pages because the allocator
    reuses table entries on re-advance (rewind-idempotent).

    Contracts beyond the base engine:
    - paged backend only, and the agent must carry a draft
      (``AgentSpec.draft``) sharing the target's tokenizer/vocab.
    - uniform budget: every request decodes up to
      ``sampling.max_new_tokens``; a prompt too long for
      prompt + budget + gamma + 1 tokens in one table row is refused at
      admission (the dense engine clamps instead — spec rounds share one
      static max_new).
    - admissions are always cold (no template prefix sharing: the draft
      pool holds no template KV, and a warm target + cold draft would
      desynchronize the verify positions).
    - emitted text is the target distribution exactly — greedy spec serving
      is token-identical to the plain engine (pinned in tests).
    """

    def __init__(
        self,
        agent,
        slots: int = 8,
        chunk: int = 16,
        idle_wait_s: float = 0.005,
        kv_backend: str = "paged",
        page_size: int = 64,
        total_pages: int | None = None,
        draft_total_pages: int | None = None,
    ):
        if getattr(agent, "draft_cfg", None) is None:
            raise ValueError(
                "SpeculativeContinuousEngine needs an agent with a draft "
                "model (AgentSpec.draft)"
            )
        if kv_backend != "paged":
            raise ValueError(
                f"speculative continuous batching runs on kv_backend='paged' "
                f"(got {kv_backend!r})"
            )
        sp = agent.sampling
        if sp.do_sample and not 0 < sp.top_k < agent.cfg.vocab_size:
            # The standalone spec path validates this up front
            # (runtime/speculative._spec_prefill); without the check here,
            # the FIRST segment would hit filtered_candidates' error inside
            # the worker, reset the pool, and fail every admitted request —
            # forever, batch after batch.
            raise ValueError(
                "speculative sampling needs bounded support: set top_k in "
                f"[1, vocab) (got {sp.top_k})"
            )
        if int(agent.spec_gamma) < 1:
            raise ValueError(f"spec_gamma must be >= 1, got {agent.spec_gamma}")
        super().__init__(
            agent, slots=slots, chunk=chunk, idle_wait_s=idle_wait_s,
            kv_backend=kv_backend, page_size=page_size, total_pages=total_pages,
        )
        from edgemesh.runtime.speculative import _spec_fns

        self.gamma = int(agent.spec_gamma)
        self.max_new = int(agent.sampling.max_new_tokens)
        self.cap = self.max_new + self.gamma + 1
        self.rounds_per_segment = max(1, self.chunk // (self.gamma + 1))
        self._verify_fn, self._spec_decode_fn = _spec_fns("paged")
        per_row = self._cache.page_table.shape[1]
        self._d_total = int(draft_total_pages or self.total_pages)
        d_cfg = agent.draft_cfg
        self._init_dpool = lambda: init_paged_cache(
            d_cfg, self.n_slots, total_pages=self._d_total,
            page_size=self.page_size, max_pages=per_row,
        )
        self._dcache = self._init_dpool()
        self._dreserved = 0
        self._spec_reset_arrays()

    def _spec_reset_arrays(self) -> None:
        b = self.n_slots
        self._pending = jnp.zeros((b,), jnp.int32)
        self._out = jnp.zeros((b, self.cap), jnp.int32)
        self._nemit = jnp.zeros((b,), jnp.int32)
        self._conf = jnp.zeros((b,), jnp.float32)
        self._acc = jnp.zeros((), jnp.int32)
        self._prop = jnp.zeros((), jnp.int32)
        self._rnds = jnp.zeros((), jnp.int32)
        # Host mirror of (accepted, proposed, rounds), refreshed by the
        # worker inside each segment's bulk fetch. stats() reads ONLY this:
        # the device counters are donated every segment, so touching them
        # from another thread (REST /metrics) races use-after-donate.
        self._spec_counters_host = (0, 0, 0)

    # Spec admissions are always cold — see the class docstring.
    def _ensure_template(self) -> None:
        return

    @property
    def _segment_pages(self) -> int:
        """Idle rows never ADVANCE lengths in spec rounds (the body masks
        inactive rows' commits), but the draft step writes one position and
        the verify chunk writes gamma+1 at the row's frozen position —
        rewind-idempotent table entries, so the bound is one chunk's pages
        + a boundary page, reclaimed by the sweep at every boundary where
        idle rows exist (_maybe_sweep)."""
        return -(-(self.gamma + 2) // self.page_size) + 1

    def _admit(self, idx: int, question: str, fut: Future, t_submit: float,
               mid_flight: bool) -> bool:
        agent = self.agent
        eos_id = int(getattr(agent.tokenizer, "eos_id", -1))
        prompt = agent.format_prompt(question)
        tokens, lengths, _ = agent._prepare_batch([prompt])
        plen = int(lengths[0])
        row_cap = self._cache.page_table.shape[1] * self.page_size
        if plen + self.max_new + self.gamma + 1 > row_cap:
            raise ValueError(
                f"prompt ({plen} tokens) + budget ({self.max_new}) + "
                f"gamma+1 ({self.gamma + 1}) exceeds the row capacity "
                f"({row_cap}); the speculative engine keeps one uniform "
                "budget per pool"
            )
        # Worst-case pages per pool: the verify chunk transiently writes
        # gamma+1 tokens past the committed length before the rewind.
        need = -(-(plen + self.max_new + self.gamma + 1) // self.page_size) + 1
        idle_after = sum(1 for s in self._slots if not s.active) - 1
        headroom = idle_after * self._segment_pages
        slack = (self.n_slots - 1) * self._segment_pages
        avail_t = self.total_pages - 1
        avail_d = self._d_total - 1
        if need + slack > min(avail_t, avail_d):
            raise ValueError(
                f"request needs {need} pages (prompt {plen} + budget "
                f"{self.max_new} + gamma overshoot); the pools hold "
                f"{min(avail_t, avail_d)} minus idle-slot headroom"
            )
        if (self._reserved_pages + need + headroom > avail_t
                or self._dreserved + need + headroom > avail_d):
            return False  # capacity — re-queue, admit at a later boundary

        try:
            logits1, self._cache = _prefill_into_row(
                self.cfg, agent.params, tokens, lengths, self._cache, idx
            )
            _, self._dcache = _prefill_into_row(
                agent.draft_cfg, agent.draft_params, tokens, lengths,
                self._dcache, idx,
            )
        except Exception:
            self._reset_pool(
                RuntimeError("page pools reset after a failed speculative admission")
            )
            raise

        # First-token bootstrap: run the SAME _spec_init the standalone path
        # uses (batch-of-1, caches pass through untouched as None) so the
        # "emits the target distribution exactly" guarantee cannot drift
        # between serving and standalone speculative decoding.
        from edgemesh.runtime.speculative import _spec_init

        valid = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
        mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(tokens, valid).mask
        self._rng, r0 = jax.random.split(self._rng)
        row = _spec_init(
            self.cfg, agent.draft_cfg, agent.params, agent.draft_params,
            agent.sampling, self.gamma, self.max_new, eos_id,
            logits1, None, None, mask1, r0,
        )
        self._pending = self._pending.at[idx].set(row.pending[0])
        self._out = self._out.at[idx].set(row.out[0])
        self._nemit = self._nemit.at[idx].set(1)
        self._conf = self._conf.at[idx].set(row.conf_sum[0])
        self._mask = self._mask.at[idx].set(row.mask[0])
        self._finished = self._finished.at[idx].set(row.finished[0])
        self._reserved_pages += need
        self._dreserved += need
        self._slots[idx] = _Slot(
            future=fut, question=question, emitted=[], remaining=self.max_new,
            t_submit=t_submit, t_start=time.perf_counter(),
            pages_reserved=need,
        )
        if mid_flight:
            self.admitted_mid_flight += 1
        return True

    def _run_segment(self, active: list[int], eos_id: int) -> None:
        from edgemesh.runtime.speculative import _SpecState

        agent = self.agent
        self._rng, seg_rng = jax.random.split(self._rng)
        state = _SpecState(
            pending=self._pending, t_cache=self._cache, d_cache=self._dcache,
            out=self._out, n_emit=self._nemit, finished=self._finished,
            mask=self._mask, rng=seg_rng, conf_sum=self._conf,
            accepted=self._acc, proposed=self._prop, rounds=self._rnds,
        )
        state = _spec_rounds_donated(
            self.cfg, agent.draft_cfg, agent.params, agent.draft_params,
            agent.sampling, self.gamma, self.max_new, eos_id,
            self.cfg.vocab_size, self.cap, state,
            jnp.asarray(self.rounds_per_segment, jnp.int32),
            self._verify_fn, self._spec_decode_fn,
        )
        (self._pending, self._cache, self._dcache, self._out, self._nemit,
         self._finished, self._mask, _, self._conf, self._acc, self._prop,
         self._rnds) = state
        self.segments += 1
        nemit_h, out_h, fin_h, acc_h, prop_h, rnds_h = jax.device_get(
            (state.n_emit, state.out, state.finished,
             state.accepted, state.proposed, state.rounds)
        )
        self._spec_counters_host = (int(acc_h), int(prop_h), int(rnds_h))
        retired = False
        for i in active:
            slot = self._slots[i]
            total = min(int(nemit_h[i]), self.max_new)
            toks = [int(t) for t in out_h[i][slot.taken : total]]
            if toks and toks[-1] == eos_id:
                toks = toks[:-1]
            slot.emitted.extend(toks)
            slot.taken = total
            slot.remaining = self.max_new - total
            if bool(fin_h[i]) or total >= self.max_new:
                self._retire(i)
                retired = True
        self._maybe_sweep(active, retired)

    def _retire(self, idx: int) -> None:
        reserved = self._slots[idx].pages_reserved  # same need in both pools
        super()._retire(idx)
        self._dreserved -= reserved
        self._dcache = self._dcache._replace(
            page_table=self._dcache.page_table.at[idx].set(0),
            lengths=self._dcache.lengths.at[idx].set(0),
        )

    def _sweep_idle_pages(self) -> None:
        # ONE bulk fetch for both tables; the reclaim loop mirrors its
        # zeroing onto the host copies so the rebuilds can reuse them
        # instead of re-reading the device (each readback ~0.13s tunneled).
        table, dtable = jax.device_get(
            (self._cache.page_table, self._dcache.page_table)
        )
        # device_get hands back read-only views; the loop mutates them.
        table, dtable = np.array(table), np.array(dtable)
        for i, s in enumerate(self._slots):
            if not s.active:
                if (table[i] > 0).any():
                    self._reclaim_pages(i)
                    table[i] = 0
                if (dtable[i] > 0).any():
                    self._dcache = self._dcache._replace(
                        page_table=self._dcache.page_table.at[i].set(0),
                        lengths=self._dcache.lengths.at[i].set(0),
                    )
                    dtable[i] = 0
        self._cache = _with_rebuilt_stack(
            self._cache, self.total_pages, self._template_pages, table=table
        )
        self._dcache = _with_rebuilt_stack(
            self._dcache, self._d_total, (), table=dtable
        )

    def _reset_pool(self, exc: Exception) -> None:
        super()._reset_pool(exc)
        # Every donated spec buffer may be invalid; rebuild them all (the
        # cumulative accept/propose counters reset with the pool).
        self._dcache = self._init_dpool()
        self._dreserved = 0
        self._spec_reset_arrays()

    def stats(self) -> dict:
        out = super().stats()
        acc, prop, rnds = self._spec_counters_host
        out["gamma"] = self.gamma
        out["rounds_per_segment"] = self.rounds_per_segment
        out["spec_proposed"] = prop
        out["spec_accepted"] = acc
        out["spec_rounds"] = rnds
        out["draft_total_pages"] = self._d_total
        return out


def make_engine(agent, **kwargs):
    """Engine factory: a draft-carrying agent on the paged backend gets the
    speculative engine; everything else gets the plain one. (An explicit
    class choice always works too — this is the convenience entry the REST
    server uses.)"""
    if (
        getattr(agent, "draft_cfg", None) is not None
        and kwargs.get("kv_backend", "dense") == "paged"
    ):
        return SpeculativeContinuousEngine(agent, **kwargs)
    return ContinuousEngine(agent, **kwargs)
