"""Continuous batching: requests join and leave the decode loop mid-flight.

The DynamicBatcher (serve/batcher.py) forms a batch, runs it to COMPLETION,
then forms the next — a request arriving one token after dispatch waits out
the whole previous batch. Real serving engines instead keep one resident
decode loop whose batch composition changes as requests arrive/finish
(vLLM-style continuous batching). A statically-shaped jitted TPU loop cannot
admit rows mid-program, but the segmented decode (runtime/stream.py) already
re-enters the host every ``chunk`` tokens — so edgemesh does continuous
batching at CHUNK granularity:

- A fixed pool of ``slots`` rows shares one KV cache and one compiled
  ``_decode_loop`` program (static shapes: one compile, reused forever).
- Between segments, free slots admit queued requests: the prompt prefills
  as a batch-of-1 (its own small compiled program) and its cache rows /
  logits / repetition mask SPLICE into the shared state at the slot index.
- Rows that hit EOS or their token budget retire at a segment boundary:
  their text resolves the caller's Future and the slot frees. Inactive
  slots ride along masked as ``finished`` (the loop writes nothing for
  them) — the standard static-shape tax.

**Pipelined segments (round 4).** The worker runs DEPTH-2: it dispatches
segment N+1 from segment N's device output handles BEFORE draining segment
N's results, so the device never idles on the host's ~0.1 s tunneled
readback + bookkeeping. The only blocking fetch per segment lands while the
NEXT segment is already executing. Consequences the code must own:

- A row whose budget ran out in segment N still rides segment N+1 (its
  retirement is only discovered while N+1 executes). EOS rows self-mask on
  device; budget overshoot is trimmed host-side as always — the page
  reservation just covers one extra segment of garbage.
- Slot bookkeeping is guarded by per-slot admission GENERATIONS: segment
  N's fetched counts must not credit tokens to a request admitted into the
  same slot afterwards.

**Host-owned paging (round 4).** ``kv_backend="paged"``/``"paged_int8"``
runs the pool over the paged KV cache (runtime/paged_kv.py) with the free
list owned ENTIRELY by the host:

- Admission pre-maps the request's worst-case pages into its table row
  from a host-side free list (the device allocator sees every slot mapped
  and never pops — ``free_top`` stays at 1 as a tripwire, checked from the
  segment fetch). Admission is still zero-copy for KV: the prompt prefills
  through a one-row VIEW of the shared pool (donated in place).
- Retirement pushes the row's pages straight back onto the host free list
  and parks the slot: table row zeroed, length set to 1. Parked rows are
  ``finished`` so the decode loop FREEZES their length (runtime/generate
  ``_decode_loop``) — they never cross a page boundary, never allocate,
  and their masked garbage write lands on the trash page. This deletes
  the round-3 machinery wholesale: no idle-slot page sweeps, no free-stack
  rebuilds from the table, no per-segment reservation headroom.
- The prompt template's prefix is SHARED across rows (vLLM/RadixAttention
  style): its KV prefills into permanent pool pages once, each admitted
  row's table maps those pages read-only (the partial boundary page copies
  on write), and only the question suffix prefills.

Interface-compatible with DynamicBatcher (submit/answer/close/stats), so
``serve_rest`` takes either.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from functools import partial

import numpy as np

from edgemesh.models.transformer import KVCache, forward_decode, forward_prefill, init_kv_cache
from edgemesh.obs import RequestTrace, SpanTracker
from edgemesh.obs.compute import ComputeLedger, SpecRoundLedger, spec_draft_frac
from edgemesh.obs.memory import SYSTEM_TENANT, TEMPLATE_RID, PoolLedger
from edgemesh.obs.quality import QualityTracker
from edgemesh.obs.trace import (
    TraceContext,
    install_compile_hook,
    uninstall_compile_hook,
    use_trace,
)
from edgemesh.ops.sampling import TokenMaskState
from edgemesh.runtime.generate import _decode_loop
from edgemesh.runtime.paged_generate import (
    forward_decode_paged,
    forward_prefill_paged,
    forward_prefill_paged_at,
    forward_ragged_paged,
)
from edgemesh.runtime.paged_kv import (
    KVWireError,
    check_wire_compat,
    decode_wire,
    export_pages,
    init_paged_cache,
    init_quant_paged_cache,
    page_nbytes,
    splice_imported,
)
from edgemesh.utils.bucketing import POW2_FLOOR, bucket_pow2

log = logging.getLogger("edgemesh.serve")


def estimate_capacity(slots: int, ewma_decode_s=None, ewma_service_s=None,
                      ewma_decode_tokens=None,
                      measured_tok_s=None) -> dict[str, Any]:
    """Sustainable-throughput estimate from the digest's service EWMAs —
    the MEASURED capacity model (docs/OBSERVABILITY.md "The capacity
    model"). Derivation: with every slot busy, each slot yields one token
    per ``ewma_decode_s``, so sustainable decode throughput is
    ``slots / ewma_decode_s``; dividing by the mean tokens a request
    generates (``ewma_decode_tokens``) gives sustainable requests/s, with
    ``slots / ewma_service_s`` as the fallback when the token split has
    not been observed yet. All ``None`` until the EWMAs exist — a cold
    replica honestly reports no capacity claim rather than a guess.

    ``measured_tok_s`` is the compute ledger's fenced-launch throughput
    (obs/compute.py): when present it REPLACES the host-EWMA-derived
    tok/s as ``est_tok_s`` — the host decode EWMA conflates device time
    with worker bookkeeping and pipeline lag, while the ledger's number
    is a true device-completion fence over the same launches. The raw
    value also ships as its own key so consumers can tell which model
    produced the estimate."""
    tok_s = None
    if measured_tok_s:
        tok_s = round(measured_tok_s, 3)
    elif ewma_decode_s:
        tok_s = round(slots / ewma_decode_s, 3)
    req_s = None
    if tok_s is not None and ewma_decode_tokens:
        req_s = round(tok_s / ewma_decode_tokens, 3)
    elif ewma_service_s:
        req_s = round(slots / ewma_service_s, 3)
    return {"slots": slots, "est_tok_s": tok_s, "est_req_s": req_s,
            "measured_tok_s": (
                None if not measured_tok_s else round(measured_tok_s, 3))}


def pool_state(total: int, free: int, reserved: int, template: int,
               page_size: int, per_row_worst: int,
               pending_tokens: int = 0) -> dict[str, Any]:
    """The paged pool's occupancy block for the load digest.

    ``occupancy_ratio`` is the non-free share of the pool;
    ``free_page_headroom`` counts how many more WORST-CASE admissions
    still fit (the number the admission path actually gates on);
    ``fragmentation_ratio`` is the worst-case allocator's internal
    fragmentation — the share of reserved page capacity held for tokens
    that have not been generated yet (``pending_tokens`` = the active
    rows' remaining budgets). High right after long-budget admissions,
    decaying toward 0 as decode fills the reserved pages."""
    reserved_capacity = reserved * page_size
    frag = 0.0
    if reserved_capacity > 0:
        frag = round(min(1.0, max(0, pending_tokens) / reserved_capacity), 4)
    return {
        "pages_total": total,
        "pages_free": free,
        "pages_reserved": reserved,
        "pages_template": template,
        "occupancy_ratio": round((total - free) / total, 4) if total else 0.0,
        "fragmentation_ratio": frag,
        "free_page_headroom": free // max(1, per_row_worst),
    }


# Donated variants of the paged prefills: admission runs them on a one-row
# view of the SHARED page pool, so without donation every admission would
# copy the whole pool to apply a few page writes.
_prefill_paged_donated = partial(
    jax.jit, static_argnums=(0,), donate_argnums=(4,)
)(forward_prefill_paged.__wrapped__)
_prefill_paged_at_donated = partial(
    jax.jit, static_argnums=(0,), donate_argnums=(4,)
)(forward_prefill_paged_at.__wrapped__)

# Donated variant of the speculative round loop for the speculative engine:
# the _SpecState carry holds BOTH page pools — without donation every
# segment would copy them. Same static args as the original jit
# (runtime/speculative._spec_rounds); arg 10 is the state.
from edgemesh.runtime.speculative import _spec_rounds  # noqa: E402

_spec_rounds_donated = partial(
    jax.jit, static_argnums=(0, 1, 4, 5, 6, 7, 8, 9, 12, 13),
    donate_argnums=(10,),
)(_spec_rounds.__wrapped__)


# The ragged boundary launch (serving's ONE admission+bridge program): packed
# segment tokens for every slot — a staged admission contributes its whole
# prompt/suffix chunk, every resident row its next decode token — run through
# forward_ragged_paged in a single launch. Replaces the per-request admission
# prefill dispatches AND the bridge for the ragged engine. The cache is
# donated (it holds the shared pool); rows finished at dispatch keep frozen
# lengths, exactly the bridge's contract.
@partial(jax.jit, static_argnums=(0, 6), donate_argnums=(5,))
def _ragged_boundary(cfg, params, tokens, cu_q_lens, fin, cache, s_cap):
    start = cache.lengths
    logits, cache = forward_ragged_paged.__wrapped__(
        cfg, params, tokens, cu_q_lens, cache, s_cap
    )
    return (
        logits.astype(jnp.float32),
        cache._replace(lengths=jnp.where(fin, start, cache.lengths)),
    )


class _StagedAdmission(NamedTuple):
    """Host-side record of an admission waiting for the next ragged boundary
    launch (its pages are already mapped, its slot already claimed)."""

    idx: int  # slot index
    trace: Any  # obs.RequestTrace
    plen: int  # full prompt tokens
    ids: Any  # np.ndarray — the token ids to prefill (suffix when warm)
    match: int  # tokens already in the row's pages (template or imported)
    imported: int = 0  # of those, tokens spliced from a remote KV payload


class _ExportJob(NamedTuple):
    """One queued ``/kv/export`` request: prefill the prompt's prefix into
    scratch pages and serialize it (serve/rest.py → submit_export)."""

    question: str
    fut: Future
    trace: Any  # obs.RequestTrace


def _make_bridge(decode_fn):
    """Finished-aware bridge step: runs the whole-batch decode forward that
    seeds the next segment's logits, but FREEZES finished rows' lengths (the
    host-owned paging contract — parked rows must never advance). The cache
    is donated: the bridge consumes the segment's dead output handle."""
    fn = decode_fn or forward_decode

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
    def bridge(cfg, params, prev, cache, fin):
        old = cache.lengths
        logits, cache = fn(cfg, params, prev, cache)
        return (
            logits.astype(jnp.float32),
            cache._replace(lengths=jnp.where(fin, old, cache.lengths)),
        )

    return bridge


def _splice_row_entries(cache, row, idx: int):
    """Graft a one-row prefill result's table/length entries back into the
    shared pool at slot ``idx`` — THE definition of the splice half of the
    donation contract (cold and warm admissions, both spec pools)."""
    return row._replace(
        page_table=cache.page_table.at[idx].set(row.page_table[0]),
        lengths=cache.lengths.at[idx].set(row.lengths[0]),
    )


def _prefill_into_row(cfg, params, tokens, lengths, cache, idx: int, row_table,
                      ledger=None):
    """Cold zero-copy paged admission: prefill through a donated one-row
    VIEW of the shared pool (the host-built pre-mapped table row + the
    shared pages, donated in place) and splice the resulting table/length
    entries back. Used by the base engine's cold path and by BOTH of the
    speculative engine's pools — one definition of the donation/splice
    contract. Every page the prompt touches is already mapped in
    ``row_table``, so the in-program allocator pops nothing. ``ledger``
    (obs/compute.ComputeLedger) attributes the launch as the
    ``paged_prefill`` boundary, keyed by the padded prompt bucket (the
    compile identity)."""
    row_view = cache._replace(
        page_table=jnp.asarray(row_table, jnp.int32)[None, :],
        lengths=jnp.zeros((1,), jnp.int32),
    )
    if ledger is not None:
        logits1, row = ledger.launch(
            "paged_prefill", _prefill_paged_donated,
            cfg, params, tokens, lengths, row_view,
            key=f"p{tokens.shape[1]}", tokens=int(tokens.shape[1]),
        )
    else:
        logits1, row = _prefill_paged_donated(
            cfg, params, tokens, lengths, row_view)
    return logits1, _splice_row_entries(cache, row, idx)


@partial(jax.jit, donate_argnums=(0,))
def _copy_page(pages, src, dst):
    """In-place physical-page copy inside a [L, P, ...] pool array."""
    return pages.at[:, dst].set(pages[:, src])


# `pool_finished` (arg 5) is NOT donated: it is [slots] bool — nothing to
# save — and the pipelined worker holds the previous segment's `fin` output
# (the same buffer) in its in-flight fetch set; donating it here deleted
# that handle mid-fetch.
@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def _splice_slot(
    pool_k, pool_v, pool_len, pool_logits, pool_mask, pool_finished,
    row_k, row_v, row_len, row_logits, row_mask, idx,
):
    """In-place (donated) insertion of one prefilled request into the shared
    pool state at slot ``idx`` — an eager .at[].set here would copy the whole
    multi-GB pool per admission (dense backend)."""
    return (
        pool_k.at[:, idx].set(row_k[:, 0]),
        pool_v.at[:, idx].set(row_v[:, 0]),
        pool_len.at[idx].set(row_len),
        pool_logits.at[idx].set(row_logits.astype(pool_logits.dtype)),
        pool_mask.at[idx].set(row_mask),
        pool_finished.at[idx].set(False),
    )


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6))
def _splice_slot_quant(
    pool_k, pool_v, pool_ks, pool_vs, pool_len, pool_logits, pool_mask,
    pool_finished,
    row_k, row_v, row_ks, row_vs, row_len, row_logits, row_mask, idx,
):
    """_splice_slot's int8-slab twin: the quant cache carries per-token
    k/v scale planes alongside the int8 data, spliced under the same
    donation contract (pool_finished stays undonated — see _splice_slot)."""
    return (
        pool_k.at[:, idx].set(row_k[:, 0]),
        pool_v.at[:, idx].set(row_v[:, 0]),
        pool_ks.at[:, idx].set(row_ks[:, 0]),
        pool_vs.at[:, idx].set(row_vs[:, 0]),
        pool_len.at[idx].set(row_len),
        pool_logits.at[idx].set(row_logits.astype(pool_logits.dtype)),
        pool_mask.at[idx].set(row_mask),
        pool_finished.at[idx].set(False),
    )


def _parked_pool(init_fn, n_slots: int, total_pages: int):
    """Fresh page pool with every slot PARKED at length 1, plus its matching
    host free list. ONE definition of the load-bearing convention: a frozen
    idle row at length 1 never sits on a page boundary, so the in-program
    allocator never pops and the host-owned free list stays authoritative
    (length 0 would pop on the first masked step and silently corrupt it).
    Used everywhere a pool is (re)built: engine init, template resize,
    reset-after-failure, and both of the speculative engine's pools."""
    cache = init_fn()._replace(lengths=jnp.ones((n_slots,), jnp.int32))
    return cache, list(range(1, total_pages))


@dataclass
class _Slot:
    future: Future | None = None
    question: str = ""
    emitted: list[int] = field(default_factory=list)
    remaining: int = 0
    t_submit: float = 0.0
    t_start: float = 0.0
    trace: Any = None  # obs.RequestTrace — the request's span tree
    pages: list[int] = field(default_factory=list)  # paged: private pages held
    # Quality accumulators (obs/quality.py): running sums/min of the decode
    # loop's per-segment [b, 3] quality slot, folded host-side per drained
    # segment. q_tokens counts the DEVICE-side steps (un-trimmed), matching
    # what the sums cover.
    q_conf_sum: float = 0.0
    q_conf_min: float = 1.0
    q_ent_sum: float = 0.0
    q_tokens: int = 0
    # Speculative engine: how many of the row's accumulated out-tokens have
    # already been emitted (the spec state's `out` grows in place; the
    # dense loop's per-segment buffers need no such cursor).
    taken: int = 0

    @property
    def active(self) -> bool:
        return self.future is not None


class _Inflight(NamedTuple):
    """One dispatched-but-undrained segment: the slot generations it was
    dispatched against plus the device handles of its outputs (async host
    copies already started)."""

    rows: list[tuple[int, int]]  # (slot index, generation at dispatch)
    handles: tuple  # device arrays to fetch; engine-specific layout


def _start_host_copy(handles) -> None:
    """Kick off device→host transfers so the blocking fetch in
    _process_segment mostly finds the bytes already landed."""
    for h in handles:
        try:
            h.copy_to_host_async()
        except Exception:  # pragma: no cover — platform-dependent
            pass


class ContinuousEngine:
    """Chunk-granular continuous batcher over one Agent's model."""

    # Low-cardinality `engine` label for every obs metric this engine feeds.
    obs_engine_label = "continuous"

    def __init__(
        self,
        agent,
        slots: int = 8,
        chunk: int = 16,
        idle_wait_s: float = 0.005,
        kv_backend: str = "dense",
        page_size: int = 64,
        total_pages: int | None = None,
        admission: str = "fifo",
        span_log=None,
        registry=None,
        trace_sample: float = 1.0,
        ragged: bool | None = None,
        tp_engine=None,
    ):
        self.agent = agent
        self.cfg = agent.cfg
        self.chunk = int(chunk)
        self.n_slots = int(slots)
        # Tensor-parallel serving (parallel/tp_infer.py): with a
        # TPInferenceEngine attached, the dense backend's prefill/decode
        # forwards run the engine's shard_map programs — every chip holds
        # its head/FFN shard and the only cross-chip traffic is the
        # collective joins, quantized/overlapped per the engine's
        # ``collective_mode``. The slab splice/bridge/decode-loop structure
        # is untouched: GSPMD reshards the spliced rows, the loop's
        # ``decode_fn`` is the engine's ``decode_forward``.
        self._tp = tp_engine
        if tp_engine is not None:
            if kv_backend != "dense":
                raise ValueError(
                    "tensor-parallel serving runs on kv_backend='dense' "
                    f"(got {kv_backend!r}); the paged pool's page tables "
                    "are not tp-sharded yet"
                )
            if tp_engine.mesh.shape.get("dp", 1) != 1:
                raise ValueError(
                    "tensor-parallel serving needs a dp=1 mesh (one-row "
                    "admission prefills cannot split over dp)"
                )
        if self.chunk < 1 or self.n_slots < 1:
            raise ValueError("slots and chunk must be >= 1")
        if admission not in ("fifo", "sjf"):
            raise ValueError(f"unknown admission policy {admission!r}")
        # "sjf": admission picks the cheapest waiting requests first —
        # estimated cost is (requested budget, prompt chars), both known at
        # submit time. Cuts p50 end-to-end latency on mixed workloads (the
        # short jobs stop queueing behind long ones) at identical aggregate
        # throughput; long jobs pay with a fatter p99, and a sustained
        # overload of short jobs can starve them — the classic SJF trade.
        self.admission = admission
        if kv_backend not in ("dense", "dense_int8", "paged", "paged_int8"):
            raise ValueError(f"unknown kv_backend {kv_backend!r}")
        # One flag for every host-owned-paging site: the dense/dense_int8
        # slabs share the splice-admission path, the paged/paged_int8 pools
        # share the page-table path.
        self._paged = kv_backend.startswith("paged")
        # Ragged boundary launches (DEFAULT for paged backends): admission
        # prefill chunks and every resident row's bridge decode token ride
        # ONE forward_ragged_paged launch per segment boundary — no
        # per-request prefill dispatch, no trailing bridge. ``ragged=False``
        # keeps the segmented path (per-request donated prefills + bridge):
        # the bench's ragged-vs-segmented ablation arm, and the only mode
        # dense slabs support.
        self._ragged = self._paged if ragged is None else bool(ragged and self._paged)
        if self._paged and int(page_size) < 1:
            raise ValueError("page_size must be >= 1")
        self.kv_backend = kv_backend
        # Cross-replica KV transfer (docs/FLEET.md "Tiered serving and KV
        # streaming"): paged pools can export a prompt's committed pages
        # over the wire and admit a request whose prefill ran elsewhere.
        # The dense slabs have no page table to splice into; the spec
        # engine opts out (its draft pool has no remote twin).
        self.supports_kv_transfer = self._paged
        self._queue: deque[
            tuple[str, Future, RequestTrace, int | None, bytes | None]
        ] = deque()
        self._cond = threading.Condition()
        self._closed = False
        # Slot table and device cache are OWNED by the engine worker thread
        # (edgelint EM301): every post-init access happens on the worker;
        # the under-_cond touches in _run/_reset_pool exist only to pair
        # with _queue/_free_pages, not because these fields are shared.
        self._slots = [_Slot() for _ in range(self.n_slots)]  # not shared
        self._gen = [0] * self.n_slots  # admission generation per slot
        cap = self.cfg.max_seq_len
        # Forwards read params from here: the tp engine's PLACED tree (with
        # its pre-divided o/down biases) when attached, the agent's
        # otherwise. One seam for every dense dispatch site.
        self._params = tp_engine.params if tp_engine is not None else agent.params
        if tp_engine is not None:
            self._cache = tp_engine.init_cache(self.n_slots, cap)
            self._decode_fn = tp_engine.decode_forward
        elif kv_backend == "dense":
            self._cache = init_kv_cache(self.cfg, self.n_slots, cap)  # not shared
            self._decode_fn = None  # _decode_loop default (forward_decode)
        elif kv_backend == "dense_int8":
            from edgemesh.runtime.quant_kv import (
                forward_decode_quant,
                init_quant_kv_cache,
            )

            self._cache = init_quant_kv_cache(self.cfg, self.n_slots, cap)
            self._decode_fn = forward_decode_quant
        else:
            self.page_size = int(page_size)
            per_row = -(-cap // self.page_size)  # ceil: table slots per row
            # Worst-case private pages one request can hold: full context
            # plus TWO segments of overshoot (mid-segment budget end + the
            # pipeline's one-segment retirement lag, each with its bridge
            # token) plus a COW boundary page for warm starts.
            self._per_row_worst = (
                -(-(cap + 2 * (self.chunk + 1)) // self.page_size) + 1
            )
            self.total_pages = int(total_pages or 1 + self.n_slots * self._per_row_worst)
            init = init_quant_paged_cache if kv_backend == "paged_int8" else init_paged_cache
            self._init_pool = lambda: init(
                self.cfg, self.n_slots, total_pages=self.total_pages,
                page_size=self.page_size, max_pages=per_row,
            )
            self._cache, self._free_pages = _parked_pool(
                self._init_pool, self.n_slots, self.total_pages
            )
            self._decode_fn = forward_decode_paged
            self._reserved_pages = 0
            self._auto_sized = total_pages is None
            # Prefix sharing (lazy, _ensure_template): the prompt template's
            # KV prefilled ONCE into pool pages that every admitted row's
            # table maps read-only (vLLM-style prefix caching on the paged
            # design — sharing is just table entries).
            self._template_ids: np.ndarray | None = None
            self._template_pages: list[int] = []
            self._template_capacity_added = False
            self.shared_prefix_hits = 0
            # Ragged boundary state (worker-owned): admissions staged for
            # the next boundary launch, and each slot's last sampled token
            # (the bridge input the boundary consumes).
            self._staged: list[_StagedAdmission] = []  # not shared
            self._prev = jnp.zeros((self.n_slots,), jnp.int32)  # not shared
            # Per-wave prefill-vs-decode token split through the SHARED
            # launch — what keeps the tracing critical path honest when both
            # phases ride one kernel. stats() reads these under the lock.
            self.ragged_boundaries = 0
            self.ragged_prefill_tokens = 0
            self.ragged_decode_tokens = 0
            # KV transfer state (worker-owned except the counters stats()
            # reads under the lock): queued export jobs, and a bounded LRU
            # of recent export payloads keyed by question — a hot shared
            # prefix prefills ONCE per replica no matter how many peers
            # fetch it (the replica half of the fleet's prefix cache).
            self._exports: deque[_ExportJob] = deque()  # guarded by: _cond
            self._export_cache: OrderedDict[str, dict] = OrderedDict()  # not shared
            self._export_cache_max = 16
            self.kv_exports = 0
            self.kv_imports = 0
            self.kv_imported_tokens = 0
        # fp32, NOT activation dtype: sampling must see the same logits the
        # solo decode path sees, or bf16 rounding flips near-tied greedy
        # tokens versus agent.answer.
        self._logits = jnp.zeros((self.n_slots, self.cfg.vocab_size), jnp.float32)
        self._mask = TokenMaskState.init(self.n_slots, self.cfg.vocab_size).mask
        self._finished = jnp.ones((self.n_slots,), bool)  # all slots idle
        self._rng = jax.random.PRNGKey(agent.sampling.seed)
        self._bridge = _make_bridge(self._decode_fn)
        # Stats for /stats and tests; the obs tracker feeds /metrics —
        # request-lifecycle spans (queued→prefill→decode→retire), latency
        # histograms, and the KV page gauges below. ``span_log`` (a JSONL
        # path) additionally flushes one span record per retired request.
        self.requests = 0
        self.segments = 0
        self.admitted_mid_flight = 0
        self.max_concurrent = 0
        self.obs = SpanTracker(registry, span_log, engine=self.obs_engine_label,
                               trace_sample=trace_sample)
        # Compile telemetry rides the same registry/span log: recompiles
        # mid-serve are the silent latency cliff every trace should show.
        self._compile_hook = install_compile_hook(
            registry=self.obs.registry, span_log=span_log
        )
        # The compute observatory (obs/compute.py): every jitted boundary
        # this engine dispatches goes through the ledger — once-per-compile
        # cost_analysis capture plus 1-in-N fenced launch timings feeding
        # the launch metrics, the span log, the flight ring (read live via
        # the tracker's attachment point), and the load digest's cost
        # block. EDGEMESH_COMPUTE_SAMPLE=0 turns the whole seam off.
        self.compute = ComputeLedger(
            registry=self.obs.registry, engine=self.obs_engine_label,
            span_log=span_log, flight_source=lambda: self.obs.flight,
        )
        # Compile-identity key strings for the statically-shaped
        # boundaries (one compile per engine lifetime each).
        self._ck_decode = f"b{self.n_slots}c{self.chunk}"
        if tp_engine is not None:
            tp_engine.instrument(self.compute)
        # The memory observatory (obs/memory.py): every page-pool
        # transition flows through the _pop_pages/_push_pages seam (plus
        # the template/reset notifications) into an attributed ledger —
        # per-tenant residency, internal/external fragmentation, the
        # conservation tripwire checked at quiesce, leak detection (the
        # pool_leak anomaly kind), and the exhaustion forecast the
        # admission controller and autoscaler consume from the digest's
        # mem block. EDGEMESH_MEM_LEDGER=0 turns the whole seam off.
        self.mem = PoolLedger(
            registry=self.obs.registry, engine=self.obs_engine_label,
            total_pages=self.total_pages if self._paged else 0,
            page_size=self.page_size if self._paged else 0,
            per_row_worst=self._per_row_worst if self._paged else 0,
            page_bytes=page_nbytes(self._cache) if self._paged else 0,
            span_log=span_log, flight_source=lambda: self.obs.flight,
            anomaly_source=lambda: self.obs.anomaly,
        )
        # The quality observatory (obs/quality.py): the decode loop's
        # per-request confidence/entropy reductions land here at retire —
        # histograms, per-tenant goodness gauges, the stats()/digest
        # quality blocks, and the quality_drift anomaly feed. The device
        # computes the signals unconditionally (an elementwise tail on the
        # sampler's softmax); EDGEMESH_QUALITY=0 disables the host-side
        # sink — the overhead-gate off arm benchmarks.py flips.
        self.quality = QualityTracker(
            registry=self.obs.registry, engine=self.obs_engine_label,
            anomaly_source=lambda: self.obs.anomaly,
        )
        self._pages_gauge = self.obs.registry.gauge(
            "edgemesh_kv_pages", "Paged KV pool occupancy by state",
            ("engine", "state"),
        )
        # The capacity model (docs/OBSERVABILITY.md): sustainable tok/s and
        # req/s derived from the service EWMAs, plus pool occupancy as
        # ratios. Refreshed on every load_digest read (the probe cadence),
        # so a scrape and /loadz agree.
        self._capacity_gauge = self.obs.registry.gauge(
            "edgemesh_capacity_tokens_per_s",
            "Live sustainable decode tok/s estimate (slots / decode EWMA)",
            ("engine",),
        )
        self._capacity_req_gauge = self.obs.registry.gauge(
            "edgemesh_capacity_requests_per_s",
            "Live sustainable req/s estimate from the capacity model",
            ("engine",),
        )
        self._pool_occupancy_gauge = self.obs.registry.gauge(
            "edgemesh_pool_occupancy_ratio",
            "Non-free share of the paged KV pool", ("engine",),
        )
        self._pool_frag_gauge = self.obs.registry.gauge(
            "edgemesh_pool_fragmentation_ratio",
            "Reserved-page capacity held for not-yet-generated tokens "
            "(worst-case allocator internal fragmentation)", ("engine",),
        )
        self._pool_headroom_gauge = self.obs.registry.gauge(
            "edgemesh_pool_free_page_headroom",
            "Worst-case admissions that still fit the free list", ("engine",),
        )
        self._prefix_hits_counter = self.obs.registry.counter(
            "edgemesh_shared_prefix_hits_total",
            "Admissions warm-started from the shared template prefix",
            ("engine",),
        ).labels(engine=self.obs_engine_label)
        self._ragged_tokens_counter = self.obs.registry.counter(
            "edgemesh_ragged_tokens_total",
            "Tokens through the shared ragged boundary launch, by phase",
            ("engine", "phase"),
        )
        # KV transfer accounting (paged backends): wire bytes by direction,
        # and admissions that consumed a remotely-computed prefix instead
        # of recomputing it (docs/OBSERVABILITY.md metric catalog).
        self._kv_transfer_counter = self.obs.registry.counter(
            "edgemesh_kv_transfer_bytes_total",
            "KV wire bytes moved by this engine, by direction",
            ("engine", "direction"),
        )
        self._remote_prefix_counter = self.obs.registry.counter(
            "edgemesh_prefix_remote_hits_total",
            "Admissions warm-started from a remotely-computed KV payload",
            ("engine",),
        ).labels(engine=self.obs_engine_label)
        # Collective wire accounting (tp serving only): analytic per-step
        # byte counts from the tp engine (shapes are static, so the counts
        # are exact for what the joins ship — parallel/collectives.py),
        # credited per dispatched segment and per admission prefill. The
        # wire savings of qpsum vs psum are a scrapeable number.
        self._collective_counter = self.obs.registry.counter(
            "edgemesh_collective_bytes_total",
            "Collective wire bytes moved by serving forwards, by op and dtype",
            ("engine", "op", "dtype"),
        )
        if tp_engine is not None:
            acct = tp_engine.collective_accounting(batch=1)
            self._collective_meta = {
                "collective_op": acct["op"],
                "collective_dtype": acct["dtype"],
                "collective_per_layer_bytes": acct["per_layer"],
            }
            # Per decode step the WHOLE pool rides the joins ([slots, 1, H]
            # payloads); per admission the one-row prefill ships [1, s, H].
            self._collective_step_bytes = tp_engine.collective_accounting(
                batch=self.n_slots
            )["bytes_per_step"]
            self._collective_row_bytes = acct["bytes_per_step"]
            self._collective_labels = self._collective_counter.labels(
                engine=self.obs_engine_label, op=acct["op"], dtype=acct["dtype"]
            )
        self._update_page_gauges()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- public interface (DynamicBatcher-compatible) -----------------------

    def submit(self, question: str, max_new: int | None = None,
               trace_ctx: TraceContext | None = None,
               tenant: str | None = None,
               session: str | None = None,
               kv_import: bytes | None = None) -> Future:
        """Enqueue one request. ``max_new`` caps THIS request's token budget
        below the engine-wide ``sampling.max_new_tokens`` (budgets are
        per-slot host state, so a per-request cap costs nothing); the
        "sjf" admission policy uses it as the job-size estimate.
        ``trace_ctx`` is the propagated distributed-trace context (the
        fleet router's attempt span) — the request's spans join that trace
        instead of minting their own (obs/trace.py). ``tenant`` is the raw
        ``X-Edgemesh-Tenant`` identity (None for untagged traffic): it
        rides the span record and the per-tenant SLO families
        (obs/slo.py), never the scheduling — fairness between tenants is
        the ROUTER's admission job, not the engine's. ``session`` is the
        raw ``X-Edgemesh-Session`` identity: span-record only, so
        ``edgemesh obs replay`` can rebuild recorded session grouping.
        ``kv_import`` is a serialized KV transfer payload (runtime/
        paged_kv.py wire format): the request's prompt prefix was
        prefilled on ANOTHER replica and admission splices the shipped
        pages instead of recomputing them — the decode half of
        prefill/decode disaggregation (paged backends only)."""
        if max_new is not None:
            max_new = int(max_new)
            if max_new < 1:
                raise ValueError(f"max_new must be >= 1, got {max_new}")
        if kv_import is not None and not self.supports_kv_transfer:
            raise ValueError(
                "kv_import needs a paged continuous engine "
                f"(kv_backend={self.kv_backend!r})"
            )
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is closed")
            trace = self.obs.submit(self.requests, trace_ctx,
                                    tenant=tenant,  # rid = arrival index
                                    session=session)
            self._queue.append((question, fut, trace, max_new, kv_import))
            self.requests += 1
            depth = len(self._queue)
            self._cond.notify()
        # Outside the engine lock: the queue-collapse detector takes the
        # monitor's own lock and a trigger dumps the flight ring to disk —
        # neither belongs inside _cond's critical section (EM303).
        anomaly = self.obs.anomaly
        if anomaly is not None:
            anomaly.on_queue_depth(depth)
        return fut

    def answer(self, question: str, max_new: int | None = None,
               trace_ctx: TraceContext | None = None,
               tenant: str | None = None,
               session: str | None = None,
               kv_import: bytes | None = None) -> dict[str, Any]:
        return self.submit(question, max_new=max_new, trace_ctx=trace_ctx,
                           tenant=tenant, session=session,
                           kv_import=kv_import).result()

    def submit_export(self, question: str,
                      trace_ctx: TraceContext | None = None,
                      tenant: str | None = None,
                      session: str | None = None) -> Future:
        """Enqueue one KV export: prefill ``question``'s prompt prefix
        (all but its last token — the importer's boundary launch needs at
        least one suffix token to seed logits) into scratch pool pages and
        resolve the future with ``{"kv_bytes", "tokens", "prompt_tokens",
        "cached"}``. Served from the bounded per-question export cache
        when warm — a hot prefix prefills once per replica. The prefill
        itself runs on the engine worker between segments, so a prefill-
        tier replica batches exports against its own decode cadence."""
        if not self.supports_kv_transfer:
            raise ValueError(
                "KV export needs a paged continuous engine "
                f"(kv_backend={self.kv_backend!r})"
            )
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is closed")
            trace = self.obs.submit(self.requests, trace_ctx,
                                    tenant=tenant, session=session)
            self._exports.append(_ExportJob(question, fut, trace))
            self.requests += 1
            self._cond.notify()
        return fut

    def check_kv_payload(self, buf: bytes) -> dict[str, int]:
        """Cheap header-only validation for the gateway: parse + geometry
        check against this engine's pool, no device work. Raises
        :class:`~edgemesh.runtime.paged_kv.KVWireError` on anything the
        import admission would refuse — the gateway turns that into a
        structured 400 before the request ever queues."""
        if not self.supports_kv_transfer:
            raise KVWireError(
                f"kv_backend={self.kv_backend!r} cannot import KV payloads"
            )
        payload = decode_wire(buf)
        check_wire_compat(payload, self._cache)
        return {"tokens": payload.tokens, "n_pages": payload.n_pages}

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=10)
        uninstall_compile_hook(self._compile_hook)

    def stats(self) -> dict[str, Any]:
        # Under the engine lock: the worker mutates counters and the paged
        # free list mid-segment, and an unlocked read could pair a new
        # reserved_pages with an old free list (torn snapshot). _cond's
        # underlying lock is an RLock, so the subclass extending this under
        # the same lock nests fine.
        with self._cond:
            out = {
                "requests": self.requests,
                "segments": self.segments,
                "admitted_mid_flight": self.admitted_mid_flight,
                "max_concurrent": self.max_concurrent,
                "slots": self.n_slots,
                "chunk": self.chunk,
                "kv_backend": self.kv_backend,
            }
            if self._tp is not None:
                out["tp"] = self._tp.tp
                out["collective_mode"] = self._tp.collective_mode
                out["collective_dtype"] = self._tp.comm_dtype
            if self._paged:
                out["total_pages"] = self.total_pages
                out["reserved_pages"] = self._reserved_pages
                out["free_pages"] = len(self._free_pages)
                out["template_pages"] = len(self._template_pages)
                out["shared_prefix_hits"] = self.shared_prefix_hits
                out["kv_exports"] = self.kv_exports
                out["kv_imports"] = self.kv_imports
                out["kv_imported_tokens"] = self.kv_imported_tokens
                out["ragged"] = self._ragged
                if self._ragged:
                    out["ragged_boundaries"] = self.ragged_boundaries
                    out["ragged_prefill_tokens"] = self.ragged_prefill_tokens
                    out["ragged_decode_tokens"] = self.ragged_decode_tokens
            # Live per-boundary ledger rollup (obs/compute.py); None when
            # the ledger is disabled or nothing launched yet.
            out["compute"] = self.compute.rollup() or None
            # Memory-observatory rollup (obs/memory.py), same contract.
            out["mem"] = self.mem.rollup() or None
            # Quality-observatory rollup (obs/quality.py), same contract.
            out["quality"] = self.quality.rollup() or None
            return out

    def load_digest(self) -> dict[str, Any]:
        """The engine's slice of the replica load digest (serve/rest.py
        ``/loadz``): admission-queue depth, the SpanTracker's latency/
        arrival EWMAs and SLO goodput, the live capacity estimate, and
        (paged backends) the pool occupancy block. Cheap by design — the
        fleet prober reads this on every probe, so it must never touch
        the device; the slot ``remaining`` reads below are advisory
        glances at worker-owned ints (GIL-atomic), not synchronization."""
        pool = None
        free_n = None
        with self._cond:
            queue_depth = len(self._queue)
            if self._paged:
                free_n = len(self._free_pages)
                pending = sum(
                    max(0, s.remaining) for s in self._slots if s.active
                )
                pool = pool_state(
                    self.total_pages, len(self._free_pages),
                    self._reserved_pages, len(self._template_pages),
                    self.page_size, self._per_row_worst,
                    pending_tokens=pending,
                )
        digest = self.obs.load_digest()
        digest["queue_depth"] = queue_depth
        cap = estimate_capacity(
            self.n_slots,
            ewma_decode_s=digest.get("ewma_decode_s"),
            ewma_service_s=digest.get("ewma_service_s"),
            ewma_decode_tokens=digest.get("ewma_decode_tokens"),
            measured_tok_s=self.compute.measured_tok_s(
                boundaries=("decode_loop", "spec_rounds")),
        )
        digest["capacity"] = cap
        digest["pool"] = pool
        # Per-boundary measured launch costs (obs/compute.py): None until
        # the ledger has fenced something — a pre-compute consumer (or an
        # old router) sees exactly the digest it always did.
        digest["costs"] = self.compute.digest_costs()
        # The memory observatory's digest block (obs/memory.py): per-tenant
        # residency, fragmentation split, leak/forecast rows, HBM drift.
        # None until the ledger has seen a transition — a pre-mem consumer
        # (or an old router) sees exactly the digest it always did.
        digest["mem"] = self.mem.digest_mem(
            free_pages=free_n,
            arrival_ewma_s=digest.get("ewma_arrival_s"),
        )
        # The quality observatory's digest block (obs/quality.py):
        # recent-weighted confidence/entropy and the low-quality fraction.
        # None until a signal has been seen — a pre-quality consumer (or
        # an old router) sees exactly the digest it always did.
        digest["quality"] = self.quality.digest_quality()
        eng = self.obs_engine_label
        if cap["est_tok_s"] is not None:
            self._capacity_gauge.labels(engine=eng).set(cap["est_tok_s"])
        if cap["est_req_s"] is not None:
            self._capacity_req_gauge.labels(engine=eng).set(cap["est_req_s"])
        if pool is not None:
            self._pool_occupancy_gauge.labels(engine=eng).set(
                pool["occupancy_ratio"])
            self._pool_frag_gauge.labels(engine=eng).set(
                pool["fragmentation_ratio"])
            self._pool_headroom_gauge.labels(engine=eng).set(
                pool["free_page_headroom"])
        return digest

    def _update_page_gauges(self) -> None:
        """Refresh the KV page-occupancy gauges (paged backends only).
        Called wherever the free list changes: admission, retirement,
        template install, pool reset."""
        if not self._paged:
            return
        g, eng = self._pages_gauge, self.obs_engine_label
        g.labels(engine=eng, state="total").set(self.total_pages)
        g.labels(engine=eng, state="free").set(len(self._free_pages))
        g.labels(engine=eng, state="reserved").set(self._reserved_pages)
        g.labels(engine=eng, state="template").set(len(self._template_pages))

    # -- host-owned page accounting -----------------------------------------

    def _pop_pages(self, n: int, rid=None, tenant: str | None = None,
                   cause: str = "admit") -> list[int]:
        # Under the engine lock so the (free list, reserved count, ledger)
        # triple mutates atomically with respect to a concurrent stats()
        # snapshot. This is THE page-lifecycle seam (edgelint EM115): the
        # attributed transition lands in the memory observatory beside the
        # existing counters, never as a side channel.
        with self._cond:
            taken = [self._free_pages.pop() for _ in range(n)]
            self._reserved_pages += n
            self.mem.on_reserve(n, rid=rid, tenant=tenant, cause=cause,
                                free=len(self._free_pages))
        return taken

    def _push_pages(self, pages: list[int], rid=None,
                    cause: str = "retire") -> None:
        with self._cond:
            self._free_pages.extend(pages)
            self._reserved_pages -= len(pages)
            self.mem.on_free(len(pages), rid=rid, cause=cause,
                             free=len(self._free_pages))

    def _build_row_table(self, shared: list[int], private: list[int]) -> np.ndarray:
        """Pre-mapped table row: shared (template) pages first, then the
        request's private pages. Slots beyond stay 0 — the request's page
        budget guarantees it never reaches them."""
        row = np.zeros((self._cache.max_pages,), np.int32)
        n = len(shared) + len(private)
        if n > row.size:
            raise ValueError(
                f"request needs {n} table slots, row has {row.size}"
            )
        row[: len(shared)] = shared
        row[len(shared) : n] = private
        return row

    # -- engine loop --------------------------------------------------------

    def _clamp_budget(self, plen: int, max_new: int | None) -> int:
        """Pipelined-overshoot budget clamp — ONE definition for every
        admission path (dense, segmented paged, staged ragged): a
        budget-exhausted row rides one unfrozen lag segment plus the
        in-segment overshoot before its length freezes, advancing up to
        2*(chunk+1) tokens past plen+budget, and even that worst case must
        stay inside the model's declared position range."""
        budget = int(self.agent.sampling.max_new_tokens)
        if max_new is not None:
            budget = min(budget, int(max_new))
        over = 2 * (self.chunk + 1)
        budget = min(budget, int(self.cfg.max_seq_len) - plen - over)
        if budget < 1:
            raise ValueError(
                f"prompt ({plen} tokens) leaves no decode room inside "
                f"max_seq_len={self.cfg.max_seq_len} after the pipeline "
                f"overshoot margin ({over} tokens)"
            )
        return budget

    def _plan_paged_admission(self, prompt_row, plen: int, budget: int):
        """Template match + worst-case page arithmetic shared by the staged
        (ragged) and prefill-now (segmented) paged admission paths — ONE
        definition so the ablation's A/B arms cannot silently diverge.
        ``prompt_row`` is the prompt's token ids (host array or device
        row). Returns ``(match, need)``: the shared-template token match
        (0 when sharing buys nothing) and the private pages to map —
        prompt + budget + one segment of mid-flight overshoot + one
        segment of pipeline retirement lag (each with its bridge/boundary
        token), capped at the table row's slot count (writes past the last
        logical slot clamp onto the row's own garbage page or the trash
        page, never another row's). Raises when the pool can NEVER satisfy
        the request; ``need`` may still exceed the current free list (the
        caller re-queues — capacity, not failure)."""
        self._ensure_template()
        from edgemesh.runtime.prefix_cache import common_token_prefix

        match = 0
        if self._template_ids is not None and self._template_ids.size:
            match = common_token_prefix(self._template_ids, prompt_row)
        if match // self.page_size == 0:
            match = 0  # below one page: sharing buys nothing, go cold
        over = 2 * (self.chunk + 1)
        mapped = min(
            -(-(plen + budget + over) // self.page_size),
            int(self._cache.max_pages),
        )
        need = max(mapped - match // self.page_size, 1)
        if need > len(self._free_pages) + self._reserved_pages:
            raise ValueError(
                f"request needs {need} pages (prompt {plen} + budget "
                f"{budget} + segment overshoot); the pool holds "
                f"{len(self._free_pages) + self._reserved_pages} beyond "
                "the template"
            )
        return match, need

    def _admit(self, idx: int, question: str, fut: Future, trace,
               mid_flight: bool, max_new: int | None = None,
               kv: bytes | None = None) -> bool:
        """Prefill one request and splice its state into slot ``idx``.

        Returns False when a paged backend lacks free pages for the request's
        worst case (the caller re-queues it — capacity, not failure).
        ``kv`` is a serialized remote-prefill payload: admission splices the
        shipped pages and prefills only the unmatched suffix."""
        if kv is not None:
            return self._admit_import(idx, question, fut, trace, mid_flight,
                                      max_new=max_new, kv=kv)
        if self._paged and self._ragged:
            return self._stage_admission(idx, question, fut, trace,
                                         mid_flight, max_new=max_new)
        agent = self.agent
        self.obs.admit_start(trace)
        prompt = agent.format_prompt(question)
        tokens, lengths, _ = agent._prepare_batch([prompt])
        plen = int(lengths[0])
        # (The spec engine freezes budget-complete rows device-side and
        # carries its own gamma-aware margin instead of this clamp.)
        budget = self._clamp_budget(plen, max_new)

        if not self._paged:
            cap = self._cache.k.shape[2]
            valid = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
            mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(tokens, valid).mask
            sidx = jnp.asarray(idx, jnp.int32)
            if self.kv_backend == "dense":
                if self._tp is not None:
                    row_cache = self._tp.init_cache(1, cap)
                    logits1, row_cache = self._tp.prefill(
                        tokens, lengths, row_cache
                    )
                    self._collective_labels.inc(
                        self._tp.collective_accounting(
                            batch=1, seq=int(tokens.shape[1])
                        )["bytes_per_step"]
                    )
                else:
                    row_cache = init_kv_cache(self.cfg, 1, cap)
                    logits1, row_cache = self.compute.launch(
                        "dense_prefill", forward_prefill,
                        self.cfg, agent.params, tokens, lengths, row_cache,
                        key=f"p{tokens.shape[1]}", tokens=plen,
                    )
                k, v, ln, self._logits, self._mask, self._finished = _splice_slot(
                    self._cache.k, self._cache.v, self._cache.lengths,
                    self._logits, self._mask, self._finished,
                    row_cache.k, row_cache.v, lengths[0], logits1[0], mask1[0],
                    sidx,
                )
                self._cache = KVCache(k=k, v=v, lengths=ln)
            else:  # dense_int8: the slab carries per-token scales too
                from edgemesh.runtime.quant_kv import (
                    QuantKVCache,
                    forward_prefill_quant,
                    init_quant_kv_cache,
                )

                row_cache = init_quant_kv_cache(self.cfg, 1, cap)
                logits1, row_cache = self.compute.launch(
                    "dense_prefill", forward_prefill_quant,
                    self.cfg, agent.params, tokens, lengths, row_cache,
                    key=f"p{tokens.shape[1]}", tokens=plen,
                )
                (k, v, ks, vs, ln, self._logits, self._mask,
                 self._finished) = _splice_slot_quant(
                    self._cache.k, self._cache.v,
                    self._cache.k_scale, self._cache.v_scale,
                    self._cache.lengths, self._logits, self._mask,
                    self._finished,
                    row_cache.k, row_cache.v,
                    row_cache.k_scale, row_cache.v_scale,
                    lengths[0], logits1[0], mask1[0], sidx,
                )
                self._cache = QuantKVCache(
                    k=k, v=v, k_scale=ks, v_scale=vs, lengths=ln
                )
            pages: list[int] = []
        else:
            # Shared-prefix match + worst-case private-page plan — the SAME
            # arithmetic the staged ragged path runs (_plan_paged_admission;
            # matching leaves at least one suffix token to prefill, same
            # matcher as the dense warm path, runtime/prefix_cache.py).
            match, need = self._plan_paged_admission(
                tokens[0, :plen], plen, budget
            )
            shared_full = match // self.page_size  # read-only shared pages
            if need > len(self._free_pages):
                return False  # capacity — re-queue, admit at a later boundary
            pages = self._pop_pages(need, rid=trace.rid, tenant=trace.tenant,
                                    cause="cow" if match else "admit")
            # Tokens landing in PRIVATE pages (the suffix plus the COW
            # boundary page's shared tail) — the ledger's committed floor;
            # reserved-minus-committed is the internal-fragmentation split.
            self.mem.on_commit(
                trace.rid, add_tokens=plen - shared_full * self.page_size)
            # Zero-copy KV admission: prefill through a one-row VIEW of the
            # shared pool (the host-built pre-mapped table + shared pages,
            # donated). Only the slot's own page-table/length entries change
            # host-side; no KV row splice exists in the paged world. With a
            # template match, the row warm-starts: its table maps the shared
            # pages read-only (boundary page copy-on-write) and only the
            # suffix prefills.
            try:
                if match:
                    shared = list(self._template_pages[:shared_full])
                    private = list(pages)
                    if match % self.page_size:
                        # The partially-shared boundary page copies on
                        # write: the suffix overwrites its tail slots.
                        self._cow_copy(self._template_pages[shared_full], private[0])
                    row_table = self._build_row_table(shared, private)
                    row_view = self._cache._replace(
                        page_table=jnp.asarray(row_table)[None, :],
                        lengths=jnp.zeros((1,), jnp.int32),
                    )
                    suffix = tokens[:, match:]
                    logits1, row = self.compute.launch(
                        "paged_splice", _prefill_paged_at_donated,
                        self.cfg, agent.params, suffix,
                        jnp.asarray([plen - match], jnp.int32), row_view,
                        jnp.asarray([match], jnp.int32),
                        key=f"p{suffix.shape[1]}", tokens=plen - match,
                    )
                    with self._cond:  # stats() reads this under the lock
                        self.shared_prefix_hits += 1
                    self._prefix_hits_counter.inc()
                    cache = _splice_row_entries(self._cache, row, idx)
                else:
                    row_table = self._build_row_table([], pages)
                    logits1, cache = _prefill_into_row(
                        self.cfg, agent.params, tokens, lengths, self._cache,
                        idx, row_table, ledger=self.compute,
                    )
            except Exception:
                # The donated pool buffers may already be invalidated — a
                # fail-only-this-request recovery is impossible. Rebuild the
                # pool and fail the in-flight rows (their KV lived in it),
                # then re-raise so the caller fails THIS request too.
                self._reset_pool(
                    RuntimeError("page pool reset after a failed admission prefill")
                )
                raise
            self._cache = cache
            valid = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
            mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(tokens, valid).mask
            self._logits = self._logits.at[idx].set(logits1[0].astype(self._logits.dtype))
            self._mask = self._mask.at[idx].set(mask1[0])
            self._finished = self._finished.at[idx].set(False)

        self.obs.admitted(
            trace, prompt_tokens=plen, prompt_chars=len(question),
            shared_prefix_hit=bool(self._paged and match),
            **(self._collective_meta if self._tp is not None else {}),
        )
        self._slots[idx] = _Slot(
            future=fut, question=question, emitted=[], remaining=budget,
            t_submit=trace.t_submit, t_start=trace.t_start, trace=trace,
            pages=pages,
        )
        self._gen[idx] += 1
        self._update_page_gauges()
        if mid_flight:
            with self._cond:  # stats() reads this under the lock
                self.admitted_mid_flight += 1
        return True

    def _stage_admission(self, idx: int, question: str, fut: Future, trace,
                         mid_flight: bool, max_new: int | None = None) -> bool:
        """Ragged admission: ALL of _admit's host bookkeeping — budget clamp,
        template match, worst-case page mapping, COW boundary copy, table-row
        splice, slot claim — with NO prefill dispatch. The prompt (or warm
        template suffix) rides the next segment boundary's ragged launch
        (_dispatch_boundary), where admission prefill and resident decode
        share one kernel. Returns False on page-pool capacity, like _admit.
        Token ids stay host-side end to end: staging never reads the device
        (the segmented path's template matcher pays a device→host readback
        per admission; over a tunneled TPU that is ~0.13 s each)."""
        agent = self.agent
        self.obs.admit_start(trace)
        prompt = agent.format_prompt(question)
        ids = np.asarray(
            agent.tokenizer.encode(prompt, max_len=agent._max_prompt()),
            np.int32,
        )
        plen = int(ids.size)
        budget = self._clamp_budget(plen, max_new)
        match, need = self._plan_paged_admission(ids, plen, budget)
        shared_full = match // self.page_size
        if need > len(self._free_pages):
            return False  # capacity — re-queue, admit at a later boundary
        pages = self._pop_pages(need, rid=trace.rid, tenant=trace.tenant,
                                cause="cow" if match else "admit")
        self.mem.on_commit(
            trace.rid, add_tokens=plen - shared_full * self.page_size)
        try:
            shared = list(self._template_pages[:shared_full]) if match else []
            private = list(pages)
            if match and match % self.page_size:
                self._cow_copy(self._template_pages[shared_full], private[0])
            row_table = self._build_row_table(shared, private)
            # Table/length splice only — the KV writes happen inside the
            # boundary launch. The row parks at ``match`` committed tokens;
            # the ragged segment appends from there.
            self._cache = self._cache._replace(
                page_table=self._cache.page_table.at[idx].set(
                    jnp.asarray(row_table)
                ),
                lengths=self._cache.lengths.at[idx].set(match),
            )
        except Exception:
            # The donated COW copy may have invalidated pool buffers —
            # same all-or-nothing recovery as a failed admission prefill.
            self._reset_pool(
                RuntimeError("page pool reset after a failed staged admission")
            )
            raise
        if match:
            with self._cond:  # stats() reads this under the lock
                self.shared_prefix_hits += 1
            self._prefix_hits_counter.inc()
        valid = jnp.ones((1, plen), bool)
        mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(
            jnp.asarray(ids)[None, :], valid
        ).mask
        self._mask = self._mask.at[idx].set(mask1[0])
        self._finished = self._finished.at[idx].set(False)
        self._slots[idx] = _Slot(
            future=fut, question=question, emitted=[], remaining=budget,
            t_submit=trace.t_submit, t_start=0.0, trace=trace, pages=pages,
        )
        self._gen[idx] += 1
        self._staged.append(_StagedAdmission(idx, trace, plen, ids[match:], match))
        self._update_page_gauges()
        if mid_flight:
            with self._cond:  # stats() reads this under the lock
                self.admitted_mid_flight += 1
        return True

    def _admit_import(self, idx: int, question: str, fut: Future, trace,
                      mid_flight: bool, max_new: int | None = None,
                      kv: bytes | None = None) -> bool:
        """Admission from a remote-prefill KV payload: splice the shipped
        pages into this pool and enter the decode loop with only the
        unmatched suffix left to prefill — the decode half of
        prefill/decode disaggregation, and the consumer side of the
        fleet's cross-replica prefix cache.

        The payload's token ids are matched against OUR tokenization of the
        prompt (runtime/prefix_cache.common_token_prefix), so a stale or
        partial payload degrades to a shorter match, never to wrong KV;
        the match is capped at plen-1 so at least one suffix token prefills
        (the boundary/suffix launch needs it to seed the row's logits).
        All imported pages are the request's PRIVATE pages — no COW, no
        template bookkeeping — and retire back to the free list normally.
        Returns False on page-pool capacity, like every admission path."""
        from edgemesh.runtime.prefix_cache import common_token_prefix

        agent = self.agent
        self.obs.admit_start(trace)
        payload = decode_wire(kv)
        check_wire_compat(payload, self._cache)
        prompt = agent.format_prompt(question)
        ids = np.asarray(
            agent.tokenizer.encode(prompt, max_len=agent._max_prompt()),
            np.int32,
        )
        plen = int(ids.size)
        budget = self._clamp_budget(plen, max_new)
        match = common_token_prefix(payload.ids, ids)
        over = 2 * (self.chunk + 1)
        need = min(
            -(-(plen + budget + over) // self.page_size),
            int(self._cache.max_pages),
        )
        if need > len(self._free_pages) + self._reserved_pages:
            raise ValueError(
                f"request needs {need} pages (prompt {plen} + budget "
                f"{budget} + segment overshoot); the pool holds "
                f"{len(self._free_pages) + self._reserved_pages} beyond "
                "the template"
            )
        if need > len(self._free_pages):
            return False  # capacity — re-queue, admit at a later boundary
        pages = self._pop_pages(need, rid=trace.rid, tenant=trace.tenant,
                                cause="import")
        self.mem.on_commit(trace.rid, add_tokens=plen)
        n_imp = -(-match // self.page_size) if match else 0
        try:
            if n_imp:
                # The payload's leading pages land in this row's private
                # pages (donated scatter); positions >= match in the last
                # page are overwritten by the suffix prefill.
                self._cache = splice_imported(self._cache, payload,
                                              pages[:n_imp])
            row_table = self._build_row_table([], pages)
            if self._ragged:
                self._cache = self._cache._replace(
                    page_table=self._cache.page_table.at[idx].set(
                        jnp.asarray(row_table)
                    ),
                    lengths=self._cache.lengths.at[idx].set(match),
                )
        except Exception:
            # Donated pool buffers may be invalidated — the same
            # all-or-nothing recovery as every failed admission prefill.
            self._reset_pool(
                RuntimeError("page pool reset after a failed KV import")
            )
            raise
        self._kv_transfer_counter.labels(
            engine=self.obs_engine_label, direction="import").inc(len(kv))
        if match:
            self._remote_prefix_counter.inc()
        with self._cond:  # stats() reads these under the lock
            self.kv_imports += 1
            self.kv_imported_tokens += match
        valid = jnp.ones((1, plen), bool)
        mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(
            jnp.asarray(ids)[None, :], valid
        ).mask
        self._mask = self._mask.at[idx].set(mask1[0])
        self._finished = self._finished.at[idx].set(False)
        if self._ragged:
            self._slots[idx] = _Slot(
                future=fut, question=question, emitted=[], remaining=budget,
                t_submit=trace.t_submit, t_start=0.0, trace=trace,
                pages=pages,
            )
            self._gen[idx] += 1
            self._staged.append(_StagedAdmission(
                idx, trace, plen, ids[match:], match, imported=match,
            ))
        else:
            # Segmented path: the suffix prefills NOW through the same
            # donated one-row programs the warm-template path uses. Pad the
            # suffix onto the pow2 ladder so import admissions key the same
            # bounded compile set as _prepare_batch prompts.
            suffix_len = plen - match
            pad = bucket_pow2(suffix_len, floor=POW2_FLOOR)
            suffix = np.zeros((1, pad), np.int32)
            suffix[0, :suffix_len] = ids[match:]
            try:
                if match:
                    row_view = self._cache._replace(
                        page_table=jnp.asarray(row_table)[None, :],
                        lengths=jnp.zeros((1,), jnp.int32),
                    )
                    logits1, row = self.compute.launch(
                        "paged_splice", _prefill_paged_at_donated,
                        self.cfg, agent.params, jnp.asarray(suffix),
                        jnp.asarray([suffix_len], jnp.int32), row_view,
                        jnp.asarray([match], jnp.int32),
                        key=f"p{pad}", tokens=suffix_len,
                    )
                    self._cache = _splice_row_entries(self._cache, row, idx)
                else:
                    logits1, self._cache = _prefill_into_row(
                        self.cfg, agent.params, jnp.asarray(suffix),
                        jnp.asarray([plen], jnp.int32), self._cache, idx,
                        row_table, ledger=self.compute,
                    )
            except Exception:
                self._reset_pool(
                    RuntimeError("page pool reset after a failed KV import")
                )
                raise
            self._logits = self._logits.at[idx].set(
                logits1[0].astype(self._logits.dtype))
            self.obs.admitted(
                trace, prompt_tokens=plen, prompt_chars=len(question),
                prefill_tokens=suffix_len, kv_import_tokens=match,
                shared_prefix_hit=False,
            )
            self._slots[idx] = _Slot(
                future=fut, question=question, emitted=[], remaining=budget,
                t_submit=trace.t_submit, t_start=trace.t_start, trace=trace,
                pages=pages,
            )
            self._gen[idx] += 1
        self._update_page_gauges()
        if mid_flight:
            with self._cond:  # stats() reads this under the lock
                self.admitted_mid_flight += 1
        return True

    def _handle_export(self, job: _ExportJob) -> bool:
        """Run one queued KV export on the worker: prefill the prompt's
        first plen-1 tokens into scratch pages (the same donated one-row
        program admissions use), serialize them, and hand the pages
        straight back to the free list — the serialized BYTES are the
        artifact, so an export never holds pool capacity past its own
        prefill. Returns False on page capacity (the caller re-queues)."""
        agent = self.agent
        eng = self.obs_engine_label
        cached = self._export_cache.get(job.question)
        if cached is not None:
            self._export_cache.move_to_end(job.question)
            self.obs.admit_start(job.trace)
            self.obs.admitted(
                job.trace, prompt_tokens=cached["prompt_tokens"],
                prefill_tokens=0, kv_export=True, kv_export_cache_hit=True,
            )
            self.obs.retire(job.trace, status="ok")
            self._kv_transfer_counter.labels(
                engine=eng, direction="export").inc(len(cached["kv_bytes"]))
            with self._cond:  # stats() reads this under the lock
                self.kv_exports += 1
            job.fut.set_result({**cached, "cached": True})
            return True
        self.obs.admit_start(job.trace)
        prompt = agent.format_prompt(job.question)
        ids = np.asarray(
            agent.tokenizer.encode(prompt, max_len=agent._max_prompt()),
            np.int32,
        )
        plen = int(ids.size)
        if plen < 2:
            raise ValueError(
                f"prompt tokenizes to {plen} tokens; KV export needs >= 2 "
                "(the importer must keep at least one suffix token)"
            )
        n = plen - 1  # the exported committed prefix
        n_pages = -(-n // self.page_size)
        if n_pages > int(self._cache.max_pages):
            raise ValueError(
                f"export needs {n_pages} table slots, a row has "
                f"{int(self._cache.max_pages)}"
            )
        with self._cond:
            free_now = len(self._free_pages)
            reserved = self._reserved_pages
        if n_pages > free_now:
            if n_pages > free_now + reserved:
                raise ValueError(
                    f"export needs {n_pages} pages; the pool holds "
                    f"{free_now + reserved} beyond the template"
                )
            return False  # capacity — retirements will free pages
        pages = self._pop_pages(
            n_pages, rid=job.trace.rid if job.trace is not None else None,
            tenant=SYSTEM_TENANT, cause="export")
        try:
            row_table = self._build_row_table([], pages)
            row_view = self._cache._replace(
                page_table=jnp.asarray(row_table)[None, :],
                lengths=jnp.zeros((1,), jnp.int32),
            )
            _, row = _prefill_paged_donated(
                self.cfg, agent.params, jnp.asarray(ids[:n])[None, :],
                jnp.asarray([n], jnp.int32), row_view,
            )
            self._cache = row._replace(
                page_table=self._cache.page_table, lengths=self._cache.lengths
            )
            buf = export_pages(self._cache, pages, n, ids[:n])
        except Exception:
            # The donated pool buffers may be invalidated; the reset also
            # rebuilds the free list, so the popped pages must NOT be
            # pushed back (they are already in the fresh list).
            self._reset_pool(
                RuntimeError("page pool reset after a failed export prefill")
            )
            raise
        self._push_pages(
            pages, rid=job.trace.rid if job.trace is not None else None,
            cause="export")
        result = {"kv_bytes": buf, "tokens": n, "prompt_tokens": plen}
        self._export_cache[job.question] = result
        while len(self._export_cache) > self._export_cache_max:
            self._export_cache.popitem(last=False)
        self._kv_transfer_counter.labels(
            engine=eng, direction="export").inc(len(buf))
        with self._cond:  # stats() reads this under the lock
            self.kv_exports += 1
        self.obs.admitted(job.trace, prompt_tokens=plen, prefill_tokens=n,
                          kv_export=True)
        self.obs.retire(job.trace, status="ok")
        job.fut.set_result({**result, "cached": False})
        return True

    def _ragged_cap(self, need: int) -> int:
        """Static packed-token capacity for a boundary launch: the
        decode-only boundary (no staged admissions) is exactly ``n_slots``
        — ONE compile reused every segment — and admission waves climb a
        doubling ladder from there, so compile variants stay O(log(slots ×
        prompt bucket)) instead of one per admission count."""
        return bucket_pow2(need, floor=self.n_slots)

    def _dispatch_boundary(self) -> None:
        """Queue the ragged boundary launch: ONE forward_ragged_paged over
        packed per-slot segments — a staged admission contributes its whole
        prompt/suffix chunk, every other slot its next decode token (the
        bridge input; parked rows ride frozen) — producing this segment's
        seed logits and advancing the pool. This is what deletes the
        per-request admission prefill dispatches: the wave structure is one
        launch regardless of how many requests joined."""
        staged = {r.idx: r for r in self._staged}
        self._staged = []
        q_lens = [
            len(staged[i].ids) if i in staged else 1
            for i in range(self.n_slots)
        ]
        cu_host = np.zeros((self.n_slots + 1,), np.int64)
        np.cumsum(q_lens, out=cu_host[1:])
        cu_host = cu_host.astype(np.int32)
        cap = self._ragged_cap(int(cu_host[-1]))
        # s_cap (the write-gather width) buckets to a power of two so the
        # (cap, s_cap) compile key space stays small.
        s_cap = 1
        for r in staged.values():
            s_cap = max(s_cap, bucket_pow2(len(r.ids), floor=POW2_FLOOR))
        base = np.zeros((cap,), np.int32)
        dec_mask = np.zeros((cap,), bool)
        dec_slot = np.zeros((cap,), np.int32)
        for i in range(self.n_slots):
            o = int(cu_host[i])
            if i in staged:
                base[o : o + len(staged[i].ids)] = staged[i].ids
            else:
                dec_mask[o] = True
                dec_slot[o] = i
        # Decode slots take their row's last sampled token from the device-
        # resident prev vector — packing never syncs on the decode loop.
        tokens = jnp.where(
            jnp.asarray(dec_mask), self._prev[jnp.asarray(dec_slot)],
            jnp.asarray(base),
        )
        self._logits, self._cache = self.compute.launch(
            "ragged_boundary", _ragged_boundary,
            self.cfg, self.agent.params, tokens, jnp.asarray(cu_host),
            self._finished, self._cache, s_cap,
            key=f"c{cap}s{s_cap}", tokens=int(cu_host[-1]),
        )
        n_prefill = sum(len(r.ids) for r in staged.values())
        n_decode = sum(
            1 for i, s in enumerate(self._slots)
            if s.active and i not in staged
        )
        with self._cond:  # stats() reads these under the lock
            self.ragged_boundaries += 1
            self.ragged_prefill_tokens += n_prefill
            self.ragged_decode_tokens += n_decode
        eng = self.obs_engine_label
        if n_prefill:
            self._ragged_tokens_counter.labels(
                engine=eng, phase="prefill").inc(n_prefill)
        if n_decode:
            self._ragged_tokens_counter.labels(
                engine=eng, phase="decode").inc(n_decode)
        for r in staged.values():
            # The prefill span closes at boundary DISPATCH (the launch is
            # async — same convention as the segmented path's admission),
            # tagged with the shared-launch token split so `edgemesh obs
            # trace` still separates prefill from decode time when both
            # phases share a kernel.
            self.obs.admitted(
                r.trace, prompt_tokens=r.plen,
                prompt_chars=len(self._slots[r.idx].question),
                prefill_tokens=int(len(r.ids)),
                # A template hit and a remote KV import both park the row
                # at `match` committed tokens, but the span must say which
                # mechanism skipped the work (the disagg e2e pins it).
                shared_prefix_hit=bool(r.match and not r.imported),
                ragged=True,
                **({"kv_import_tokens": int(r.imported)} if r.imported else {}),
            )
            self._slots[r.idx].t_start = r.trace.t_start

    def _ensure_template(self) -> None:
        """Lazily prefill the prompt template's shared prefix into
        host-assigned permanent pool pages (once per pool lifetime).
        Sharing is pure table bookkeeping afterwards: admitted rows map
        these pages read-only; the boundary page copies on write."""
        if self._template_ids is not None:
            return
        with self._cond:  # stats()/_reset_pool touch template state locked
            self._template_ids = np.zeros((0,), np.int32)  # default: no sharing
        if not getattr(self.agent, "prefix_cache", True):
            return
        tpl = self.agent.prompt_template
        if "{question}" not in tpl:
            return
        ids = np.asarray(
            self.agent.tokenizer.encode(tpl.split("{question}")[0]), np.int32
        )
        if ids.size < self.page_size or ids.size > self.cfg.max_seq_len - 8:
            return
        n_pages = -(-int(ids.size) // self.page_size)
        if self._auto_sized and not self._template_capacity_added:
            # Grow the (still-empty) pool so the permanent template pages
            # don't eat the per-request margin the default sizing
            # guarantees. Runs before any admission; one-time. total_pages
            # flips under the lock first (_init_pool sizes off it), the
            # rebuild runs OUTSIDE the lock (device work), and the
            # (cache, free list) pair swaps in under the lock so a
            # concurrent stats() never sees a torn pair.
            with self._cond:
                self.total_pages += n_pages
                self._template_capacity_added = True
            cache, free = _parked_pool(
                self._init_pool, self.n_slots, self.total_pages
            )
            with self._cond:
                self._cache, self._free_pages = cache, free
            # The regrown pool re-prices the books: a fresh total (the
            # conservation target) and a fresh page size in bytes. Runs
            # before any admission, so no holdings need migrating.
            self.mem.total_pages = self.total_pages
            self.mem.page_bytes = page_nbytes(self._cache)
        # A user-sized pool must still be able to SERVE after the template
        # moves in permanently — including a max-context COLD request (no
        # template match gets no page discount). Otherwise sharing is a net
        # loss. Skip sharing, don't fail: it is an optimization.
        if len(self._free_pages) - n_pages < self._per_row_worst:
            log.warning(
                "prefix sharing disabled: installing the %d-page template "
                "would leave %d pages, below the max-request bound %d",
                n_pages, len(self._free_pages) - n_pages, self._per_row_worst,
            )
            return
        with self._cond:
            tpl_pages = [self._free_pages.pop() for _ in range(n_pages)]
            # Permanent pages the engine itself holds: attributed to the
            # system tenant under the template's reserved rid, fully
            # committed (the prefix KV fills every slot it maps). Direct
            # pop (not _pop_pages): template pages are template state,
            # not _reserved_pages — but the ledger still sees them.
            self.mem.on_reserve(n_pages, rid=TEMPLATE_RID,
                                tenant=SYSTEM_TENANT, cause="template",
                                free=len(self._free_pages))
            self.mem.on_commit(TEMPLATE_RID, committed_pages=n_pages)
        row_view = self._cache._replace(
            page_table=jnp.asarray(
                self._build_row_table(tpl_pages, []))[None, :],
            lengths=jnp.zeros((1,), jnp.int32),
        )
        try:
            _, row = _prefill_paged_donated(
                self.cfg, self.agent.params, jnp.asarray(ids)[None, :],
                jnp.asarray([int(ids.size)], jnp.int32), row_view,
            )
        except Exception:
            # Donated pool buffers may be invalidated — same recovery as a
            # failed admission prefill (template retried after the reset).
            self._reset_pool(
                RuntimeError("page pool reset after a failed template prefill")
            )
            raise
        self._cache = row._replace(
            page_table=self._cache.page_table, lengths=self._cache.lengths
        )
        with self._cond:  # stats() reads template state under the lock
            self._template_pages = tpl_pages
            self._template_ids = ids

    def _cow_copy(self, src: int, dst: int) -> None:
        """Copy physical page src → dst across all layers (donated, in
        place): the suffix will overwrite dst's tail slots, so the shared
        original stays pristine for other rows."""
        c = self._cache
        upd = dict(
            k=_copy_page(c.k, src, dst), v=_copy_page(c.v, src, dst)
        )
        if hasattr(c, "k_scale"):
            upd["k_scale"] = _copy_page(c.k_scale, src, dst)
            upd["v_scale"] = _copy_page(c.v_scale, src, dst)
        self._cache = c._replace(**upd)

    def _park_slot_device(self, idx: int) -> None:
        """Device half of retirement for paged backends: zero the table row
        and park the length at 1, so the frozen idle row never allocates and
        its masked garbage write lands on the trash page. These updates
        queue AFTER any in-flight segment — which may still advance the
        retired row for one lag segment, covered by the page reservation."""
        self._cache = self._cache._replace(
            page_table=self._cache.page_table.at[idx].set(0),
            lengths=self._cache.lengths.at[idx].set(1),
        )

    def _reset_pool(self, exc: Exception) -> None:
        """Fail every in-flight request and rebuild the KV state from scratch
        — fresh zeroed arrays for EVERY donated buffer (cache + repetition
        mask), safe even when the old ones were invalidated by a failed
        donated prefill or segment. One recovery path for both backends."""
        self.obs.pool_reset(reason=str(exc))
        for i, s in enumerate(self._slots):
            if s.active:
                if not s.future.done():
                    s.future.set_exception(exc)
                if s.trace is not None:
                    self.obs.retire(s.trace, status="preempted")
                self._slots[i] = _Slot()
                self._gen[i] += 1
        self._finished = jnp.ones((self.n_slots,), bool)
        if self._tp is not None:
            self._cache = self._tp.init_cache(self.n_slots, self.cfg.max_seq_len)
        elif self.kv_backend == "dense":
            self._cache = init_kv_cache(self.cfg, self.n_slots, self.cfg.max_seq_len)
        elif self.kv_backend == "dense_int8":
            from edgemesh.runtime.quant_kv import init_quant_kv_cache

            self._cache = init_quant_kv_cache(
                self.cfg, self.n_slots, self.cfg.max_seq_len
            )
        else:
            cache, free = _parked_pool(
                self._init_pool, self.n_slots, self.total_pages
            )
            # Free list + reserved count swap atomically under the engine
            # lock (device work above stays outside it).
            with self._cond:
                self._cache = cache
                self._free_pages = free
                self._reserved_pages = 0
                # Template pages died with the pool; rebuild lazily on the
                # next admission (the capacity bump is one-time, survives).
                self._template_ids = None
                self._template_pages = []
                # Every resident page returned at once — the ledger's
                # books zero with the pool, recorded as one reset event.
                self.mem.on_reset(str(exc))
            if self._ragged:
                # Staged admissions' table rows died with the pool; their
                # futures were failed above (the slots were active).
                self._staged = []
                self._prev = jnp.zeros((self.n_slots,), jnp.int32)
        self._mask = TokenMaskState.init(self.n_slots, self.cfg.vocab_size).mask
        self._update_page_gauges()

    def _retire(self, idx: int):
        slot = self._slots[idx]
        tokenizer = self.agent.tokenizer
        # slot.emitted is already a host-side list of ints — hand it to the
        # tokenizer as-is. Round-tripping it through a device array made
        # decode's per-element int() a device readback EACH (~0.13s over the
        # tunnel): ~4s per retired request, 33s of a 36s serving wave.
        text = tokenizer.decode(slot.emitted) if slot.emitted else ""
        # Fold the segment-accumulated device signals into the request's
        # quality block BEFORE the span record flushes: the record is built
        # from trace.attrs, so the block rides JSONL + flight ring for free.
        quality = None
        if slot.q_tokens > 0:
            quality = {
                "confidence_mean": round(slot.q_conf_sum / slot.q_tokens, 4),
                "confidence_min": round(slot.q_conf_min, 4),
                "entropy_mean": round(slot.q_ent_sum / slot.q_tokens, 4),
                "tokens": slot.q_tokens,
            }
            if slot.trace is not None:
                slot.trace.attrs["quality"] = quality
        now = self.obs.retire(slot.trace, status="ok")
        tenant = slot.trace.tenant if slot.trace is not None else None
        self.quality.on_retire(quality, tenant=tenant)
        wall = max(now - slot.t_start, 1e-9)
        slot.future.set_result(
            {
                "answer": text.strip(),
                "role": self.agent.role,
                "tps": len(slot.emitted) / wall,
                "generated": len(slot.emitted),
                "queue_s": slot.t_start - slot.t_submit,
                "t_start": slot.t_start,
                "t_end": now,
                # The ensemble coordinator scores branch candidates by this
                # (fleet/ensemble.py) — None when no decode step landed.
                "confidence": (
                    None if quality is None else quality["confidence_mean"]),
            }
        )
        if self._paged:
            rid = slot.trace.rid if slot.trace is not None else None
            self._push_pages(slot.pages, rid=rid, cause="retire")
            # Start the leak clock: a holding that still has pages after
            # its owner retired is exactly what the pool_leak tripwire
            # hunts (a clean retirement just dropped the holding above).
            self.mem.on_retired(rid)
            self._park_slot_device(idx)
            self._update_page_gauges()
        self._slots[idx] = _Slot()
        self._gen[idx] += 1
        self._finished = self._finished.at[idx].set(True)

    def _dispatch_segment(self, active: list[int], eos_id: int) -> _Inflight:
        """Queue one pool-wide decode segment + its bridge on the device and
        return the output handles WITHOUT waiting. Segment length is ALWAYS
        ``chunk`` so _decode_loop compiles exactly once; a row whose budget
        ends mid-segment overshoots by < chunk forwards and the extras are
        trimmed host-side. Overridden by the speculative engine with
        draft→verify rounds."""
        agent = self.agent
        self._rng, seg_rng = jax.random.split(self._rng)
        if self._ragged:
            # Boundary-first pipeline: ONE launch advances every resident
            # row by its bridge token AND prefills every staged admission,
            # seeding this segment's logits. No trailing bridge and no
            # per-request prefill dispatch exist in this mode. A boundary
            # with nothing staged degenerates to q_lens == 1 everywhere —
            # run the plain bridge program for it (the decode kernel's
            # fold-fresh fast path); the ragged launch fires only when a
            # prefill chunk actually rides along.
            if self._staged:
                self._dispatch_boundary()
            else:
                with self._cond:  # stats() reads this under the lock
                    self.ragged_boundaries += 1
                    self.ragged_decode_tokens += len(active)
                self._ragged_tokens_counter.labels(
                    engine=self.obs_engine_label, phase="decode"
                ).inc(len(active))
                self._logits, self._cache = self.compute.launch(
                    "bridge", self._bridge,
                    self.cfg, agent.params, self._prev, self._cache,
                    self._finished,
                    key=self._ck_decode, tokens=len(active),
                )
        out, counts, cache, qual, mask, prev, fin = self.compute.launch(
            "decode_loop", _decode_loop,
            self.cfg, self._params, agent.sampling, self.chunk, eos_id,
            self._logits, self._cache, self._mask, seg_rng,
            self._decode_fn, self._finished,
            key=self._ck_decode, tokens=self.chunk * max(len(active), 1),
        )
        self._mask, self._finished = mask, fin
        with self._cond:  # stats() reads this under the lock
            self.segments += 1
        if self._tp is not None:
            # chunk decode steps + the trailing bridge, each a full-pool
            # forward through the collective joins.
            self._collective_labels.inc(
                (self.chunk + 1) * self._collective_step_bytes
            )
        self.obs.segment_dispatched()
        if self._ragged:
            # The NEXT boundary consumes prev; nothing else runs here.
            self._prev = prev
            self._cache = cache
        else:
            # Bridge into the next segment unconditionally: rows that turn
            # out to have finished get frozen lengths (finished-aware
            # bridge) and a masked garbage write. The alternative — waiting
            # to know whether anyone survives — is exactly the sync this
            # pipeline removes.
            self._logits, self._cache = self.compute.launch(
                "bridge", self._bridge,
                self.cfg, self._params, prev, cache, fin,
                key=self._ck_decode, tokens=len(active),
            )
        if self._paged:
            # +0 detaches the tripwire snapshot from the cache buffer — the
            # cache itself is donated into the next segment/admission while
            # this handle is still awaiting its host fetch.
            # The quality slot rides LAST: fetched[:3] and the paged
            # tripwire's fetched[3] keep their positions either way.
            handles = (counts, out, fin, self._cache.free_top + 0, qual)
        else:
            handles = (counts, out, fin, qual)
        _start_host_copy(handles)
        return _Inflight([(i, self._gen[i]) for i in active], handles)

    def _process_segment(self, seg: _Inflight, eos_id: int) -> None:
        """Drain one segment's results (its successor is already executing)
        and run the host-side emit/retire bookkeeping."""
        # Already-complete handles: the successor segment is executing,
        # so this readback gates nothing.
        fetched = jax.device_get(seg.handles)  # edgelint: disable=EM114
        counts_h, out_h, fin_h = fetched[:3]
        if self._paged and int(fetched[3]) != 1:
            # Host-owned-allocator tripwire: the device popped pages. A bug,
            # not a capacity event — any page it handed out is ALSO on the
            # host free list, so a later admission could double-map the same
            # physical page across two rows (silent KV cross-contamination).
            # Fatal for the pool: RAISE so _run's segment-failure handler
            # resets (failing in-flight rows loudly) AND drops the already-
            # dispatched successor segment — a reset here would leave that
            # successor's stale pre-reset free_top snapshot to re-fire the
            # tripwire and fail requests admitted after recovery.
            raise RuntimeError(  # pragma: no cover
                "paged-pool tripwire: device allocator popped pages "
                f"(free_top={int(fetched[3])}) despite host-owned pre-mapping"
            )
        qual_h = fetched[-1]
        for i, gen in seg.rows:
            slot = self._slots[i]
            if not slot.active or self._gen[i] != gen:
                continue  # retired earlier and possibly re-admitted
            # Fold this segment's device-side quality reductions into the
            # slot BEFORE trimming: the device accumulated over every step
            # it actually sampled (raw count), including budget overshoot.
            raw = int(counts_h[i])
            if raw > 0:
                slot.q_conf_sum += float(qual_h[i][0])
                slot.q_conf_min = min(slot.q_conf_min, float(qual_h[i][1]))
                slot.q_ent_sum += float(qual_h[i][2])
                slot.q_tokens += raw
            n = min(int(counts_h[i]), max(slot.remaining, 0))
            toks = [int(t) for t in out_h[i][:n]]
            if toks and toks[-1] == eos_id:
                toks = toks[:-1]
            slot.emitted.extend(toks)
            slot.remaining -= n
            if self._paged and slot.trace is not None:
                # Per-boundary commit: the row advanced n tokens into its
                # private pages (internal-fragmentation bookkeeping).
                self.mem.on_commit(slot.trace.rid, add_tokens=n)
            # tp serving: each decode span carries its slice of the wire
            # (tokens x per-row collective bytes) so `edgemesh obs trace`
            # can roll the savings up per request (obs/trace.critical_path).
            attrs = (
                {"collective_bytes": len(toks) * self._collective_row_bytes}
                if self._tp is not None else {}
            )
            self.obs.tokens(slot.trace, len(toks), **attrs)
            if bool(fin_h[i]) or slot.remaining <= 0:
                self._retire(i)

    def _run(self) -> None:
        agent = self.agent
        eos_id = int(getattr(agent.tokenizer, "eos_id", -1))
        inflight: _Inflight | None = None
        while True:
            # Admit as many queued requests as there are free slots.
            with self._cond:
                while (
                    not self._queue
                    and not (self._paged and self._exports)
                    and not any(s.active for s in self._slots)
                    and inflight is None
                ):
                    if self._closed:
                        return
                    if self._paged:
                        # Quiesce: no queue, no active slot, no in-flight
                        # segment — every page must be home. The tripwire
                        # counter (not an exception) records a break;
                        # pages whose owner retired long ago fire the
                        # pool_leak anomaly (fleet-wide flight dump).
                        self.mem.check_conservation(len(self._free_pages))
                        self.mem.leak_scan()
                    self._cond.wait()
                exports: list[_ExportJob] = []
                if self._paged and self._exports:
                    exports = list(self._exports)
                    self._exports.clear()
                free = [i for i, s in enumerate(self._slots) if not s.active]
                if self.admission == "sjf" and len(self._queue) > 1 and free:
                    # Stable sort: FIFO among equal-cost jobs, so same-size
                    # requests keep their arrival order.
                    default = int(self.agent.sampling.max_new_tokens)
                    # Key on the EFFECTIVE budget (admission clamps to the
                    # engine-wide max), not the raw request cap — a cap
                    # above the engine budget costs the same as default.
                    self._queue = deque(sorted(
                        self._queue,
                        key=lambda it: (
                            min(it[3], default) if it[3] is not None else default,
                            len(it[0]),
                        ),
                    ))
                pending: list[
                    tuple[str, Future, RequestTrace, int | None, bytes | None]
                ] = []
                while self._queue and len(pending) < len(free):
                    pending.append(self._queue.popleft())
            # KV exports run between segments on the worker (the only
            # thread allowed to touch the donated pool): slot-free one-row
            # prefills whose pages return to the free list immediately.
            for pos, job in enumerate(exports):
                try:
                    done = self._handle_export(job)
                except Exception as exc:
                    log.exception("kv export failed for %r",
                                  job.question[:80])
                    self.obs.retire(job.trace, status="error")
                    if not job.fut.done():
                        job.fut.set_exception(exc)
                    continue
                if not done:
                    # Page capacity: re-queue this and the rest in order;
                    # they run once retirements reclaim pages (held pages
                    # imply active rows exist, so the loop cannot spin).
                    with self._cond:
                        for j in reversed(exports[pos:]):
                            self._exports.appendleft(j)
                    break
            free_now = [i for i, s in enumerate(self._slots) if not s.active]
            mid = any(s.active for s in self._slots) or inflight is not None
            for pos, ((q, fut, trace, req_max, kv), idx) in enumerate(zip(pending, free_now)):
                try:
                    # Bind the request's trace context around admission so
                    # a prefill-triggered jit compile lands in ITS trace
                    # (compile records are process-ambient otherwise).
                    ctx = (
                        TraceContext(trace.trace_id, trace.span_id,
                                     trace.sampled)
                        if trace.trace_id and trace.span_id else None
                    )
                    with use_trace(ctx):
                        ok = self._admit(idx, q, fut, trace, mid_flight=mid,
                                         max_new=req_max, kv=kv)
                except Exception as exc:
                    # Fail only THIS request: already-admitted slots keep
                    # their pending futures (poisoning them would make the
                    # later _retire set_result raise InvalidStateError and
                    # kill the worker).
                    log.exception("admission failed for %r", q[:80])
                    self.obs.retire(trace, status="error")
                    if not fut.done():
                        fut.set_exception(exc)
                    continue
                if not ok:
                    # Page-pool capacity: re-queue this and the rest of the
                    # batch (order preserved); they admit at a later segment
                    # boundary once retirements reclaim pages. Held pages
                    # imply active rows exist, so the loop cannot spin.
                    with self._cond:
                        for item in reversed(pending[pos:]):
                            self._queue.appendleft(item)
                    break

            active = [i for i, s in enumerate(self._slots) if s.active]
            with self._cond:  # stats() reads this under the lock
                self.max_concurrent = max(self.max_concurrent, len(active))

            # Depth-2 pipeline: dispatch the next segment BEFORE draining the
            # previous one — the fetch + bookkeeping below overlap with the
            # device executing this dispatch. A failure anywhere must not
            # kill the worker: fail the in-flight futures, reset, continue.
            cur: _Inflight | None = None
            if active:
                try:
                    cur = self._dispatch_segment(active, eos_id)
                except Exception as exc:
                    log.exception(
                        "segment dispatch failed; failing %d in-flight requests",
                        len(active),
                    )
                    self._reset_pool(exc)
            if inflight is not None:
                try:
                    self._process_segment(inflight, eos_id)
                except Exception as exc:
                    log.exception(
                        "segment processing failed; failing in-flight requests"
                    )
                    self._reset_pool(exc)
                    cur = None  # its handles died with the pool
            inflight = cur


class SpeculativeContinuousEngine(ContinuousEngine):
    """Continuous batching WITH speculative decoding over the paged pool.

    Each segment runs up to ``chunk // (gamma+1)`` pool-wide draft→verify
    rounds in ONE jitted program (``runtime.speculative._spec_rounds`` — the
    same body the standalone and streaming speculative paths use), so every
    request in flight gets draft acceleration while requests still join and
    leave at segment boundaries. Both models' KV live as page pools; the
    verify rewind is a lengths rollback, safe on pages because the allocator
    reuses table entries on re-advance (rewind-idempotent). Pages for BOTH
    pools are host-owned and pre-mapped at admission, exactly like the base
    engine; segments pipeline depth-2 the same way (the spec body freezes
    inactive rows itself, so the retirement lag costs nothing here).

    Contracts beyond the base engine:
    - paged backend only, and the agent must carry a draft
      (``AgentSpec.draft``) sharing the target's tokenizer/vocab.
    - uniform budget: every request decodes up to
      ``sampling.max_new_tokens``; a prompt too long for
      prompt + budget + gamma + 1 tokens in the model context (or one table
      row) is refused at admission (the dense engine clamps instead — spec
      rounds share one static max_new).
    - admissions are always cold (no template prefix sharing: the draft
      pool holds no template KV, and a warm target + cold draft would
      desynchronize the verify positions).
    - emitted text is the target distribution exactly — greedy spec serving
      is token-identical to the plain engine (pinned in tests).
    """

    obs_engine_label = "speculative"

    def __init__(
        self,
        agent,
        slots: int = 8,
        chunk: int = 16,
        idle_wait_s: float = 0.005,
        kv_backend: str = "paged",
        page_size: int = 64,
        total_pages: int | None = None,
        draft_total_pages: int | None = None,
        admission: str = "fifo",
        span_log=None,
        registry=None,
        trace_sample: float = 1.0,
    ):
        if getattr(agent, "draft_cfg", None) is None:
            raise ValueError(
                "SpeculativeContinuousEngine needs an agent with a draft "
                "model (AgentSpec.draft)"
            )
        if kv_backend not in ("paged", "paged_int8"):
            raise ValueError(
                f"speculative continuous batching runs on kv_backend='paged' "
                f"or 'paged_int8' (got {kv_backend!r})"
            )
        sp = agent.sampling
        if sp.do_sample and not 0 < sp.top_k < agent.cfg.vocab_size:
            # The standalone spec path validates this up front
            # (runtime/speculative._spec_prefill); without the check here,
            # the FIRST segment would hit filtered_candidates' error inside
            # the worker, reset the pool, and fail every admitted request —
            # forever, batch after batch.
            raise ValueError(
                "speculative sampling needs bounded support: set top_k in "
                f"[1, vocab) (got {sp.top_k})"
            )
        if int(agent.spec_gamma) < 1:
            raise ValueError(f"spec_gamma must be >= 1, got {agent.spec_gamma}")
        if int(page_size) < int(agent.spec_gamma) + 3:
            # Parked rows sit at length 1; a verify chunk writes gamma+1
            # rewind-idempotent positions there, which must stay inside
            # logical page 0 or idle rows would allocate.
            raise ValueError(
                f"page_size must be >= spec_gamma + 3 "
                f"(got {page_size} vs gamma {agent.spec_gamma})"
            )
        # admission="sjf" is legal here too: with the engine's uniform
        # budget the sort key degenerates to prompt length, which is still
        # a valid job-size signal (prefill cost).
        # ragged=False: the spec engine's segment is the draft→verify round
        # loop, whose rewind/advance cadence does not decompose into the
        # one-boundary-launch shape (admissions stay per-request cold
        # prefills of BOTH pools).
        super().__init__(
            agent, slots=slots, chunk=chunk, idle_wait_s=idle_wait_s,
            kv_backend=kv_backend, page_size=page_size, total_pages=total_pages,
            admission=admission, span_log=span_log, registry=registry,
            trace_sample=trace_sample, ragged=False,
        )
        # The worker thread is live from here on: a failure below would
        # orphan it blocked on the condition with a half-built engine —
        # close it on the way out (round-3 advisor finding).
        try:
            from edgemesh.runtime.speculative import _spec_fns

            self.gamma = int(agent.spec_gamma)
            self.max_new = int(agent.sampling.max_new_tokens)
            self.cap = self.max_new + self.gamma + 1
            self.rounds_per_segment = max(1, self.chunk // (self.gamma + 1))
            self._verify_fn, self._spec_decode_fn = _spec_fns(kv_backend)
            per_row = self._cache.page_table.shape[1]
            self._d_total = int(draft_total_pages or self.total_pages)
            d_cfg = agent.draft_cfg
            # The draft pool matches the target pool's precision: int8
            # everywhere is the point of the paged_int8 backend, and greedy
            # emitted tokens stay target-argmax regardless of draft cache
            # precision (draft quality only moves the acceptance rate).
            d_init = (
                init_quant_paged_cache if kv_backend == "paged_int8"
                else init_paged_cache
            )
            self._init_dpool = lambda: d_init(
                d_cfg, self.n_slots, total_pages=self._d_total,
                page_size=self.page_size, max_pages=per_row,
            )
            self._dcache, self._dfree = _parked_pool(
                self._init_dpool, self.n_slots, self._d_total
            )
            self._dslot_pages: dict[int, list[int]] = {}
            # The draft pool keeps its own books (obs/memory.py): separate
            # conservation target, separate per-tenant attribution, under
            # a distinct engine label. No span log — the target ledger's
            # records already carry the request lifecycle; the draft twin
            # exists so draft-pool leaks and occupancy are visible.
            self.dmem = PoolLedger(
                registry=self.obs.registry,
                engine=self.obs_engine_label + "_draft",
                total_pages=self._d_total, page_size=self.page_size,
                per_row_worst=self._per_row_worst,
                page_bytes=page_nbytes(self._dcache),
                flight_source=lambda: self.obs.flight,
                anomaly_source=lambda: self.obs.anomaly,
            )
            # The speculative round ledger (obs/compute.py): segment-level
            # counter deltas + the compute ledger's sampled launch timings,
            # split draft-vs-verify by the analytic flops ratio of gamma
            # draft steps against one gamma+1-token verify. This is the
            # instrument that decomposes the spec-vs-plain loss
            # (docs/PERFORMANCE.md) into its round structure.
            self._round_ledger = SpecRoundLedger(
                ledger=self.compute, engine=self.obs_engine_label,
                draft_frac=spec_draft_frac(
                    agent.params, agent.draft_params, int(agent.spec_gamma)),
            )
            self._spec_reset_arrays()
            # No KV transfer: an imported target prefix has no draft-pool
            # twin, and a warm target + cold draft would desynchronize the
            # verify positions (same reason spec admissions are always
            # cold).
            self.supports_kv_transfer = False
        except Exception:
            self.close()
            raise

    def _spec_reset_arrays(self) -> None:
        b = self.n_slots
        self._pending = jnp.zeros((b,), jnp.int32)
        self._out = jnp.zeros((b, self.cap), jnp.int32)
        self._nemit = jnp.zeros((b,), jnp.int32)
        self._conf = jnp.zeros((b,), jnp.float32)
        self._acc = jnp.zeros((), jnp.int32)
        self._prop = jnp.zeros((), jnp.int32)
        self._rnds = jnp.zeros((), jnp.int32)
        # Host mirror of (accepted, proposed, rounds), refreshed by the
        # worker inside each segment's bulk fetch. stats() reads ONLY this:
        # the device counters are donated every segment, so touching them
        # from another thread (REST /stats) races use-after-donate.
        self._spec_counters_host = (0, 0, 0)
        # The round ledger diffs successive host-counter snapshots; a pool
        # reset zeroes the device counters, so the baseline resets with it.
        self._spec_counters_prev = (0, 0, 0)
        self._update_spec_gauges()

    def _update_spec_gauges(self) -> None:
        """Mirror the cumulative draft→verify counters into obs gauges
        (gauges, not counters: the device counters reset with the pool)."""
        reg, eng = self.obs.registry, self.obs_engine_label
        acc, prop, rnds = self._spec_counters_host
        toks = reg.gauge(
            "edgemesh_spec_tokens", "Cumulative speculative draft tokens",
            ("engine", "kind"),
        )
        toks.labels(engine=eng, kind="accepted").set(acc)
        toks.labels(engine=eng, kind="proposed").set(prop)
        reg.gauge(
            "edgemesh_spec_rounds", "Cumulative draft→verify rounds",
            ("engine",),
        ).labels(engine=eng).set(rnds)
        reg.gauge(
            "edgemesh_spec_acceptance_ratio",
            "accepted / proposed draft tokens", ("engine",),
        ).labels(engine=eng).set(acc / prop if prop else 0.0)

    def _update_page_gauges(self) -> None:
        super()._update_page_gauges()
        if not hasattr(self, "_dfree"):  # base __init__ runs before spec's
            return
        g, eng = self._pages_gauge, self.obs_engine_label
        g.labels(engine=eng, state="draft_total").set(self._d_total)
        g.labels(engine=eng, state="draft_free").set(len(self._dfree))

    # Spec admissions are always cold — see the class docstring.
    def _ensure_template(self) -> None:
        return

    def submit(self, question: str, max_new: int | None = None,
               trace_ctx: TraceContext | None = None,
               tenant: str | None = None,
               session: str | None = None,
               kv_import: bytes | None = None) -> Future:
        if max_new is not None:
            # Fail fast on the caller's thread — the _admit guard below
            # stays as defense in depth, but surfacing an EXPECTED
            # validation error asynchronously via log.exception would be
            # noise indistinguishable from real admission failures.
            raise ValueError(
                "the speculative engine keeps one uniform budget per pool; "
                "per-request max_new is not supported"
            )
        if kv_import is not None:
            raise ValueError(
                "the speculative engine cannot import KV (the draft pool "
                "has no remote twin; see supports_kv_transfer)"
            )
        return super().submit(question, trace_ctx=trace_ctx, tenant=tenant,
                              session=session)

    def _admit(self, idx: int, question: str, fut: Future, trace,
               mid_flight: bool, max_new: int | None = None,
               kv: bytes | None = None) -> bool:
        if kv is not None:
            raise ValueError(
                "the speculative engine cannot import KV payloads"
            )
        if max_new is not None:
            # The spec rounds body runs ONE static max_new for the whole
            # pool (out-buffer capacity, freeze conditions); a per-request
            # budget would need per-row round budgets inside the while_loop.
            raise ValueError(
                "the speculative engine keeps one uniform budget per pool; "
                "per-request max_new is not supported"
            )
        agent = self.agent
        self.obs.admit_start(trace)
        eos_id = int(getattr(agent.tokenizer, "eos_id", -1))
        prompt = agent.format_prompt(question)
        tokens, lengths, _ = agent._prepare_batch([prompt])
        plen = int(lengths[0])
        row_cap = self._cache.page_table.shape[1] * self.page_size
        # One uniform static budget per pool: refuse (don't clamp) prompts
        # that cannot hold prompt + budget + the verify chunk's gamma+1
        # transient — against BOTH the table row and the model context
        # (positions past max_seq_len would feed RoPE/attention out of the
        # model's declared range; round-3 advisor finding).
        limit = min(row_cap, int(self.cfg.max_seq_len))
        if plen + self.max_new + self.gamma + 1 > limit:
            raise ValueError(
                f"prompt ({plen} tokens) + budget ({self.max_new}) + "
                f"gamma+1 ({self.gamma + 1}) exceeds the usable context "
                f"({limit}); the speculative engine keeps one uniform "
                "budget per pool"
            )
        # Worst-case pages per pool: the verify chunk transiently writes
        # gamma+1 tokens past the committed length before the rewind. (No
        # pipeline-lag margin: the spec body freezes budget-complete rows
        # itself.) Fits the table row by the admission check above.
        need = -(-(plen + self.max_new + self.gamma + 1) // self.page_size)
        cap_both = min(self.total_pages - 1, self._d_total - 1)
        if need > cap_both:
            raise ValueError(
                f"request needs {need} pages (prompt {plen} + budget "
                f"{self.max_new} + gamma overshoot); the pool holds {cap_both}"
            )
        if need > len(self._free_pages) or need > len(self._dfree):
            return False  # capacity — re-queue, admit at a later boundary

        pages = self._pop_pages(need, rid=trace.rid, tenant=trace.tenant,
                                cause="admit")
        self.mem.on_commit(trace.rid, add_tokens=plen)
        dpages = [self._dfree.pop() for _ in range(need)]
        self.dmem.on_reserve(need, rid=trace.rid, tenant=trace.tenant,
                             cause="admit", free=len(self._dfree))
        self.dmem.on_commit(trace.rid, add_tokens=plen)
        row_table = self._build_row_table([], pages)
        drow_table = self._build_row_table([], dpages)
        try:
            logits1, self._cache = _prefill_into_row(
                self.cfg, agent.params, tokens, lengths, self._cache, idx,
                row_table,
            )
            _, self._dcache = _prefill_into_row(
                agent.draft_cfg, agent.draft_params, tokens, lengths,
                self._dcache, idx, drow_table,
            )
        except Exception:
            self._reset_pool(
                RuntimeError("page pools reset after a failed speculative admission")
            )
            raise

        # First-token bootstrap: run the SAME _spec_init the standalone path
        # uses (batch-of-1, caches pass through untouched as None) so the
        # "emits the target distribution exactly" guarantee cannot drift
        # between serving and standalone speculative decoding.
        from edgemesh.runtime.speculative import _spec_init

        valid = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
        mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(tokens, valid).mask
        self._rng, r0 = jax.random.split(self._rng)
        row = _spec_init(
            agent.sampling, self.gamma, self.max_new, eos_id,
            logits1, None, None, mask1, r0,
        )
        self._pending = self._pending.at[idx].set(row.pending[0])
        self._out = self._out.at[idx].set(row.out[0])
        self._nemit = self._nemit.at[idx].set(1)
        self._conf = self._conf.at[idx].set(row.conf_sum[0])
        self._mask = self._mask.at[idx].set(row.mask[0])
        self._finished = self._finished.at[idx].set(row.finished[0])
        self.obs.admitted(trace, prompt_tokens=plen,
                          prompt_chars=len(question))
        self._slots[idx] = _Slot(
            future=fut, question=question, emitted=[], remaining=self.max_new,
            t_submit=trace.t_submit, t_start=trace.t_start, trace=trace,
            pages=pages, taken=0,
        )
        self._dslot_pages[idx] = dpages
        self._gen[idx] += 1
        self._update_page_gauges()
        if mid_flight:
            with self._cond:  # stats() reads this under the lock
                self.admitted_mid_flight += 1
        return True

    def _dispatch_segment(self, active: list[int], eos_id: int) -> _Inflight:
        from edgemesh.runtime.speculative import _SpecState

        agent = self.agent
        self._rng, seg_rng = jax.random.split(self._rng)
        state = _SpecState(
            pending=self._pending, t_cache=self._cache, d_cache=self._dcache,
            out=self._out, n_emit=self._nemit, finished=self._finished,
            mask=self._mask, rng=seg_rng, conf_sum=self._conf,
            accepted=self._acc, proposed=self._prop, rounds=self._rnds,
        )
        state = self.compute.launch(
            "spec_rounds", _spec_rounds_donated,
            self.cfg, agent.draft_cfg, agent.params, agent.draft_params,
            agent.sampling, self.gamma, self.max_new, eos_id,
            self.cfg.vocab_size, self.cap, state,
            jnp.asarray(self.rounds_per_segment, jnp.int32),
            self._verify_fn, self._spec_decode_fn,
            key=self._ck_decode,
            # Guaranteed token floor: every round emits >= 1 token per
            # active row (the verify bonus); accepted drafts only add.
            tokens=self.rounds_per_segment * max(len(active), 1),
        )
        (self._pending, self._cache, self._dcache, self._out, self._nemit,
         self._finished, self._mask, _, self._conf, self._acc, self._prop,
         self._rnds) = state
        with self._cond:  # stats() reads this under the lock
            self.segments += 1
        self.obs.segment_dispatched()
        # Detach every fetched handle from the state buffers: the NEXT
        # segment's _spec_rounds_donated donates the whole state, which
        # would delete these mid-fetch (+0 / double-not copy).
        handles = (
            state.n_emit + 0, state.out + 0, ~~state.finished,
            state.accepted + 0, state.proposed + 0, state.rounds + 0,
            self._cache.free_top + 0, self._dcache.free_top + 0,
        )
        _start_host_copy(handles)
        return _Inflight([(i, self._gen[i]) for i in active], handles)

    def _process_segment(self, seg: _Inflight, eos_id: int) -> None:
        # Already-complete handles: the successor segment is executing,
        # so this readback gates nothing.
        fetched = jax.device_get(seg.handles)  # edgelint: disable=EM114
        nemit_h, out_h, fin_h, acc_h, prop_h, rnds_h, ft_t, ft_d = fetched
        self._spec_counters_host = (int(acc_h), int(prop_h), int(rnds_h))
        # Round-structure attribution: this segment's counter deltas,
        # paired with the compute ledger's sampled launch time when this
        # segment's spec_rounds dispatch was the measured one (both run
        # on the worker, so consume_measured pairs them race-free).
        pa, pp, pr = self._spec_counters_prev
        self._spec_counters_prev = self._spec_counters_host
        self._round_ledger.on_segment(
            int(rnds_h) - pr, int(acc_h) - pa, int(prop_h) - pp,
            measured_s=self.compute.consume_measured("spec_rounds"),
        )
        self._update_spec_gauges()
        if int(ft_t) != 1 or int(ft_d) != 1:
            # Same contract as the base engine: a popped page is also on a
            # host free list → double-mapping hazard. Raise so _run resets
            # both pools AND drops the in-flight successor segment.
            raise RuntimeError(  # pragma: no cover
                "spec paged-pool tripwire: device allocator popped pages "
                f"(target free_top={int(ft_t)}, draft free_top={int(ft_d)})"
            )
        for i, gen in seg.rows:
            slot = self._slots[i]
            if not slot.active or self._gen[i] != gen:
                continue
            total = min(int(nemit_h[i]), self.max_new)
            toks = [int(t) for t in out_h[i][slot.taken : total]]
            if toks and toks[-1] == eos_id:
                toks = toks[:-1]
            slot.emitted.extend(toks)
            self.obs.tokens(slot.trace, len(toks))
            if slot.trace is not None:
                # Both pools advanced by the segment's accepted tokens.
                adv = max(0, total - slot.taken)
                self.mem.on_commit(slot.trace.rid, add_tokens=adv)
                self.dmem.on_commit(slot.trace.rid, add_tokens=adv)
            slot.taken = total
            slot.remaining = self.max_new - total
            if bool(fin_h[i]) or total >= self.max_new:
                self._retire(i)

    def _retire(self, idx: int) -> None:
        slot = self._slots[idx]
        rid = slot.trace.rid if slot.trace is not None else None
        super()._retire(idx)
        dp = self._dslot_pages.pop(idx, [])
        self._dfree.extend(dp)
        self.dmem.on_free(len(dp), rid=rid, cause="retire",
                          free=len(self._dfree))
        self.dmem.on_retired(rid)
        self._dcache = self._dcache._replace(
            page_table=self._dcache.page_table.at[idx].set(0),
            lengths=self._dcache.lengths.at[idx].set(1),
        )
        self._update_page_gauges()

    def _reset_pool(self, exc: Exception) -> None:
        super()._reset_pool(exc)
        # Every donated spec buffer may be invalid; rebuild them all (the
        # cumulative accept/propose counters reset with the pool).
        if hasattr(self, "_init_dpool"):
            self._dcache, self._dfree = _parked_pool(
                self._init_dpool, self.n_slots, self._d_total
            )
            self._dslot_pages = {}
            self.dmem.on_reset(str(exc))
            self._spec_reset_arrays()
            self._update_page_gauges()

    def stats(self) -> dict:
        out = super().stats()
        acc, prop, rnds = self._spec_counters_host
        out["gamma"] = self.gamma
        out["rounds_per_segment"] = self.rounds_per_segment
        out["spec_proposed"] = prop
        out["spec_accepted"] = acc
        out["spec_rounds"] = rnds
        out["draft_total_pages"] = self._d_total
        out["spec_round_ledger"] = self._round_ledger.summary()
        return out


def make_engine(agent, **kwargs):
    """Engine factory: a draft-carrying agent on the paged backend gets the
    speculative engine; everything else gets the plain one. (An explicit
    class choice always works too — this is the convenience entry the REST
    server uses.)"""
    if kwargs.get("tp_engine", None) is None:
        # The speculative engine (below) has no tp path; only forward the
        # kwarg when a tensor-parallel engine is actually attached.
        kwargs.pop("tp_engine", None)
    if (
        getattr(agent, "draft_cfg", None) is not None
        and kwargs.get("kv_backend", "dense") in ("paged", "paged_int8")
    ):
        return SpeculativeContinuousEngine(agent, **kwargs)
    return ContinuousEngine(agent, **kwargs)
