"""Continuous batching: requests join and leave the decode loop mid-flight.

The DynamicBatcher (serve/batcher.py) forms a batch, runs it to COMPLETION,
then forms the next — a request arriving one token after dispatch waits out
the whole previous batch. Real serving engines instead keep one resident
decode loop whose batch composition changes as requests arrive/finish
(vLLM-style continuous batching). A statically-shaped jitted TPU loop cannot
admit rows mid-program, but the segmented decode (runtime/stream.py) already
re-enters the host every ``chunk`` tokens — so edgemesh does continuous
batching at CHUNK granularity:

- A fixed pool of ``slots`` rows shares one KV cache and one compiled
  ``_decode_loop`` program (static shapes: one compile, reused forever).
- Between segments, free slots admit queued requests: the prompt prefills
  as a batch-of-1 (its own small compiled program) and its cache rows /
  logits / repetition mask SPLICE into the shared state at the slot index.
- Rows that hit EOS or their token budget retire at the segment boundary:
  their text resolves the caller's Future and the slot frees. Inactive
  slots ride along masked as ``finished`` (the loop writes nothing for
  them) — the standard static-shape tax.

Worst-case admission latency is one segment (``chunk`` tokens ≈ tens of ms)
instead of a full answer (hundreds of tokens).

Interface-compatible with DynamicBatcher (submit/answer/close/stats), so
``serve_rest`` takes either.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from functools import partial

from edgemesh.models.transformer import KVCache, forward_decode, forward_prefill, init_kv_cache
from edgemesh.ops.sampling import TokenMaskState
from edgemesh.runtime.generate import _decode_loop

log = logging.getLogger("edgemesh.serve")


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _splice_slot(
    pool_k, pool_v, pool_len, pool_logits, pool_mask, pool_finished,
    row_k, row_v, row_len, row_logits, row_mask, idx,
):
    """In-place (donated) insertion of one prefilled request into the shared
    pool state at slot ``idx`` — an eager .at[].set here would copy the whole
    multi-GB pool per admission."""
    return (
        pool_k.at[:, idx].set(row_k[:, 0]),
        pool_v.at[:, idx].set(row_v[:, 0]),
        pool_len.at[idx].set(row_len),
        pool_logits.at[idx].set(row_logits.astype(pool_logits.dtype)),
        pool_mask.at[idx].set(row_mask),
        pool_finished.at[idx].set(False),
    )


@dataclass
class _Slot:
    future: Future | None = None
    question: str = ""
    emitted: list[int] = field(default_factory=list)
    remaining: int = 0
    t_submit: float = 0.0
    t_start: float = 0.0

    @property
    def active(self) -> bool:
        return self.future is not None


class ContinuousEngine:
    """Chunk-granular continuous batcher over one Agent's model."""

    def __init__(self, agent, slots: int = 8, chunk: int = 16, idle_wait_s: float = 0.005):
        self.agent = agent
        self.cfg = agent.cfg
        self.chunk = int(chunk)
        self.n_slots = int(slots)
        if self.chunk < 1 or self.n_slots < 1:
            raise ValueError("slots and chunk must be >= 1")
        self._queue: deque[tuple[str, Future, float]] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._slots = [_Slot() for _ in range(self.n_slots)]
        cap = self.cfg.max_seq_len
        self._cache = init_kv_cache(self.cfg, self.n_slots, cap)
        # fp32, NOT activation dtype: sampling must see the same logits the
        # solo decode path sees, or bf16 rounding flips near-tied greedy
        # tokens versus agent.answer.
        self._logits = jnp.zeros((self.n_slots, self.cfg.vocab_size), jnp.float32)
        self._mask = TokenMaskState.init(self.n_slots, self.cfg.vocab_size).mask
        self._finished = jnp.ones((self.n_slots,), bool)  # all slots idle
        self._rng = jax.random.PRNGKey(agent.sampling.seed)
        # Stats for /metrics and tests.
        self.requests = 0
        self.segments = 0
        self.admitted_mid_flight = 0
        self.max_concurrent = 0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- public interface (DynamicBatcher-compatible) -----------------------

    def submit(self, question: str) -> Future:
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._queue.append((question, fut, time.perf_counter()))
            self.requests += 1
            self._cond.notify()
        return fut

    def answer(self, question: str) -> dict[str, Any]:
        return self.submit(question).result()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=10)

    def stats(self) -> dict[str, Any]:
        return {
            "requests": self.requests,
            "segments": self.segments,
            "admitted_mid_flight": self.admitted_mid_flight,
            "max_concurrent": self.max_concurrent,
            "slots": self.n_slots,
            "chunk": self.chunk,
        }

    # -- engine loop --------------------------------------------------------

    def _admit(self, idx: int, question: str, fut: Future, t_submit: float, mid_flight: bool):
        """Prefill one request and splice its state into slot ``idx``."""
        agent = self.agent
        prompt = agent.format_prompt(question)
        tokens, lengths, _ = agent._prepare_batch([prompt])
        cap = self._cache.k.shape[2]
        row_cache = init_kv_cache(self.cfg, 1, cap)
        logits1, row_cache = forward_prefill(self.cfg, agent.params, tokens, lengths, row_cache)
        valid = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
        mask1 = TokenMaskState.init(1, self.cfg.vocab_size).add_sequence(tokens, valid).mask

        k, v, ln, self._logits, self._mask, self._finished = _splice_slot(
            self._cache.k, self._cache.v, self._cache.lengths,
            self._logits, self._mask, self._finished,
            row_cache.k, row_cache.v, lengths[0], logits1[0], mask1[0],
            jnp.asarray(idx, jnp.int32),
        )
        self._cache = KVCache(k=k, v=v, lengths=ln)
        budget = int(agent.sampling.max_new_tokens)
        budget = min(budget, int(self.cfg.max_seq_len) - int(lengths[0]))
        self._slots[idx] = _Slot(
            future=fut, question=question, emitted=[], remaining=budget,
            t_submit=t_submit, t_start=time.perf_counter(),
        )
        if mid_flight:
            self.admitted_mid_flight += 1

    def _retire(self, idx: int):
        slot = self._slots[idx]
        tokenizer = self.agent.tokenizer
        text = tokenizer.decode(jnp.asarray(slot.emitted, jnp.int32)) if slot.emitted else ""
        now = time.perf_counter()
        wall = max(now - slot.t_start, 1e-9)
        slot.future.set_result(
            {
                "answer": text.strip(),
                "role": self.agent.role,
                "tps": len(slot.emitted) / wall,
                "queue_s": slot.t_start - slot.t_submit,
                "t_start": slot.t_start,
                "t_end": now,
            }
        )
        self._slots[idx] = _Slot()
        self._finished = self._finished.at[idx].set(True)

    def _run(self) -> None:
        agent = self.agent
        eos_id = int(getattr(agent.tokenizer, "eos_id", -1))
        any_active_before = False
        while True:
            # Admit as many queued requests as there are free slots.
            with self._cond:
                while not self._queue and not any(s.active for s in self._slots):
                    if self._closed:
                        return
                    self._cond.wait()
                pending: list[tuple[str, Future, float]] = []
                free = [i for i, s in enumerate(self._slots) if not s.active]
                while self._queue and free and len(pending) < len(free):
                    pending.append(self._queue.popleft())
            for (q, fut, ts), idx in zip(
                pending, [i for i, s in enumerate(self._slots) if not s.active]
            ):
                try:
                    self._admit(idx, q, fut, ts, mid_flight=any_active_before)
                except Exception as exc:
                    # Fail only THIS request: already-admitted slots keep
                    # their pending futures (poisoning them would make the
                    # later _retire set_result raise InvalidStateError and
                    # kill the worker).
                    log.exception("admission failed for %r", q[:80])
                    if not fut.done():
                        fut.set_exception(exc)

            active = [i for i, s in enumerate(self._slots) if s.active]
            self.max_concurrent = max(self.max_concurrent, len(active))
            any_active_before = bool(active)
            if not active:
                continue

            # One decode segment over the whole pool; idle rows are finished.
            # Segment length is ALWAYS ``chunk`` so _decode_loop compiles
            # exactly once; a row whose budget ends mid-segment overshoots by
            # < chunk forwards and the extras are trimmed host-side. A
            # failure anywhere in the segment must not kill the worker —
            # fail the in-flight futures, reset the pool, keep serving.
            try:
                self._rng, seg_rng = jax.random.split(self._rng)
                out, counts, self._cache, _, self._mask, prev, fin = _decode_loop(
                    self.cfg, agent.params, agent.sampling, self.chunk, eos_id,
                    self._logits, self._cache, self._mask, seg_rng, None,
                    self._finished,
                )
                self.segments += 1
                counts_h = jax.device_get(counts)
                out_h = jax.device_get(out)
                fin_h = jax.device_get(fin)
                self._finished = fin
                for i in active:
                    slot = self._slots[i]
                    n = min(int(counts_h[i]), max(slot.remaining, 0))
                    toks = [int(t) for t in out_h[i][:n]]
                    if toks and toks[-1] == eos_id:
                        toks = toks[:-1]
                    slot.emitted.extend(toks)
                    slot.remaining -= n
                    if bool(fin_h[i]) or slot.remaining <= 0:
                        self._retire(i)

                # Bridge into the next segment for rows still going (the loop
                # stops before a wasted trailing forward; run it for the batch).
                # This whole-batch step also advances lengths / writes one KV
                # row for retired and idle slots — garbage BY DESIGN: idle-slot
                # state is meaningless until _splice_slot resets lengths on
                # admission, and writes clamp at capacity. Do not read idle
                # rows' lengths as if they tracked anything.
                if any(s.active for s in self._slots):
                    logits, self._cache = forward_decode(self.cfg, agent.params, prev, self._cache)
                    self._logits = logits.astype(self._logits.dtype)
            except Exception as exc:
                log.exception("decode segment failed; failing %d in-flight requests", len(active))
                for i in active:
                    fut = self._slots[i].future
                    if fut is not None and not fut.done():
                        fut.set_exception(exc)
                    self._slots[i] = _Slot()
                self._finished = jnp.ones((self.n_slots,), bool)

            # Give stragglers a brief window to queue before the next segment
            # (they join at the boundary either way; this just batches admits).
            with self._cond:
                if not self._queue and any(s.active for s in self._slots):
                    self._cond.wait(timeout=0.001)
