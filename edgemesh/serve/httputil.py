"""Shared stdlib-HTTP handler helpers.

One implementation of JSON responses (with optional extra headers) and
hardened request-body parsing for BOTH front doors — the replica gateway
(serve/rest.py) and the fleet frontend (fleet/frontend.py) — so the
robustness contract (a client-input problem is always a structured 400,
never a 500) cannot silently diverge between them. Imports nothing beyond
the stdlib: the fleet must stay importable on hosts with no accelerator.
"""

from __future__ import annotations

import json


def send_json(handler, code: int, payload: dict,
              extra: dict | None = None) -> None:
    body = json.dumps(payload).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for k, v in (extra or {}).items():
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(body)


def send_text(handler, code: int, text: str,
              content_type: str = "text/plain; charset=utf-8") -> None:
    body = text.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


DEADLINE_HEADER = "X-Edgemesh-Deadline-S"


def read_deadline_header(handler) -> tuple[bool, float | None]:
    """Parse the propagated per-request deadline header (seconds of budget
    remaining). Returns ``(ok, seconds)`` — ``(True, None)`` when absent;
    on a malformed value the 400 has already been answered and ``ok`` is
    False. Both front doors speak this one contract: the fleet router sets
    the header on every attempt, the replica gateway refuses expired ones."""
    raw = handler.headers.get(DEADLINE_HEADER)
    if raw is None:
        return True, None
    try:
        return True, float(raw)
    except ValueError:
        send_json(handler, 400, {"error": f"malformed {DEADLINE_HEADER}"})
        return False, None


TRACE_HEADER = "X-Edgemesh-Trace"


def read_trace_header(handler):
    """Parse the propagated distributed-trace context (obs/trace.py).
    Returns a ``TraceContext`` or None; malformed values are dropped, not
    400s — tracing must never be able to fail a request. The import is
    deferred so this module keeps its stdlib-only surface for callers that
    never see the header."""
    raw = handler.headers.get(TRACE_HEADER)
    if raw is None:
        return None
    from edgemesh.obs.trace import TraceContext

    return TraceContext.parse(raw)


TENANT_HEADER = "X-Edgemesh-Tenant"


def read_tenant_header(handler) -> str | None:
    """The raw tenant identity header (load observatory / per-tenant
    telemetry — docs/OBSERVABILITY.md "The load observatory"). Returns the
    raw string or None; normalization to a BOUNDED metric label happens at
    the metric seam (``edgemesh.obs.metrics.bounded_label``, enforced by
    edgelint EM112) — never here, so span logs keep the honest value. A
    missing header is legal: untagged traffic stays single-tenant."""
    raw = handler.headers.get(TENANT_HEADER)
    if raw is None:
        return None
    raw = raw.strip()
    return raw or None


SESSION_HEADER = "X-Edgemesh-Session"


def read_session_header(handler) -> str | None:
    """The raw session identity header (multi-turn shared-prefix sessions;
    the load observatory's generator sends it, the fleet router forwards
    it). Span-record identity ONLY — it exists so ``edgemesh obs replay``
    can rebuild recorded traffic's session grouping; it must never become
    a metric label (EM112). Missing is legal: sessionless traffic replays
    with synthesized per-tenant sessions."""
    raw = handler.headers.get(SESSION_HEADER)
    if raw is None:
        return None
    raw = raw.strip()
    return raw or None


# -- KV transfer (prefill/decode disaggregation) ------------------------------
#
# Both sides of a cross-replica KV transfer speak these: the replica gateway
# serves them (serve/rest.py), the fleet router orchestrates them
# (fleet/router.py — export from a prefill-tier replica, import into a
# decode-tier one). The binary wire payload (runtime/paged_kv.py) rides the
# JSON body base64-encoded so the transfer reuses the one hardened HTTP
# contract instead of growing a second content type.

KV_EXPORT_PATH = "/kv/export"
KV_IMPORT_PATH = "/kv/import"

#: The fleet frontend's ensemble fan-out route (fleet/ensemble.py): one
#: question, N parallel QA pool branches, one refiner pass.
ENSEMBLE_PATH = "/ensemble"

#: Decoded payload size cap: a transfer bigger than this is refused with a
#: structured 400 before any base64 work lands on the heap. Generous — a
#: full-context 8B-model prefix is tens of MB — while still bounding what
#: one request can make the gateway buffer.
KV_PAYLOAD_MAX_BYTES = 1 << 30


def encode_kv_b64(buf: bytes) -> str:
    import base64

    return base64.b64encode(buf).decode("ascii")


def decode_kv_b64(text: str) -> bytes:
    """Decode a transfer payload; raises ValueError on malformed base64 or
    an oversized payload — callers answer a structured 400."""
    import base64

    if not isinstance(text, str):
        raise ValueError("'kv' must be a base64 string")
    if len(text) > (KV_PAYLOAD_MAX_BYTES // 3) * 4 + 8:
        raise ValueError("KV payload exceeds the transfer size cap")
    try:
        return base64.b64decode(text.encode("ascii"), validate=True)
    except Exception as e:
        raise ValueError(f"malformed base64 KV payload: {e}") from None


# -- The wire contract ---------------------------------------------------------
#
# ONE declaration of every HTTP route the fleet fabric speaks — replica
# gateway (serve/rest.py, server name "gateway") and fleet frontend
# (fleet/frontend.py, "frontend") — keyed by (method, path). This table is
# the protocol's source of truth, consumed from three directions:
#
# - the wire lint pass (analysis/wire.py, EM501-EM505) checks client call
#   sites and handler bodies against it statically;
# - the wire dryrun (EM506) cross-checks each server's SERVED_ROUTES
#   dispatch table against it at fast-tier speed, no sockets;
# - ``edgemesh obs routes`` renders it, so docs/FLEET.md's protocol section
#   is generated-verifiable instead of hand-maintained.
#
# Row fields (all optional; absent means "empty"):
#   servers           which front doors answer the route
#   required_headers  a fleet-side client that builds a headers dict for
#                     this route must include these, and the handler must
#                     read each via the matching ``read_*`` helper
#   forwarded_headers identity headers the handler must read (and forward)
#                     when present; clients send them opportunistically
#   strict_headers    True: a client call with NO headers mapping at all is
#                     itself a contract violation (KV transfer hops — the
#                     deadline/trace plumbing is load-bearing there)
#   request_keys      the JSON body keys the route carries (POST only)
#   error_kinds       structured ``{"kind": ...}`` error vocabulary the
#                     route can answer with (besides plain 400 ``error``)
#   prefix            True: the path is a prefix route (trailing segment
#                     varies per request, e.g. a trace id)

REPLICA_HEADER = "X-Edgemesh-Replica"
ATTEMPTS_HEADER = "X-Edgemesh-Attempts"
TIERED_HEADER = "X-Edgemesh-Tiered"
RETRY_AFTER_HEADER = "Retry-After"

WIRE_CONTRACT: dict[tuple[str, str], dict] = {
    # -- probes / introspection (no headers, no body) ------------------------
    ("GET", "/"): {"servers": ("gateway", "frontend")},
    ("GET", "/health"): {"servers": ("gateway",)},
    ("GET", "/healthz"): {"servers": ("gateway", "frontend")},
    ("GET", "/readyz"): {"servers": ("gateway", "frontend")},
    ("GET", "/loadz"): {"servers": ("gateway",)},
    ("GET", "/metrics"): {"servers": ("gateway", "frontend")},
    ("GET", "/stats"): {"servers": ("gateway",)},
    ("GET", "/statusz"): {"servers": ("gateway",)},
    ("GET", "/debug/profile"): {"servers": ("gateway",)},
    ("GET", "/fleetz"): {"servers": ("frontend",)},
    ("GET", "/debug/traces/"): {"servers": ("frontend",), "prefix": True},
    # -- inference -----------------------------------------------------------
    ("POST", "/generate"): {
        "servers": ("gateway", "frontend"),
        "required_headers": (TRACE_HEADER,),
        "forwarded_headers": (DEADLINE_HEADER, TENANT_HEADER, SESSION_HEADER),
        "request_keys": ("question", "max_new"),
        "error_kinds": ("draining", "overloaded", "deadline", "internal"),
    },
    ("POST", ENSEMBLE_PATH): {
        "servers": ("frontend",),
        "required_headers": (TRACE_HEADER,),
        "forwarded_headers": (DEADLINE_HEADER, TENANT_HEADER, SESSION_HEADER),
        "request_keys": ("question", "max_new"),
        "error_kinds": ("ensemble_failed", "overloaded", "deadline",
                        "internal"),
    },
    ("POST", "/generate_stream"): {
        "servers": ("gateway",),
        "required_headers": (TRACE_HEADER,),
        "forwarded_headers": (DEADLINE_HEADER, TENANT_HEADER, SESSION_HEADER),
        "request_keys": ("question", "max_new"),
        "error_kinds": ("draining", "overloaded", "deadline", "internal"),
    },
    ("POST", KV_EXPORT_PATH): {
        "servers": ("gateway",),
        "required_headers": (DEADLINE_HEADER, TRACE_HEADER),
        "forwarded_headers": (TENANT_HEADER, SESSION_HEADER),
        "strict_headers": True,
        "request_keys": ("question",),
        "error_kinds": ("kv_capability", "kv_wire", "draining",
                        "overloaded", "deadline", "internal"),
    },
    ("POST", KV_IMPORT_PATH): {
        "servers": ("gateway",),
        "required_headers": (DEADLINE_HEADER, TRACE_HEADER),
        "forwarded_headers": (TENANT_HEADER, SESSION_HEADER),
        "strict_headers": True,
        "request_keys": ("question", "kv", "max_new"),
        "error_kinds": ("kv_capability", "kv_wire", "draining",
                        "overloaded", "deadline", "internal"),
    },
    # -- fleet control plane -------------------------------------------------
    ("POST", "/drain"): {"servers": ("gateway",)},
    ("POST", "/incident"): {
        "servers": ("gateway",),
        "request_keys": ("id", "kind", "source"),
    },
    ("POST", "/replicas/register"): {
        "servers": ("frontend",),
        # "model" is the optional model descriptor ({"pool", "role",
        # "family", "size", ...}) that enrolls the replica in a model-keyed
        # pool (fleet/registry.py, docs/FLEET.md "Ensemble serving").
        "request_keys": ("id", "url", "model"),
    },
    ("POST", "/replicas/deregister"): {
        "servers": ("frontend",),
        "request_keys": ("id",),
    },
    ("POST", "/replicas/drain"): {
        "servers": ("frontend",),
        "request_keys": ("id",),
    },
}


def route_base(path: str) -> str:
    """The dispatchable part of a request path: the query string is per
    request, the contract speaks in bases."""
    return path.split("?", 1)[0]


def route_matches(path: str, routes: tuple[str, ...]) -> bool:
    """True when ``path`` (already a :func:`route_base`) is one of
    ``routes``. An entry other than ``"/"`` that ends with ``/`` is a
    prefix route (``/debug/traces/<id>``) and matches by prefix — same
    convention WIRE_CONTRACT marks with ``prefix: True``."""
    for r in routes:
        if r != "/" and r.endswith("/"):
            if path.startswith(r):
                return True
        elif path == r:
            return True
    return False


def contract_rows() -> list[dict]:
    """WIRE_CONTRACT flattened to sorted row dicts — the shape
    ``edgemesh obs routes --json`` prints and tests assert on."""
    rows = []
    for (method, path), row in sorted(WIRE_CONTRACT.items(),
                                      key=lambda kv: (kv[0][1], kv[0][0])):
        rows.append({
            "method": method,
            "path": path,
            "servers": list(row.get("servers", ())),
            "required_headers": list(row.get("required_headers", ())),
            "forwarded_headers": list(row.get("forwarded_headers", ())),
            "strict_headers": bool(row.get("strict_headers", False)),
            "request_keys": list(row.get("request_keys", ())),
            "error_kinds": list(row.get("error_kinds", ())),
            "prefix": bool(row.get("prefix", False)),
        })
    return rows


def read_json_body(handler) -> dict | None:
    """Parse the request body; answers the 400 itself on bad input."""
    try:
        length = int(handler.headers.get("Content-Length") or 0)
    except ValueError:
        send_json(handler, 400, {"error": "malformed Content-Length header"})
        return None
    try:
        payload = json.loads(handler.rfile.read(length) or b"{}")
    except json.JSONDecodeError:
        send_json(handler, 400, {"error": "invalid JSON body"})
        return None
    if not isinstance(payload, dict):
        send_json(handler, 400, {"error": "body must be a JSON object"})
        return None
    return payload
