"""Dynamic request batching for the serving front door.

The reference serves strictly one question at a time (each runner's loop,
and the REST PoC, ``Code/gRPC/rest_api.py:9-15``). On TPU that wastes the
decode loop's defining property: it is HBM-bandwidth-bound, so a batch of 8
concurrent requests costs barely more wall time than 1 — the weight stream
amortizes. ``DynamicBatcher`` converts concurrent REST requests into batched
``answer_batch`` calls:

- ``submit()`` enqueues a question and returns a Future.
- A worker drains the queue: while the pending set is smaller than
  ``max_batch`` it lingers up to ``max_wait_s`` (a fixed batch-formation
  window — late arrivals inside the window join THIS batch) before
  dispatching whatever is waiting. Under load, batches form naturally
  (requests that arrive mid-dispatch wait for the next batch — classic
  continuous-batching-lite without mid-flight joins, which a static-shape
  decode loop cannot accept anyway).
- Per-request order within a batch is preserved; errors fail only the
  affected batch's futures, the worker survives.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable

log = logging.getLogger("edgemesh.serve")


class DynamicBatcher:
    def __init__(
        self,
        answer_batch: Callable[[list[str]], list[dict[str, Any]]],
        max_batch: int = 8,
        max_wait_s: float = 0.02,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._answer_batch = answer_batch
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self._queue: deque[tuple[str, Future]] = deque()
        self._cond = threading.Condition()
        self._closed = False
        # Stats for /metrics and tests.
        self.requests = 0
        self.batches = 0
        self.largest_batch = 0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, question: str) -> Future:
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._queue.append((question, fut))
            self.requests += 1
            self._cond.notify()
        return fut

    def answer(self, question: str) -> dict[str, Any]:
        """Blocking drop-in for Ensemble.answer — what the REST handler calls."""
        return self.submit(question).result()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=5)

    def stats(self) -> dict[str, Any]:
        return {
            "requests": self.requests,
            "batches": self.batches,
            "largest_batch": self.largest_batch,
            "mean_batch": round(self.requests / self.batches, 2) if self.batches else 0.0,
        }

    def _take_batch(self) -> list[tuple[str, Future]]:
        with self._cond:
            while not self._queue and not self._closed:
                self._cond.wait()
            if not self._queue:
                return []
            # Linger briefly for stragglers when under-filled; requests that
            # arrive during the linger join THIS batch. (EM107: these clocks
            # are wait control flow, not a latency measurement.)
            deadline = time.monotonic() + self.max_wait_s  # edgelint: disable=EM107
            while len(self._queue) < self.max_batch:
                remaining = deadline - time.monotonic()  # edgelint: disable=EM107
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(timeout=remaining)
            batch = [
                self._queue.popleft()
                for _ in range(min(self.max_batch, len(self._queue)))
            ]
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                if self._closed:
                    return
                continue
            questions = [q for q, _ in batch]
            with self._cond:
                self.batches += 1
                self.largest_batch = max(self.largest_batch, len(batch))
            try:
                results = self._answer_batch(questions)
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"answer_batch returned {len(results)} results for "
                        f"{len(batch)} questions"
                    )
                for (_, fut), res in zip(batch, results):
                    fut.set_result(res)
            except Exception as exc:  # fail this batch only; worker survives
                log.exception("batched answer failed")
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(exc)
