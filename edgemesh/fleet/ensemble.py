"""Ensemble-over-the-fleet: parallel QA fan-out + the refiner pipeline.

The source paper's headline capability — two QA models answer independently
and a refiner merges their answers — exists in-process as
``agents/orchestrator.Ensemble`` (one host, submeshes). This module serves
the same pipeline THROUGH the fleet: ``POST /ensemble`` fans the question
out to every QA model pool in parallel (one routed branch per pool, each
with its own child trace span and the pool's own hedging/tiering via
``FleetRouter._route``), then drives the refiner pool with the candidate
answers. The refiner prompt is composed fleet-side from the SAME template
the in-process ensemble uses (``agents/prompts.py`` — reused, not forked);
refiner-pool replicas therefore serve a passthrough template so the prompt
is not wrapped twice.

Graceful degradation is a first-class state machine, not an error path:

    every branch ok, refiner ok          → outcome "ok"
    some branch failed/timed out,        → outcome "degraded_qa"
      refiner ok over the survivors        (single-candidate refine included)
    refiner failed/timed out             → outcome "refiner_fallback"
      → best QA candidate wins
    no refiner pool registered           → outcome "no_refiner"
      → best QA candidate wins
    every branch failed                  → outcome "failed" (502, the only
                                           client-visible ensemble failure)

Every outcome lands in ``edgemesh_ensemble_total{outcome}`` and on the
request's span tree (branch spans carry the pool and fate; overlapping
branch intervals are the concurrency proof ``edgemesh obs trace`` renders).

One trace record: branches share the request's span list and the request
finishes through the router's ``_finish_trace``, so cross-process assembly
sees a single router record whose children are the fan-out tree.
"""

from __future__ import annotations

import threading
import time

from edgemesh.agents.prompts import REFINER_ROLE, format_refiner_prompt
from edgemesh.obs.metrics import bounded_label
from edgemesh.obs.quality import UNIT_BUCKETS, pairwise_agreement, token_f1
from edgemesh.obs.trace import TraceContext, sample
from edgemesh.serve.httputil import RETRY_AFTER_HEADER, TRACE_HEADER

#: Terminal request outcomes — the degradation ladder, best to worst.
OUTCOMES = ("ok", "degraded_qa", "refiner_fallback", "no_refiner", "failed")


class EnsembleCoordinator:
    """Fans one question across the QA pools and refines the candidates.

    Pool discovery is live by default: every registered pool whose role is
    not ``refiner`` is a QA pool; the first refiner-role pool (sorted) is
    the refiner. Explicit ``qa_pools``/``refiner_pool`` pin the topology
    instead. A fleet with NO model descriptors degenerates to a single
    branch over the whole fleet (pool None) with no refiner — ``/ensemble``
    then behaves like ``/generate`` with ensemble accounting.
    """

    def __init__(self, router, qa_pools: list[str] | None = None,
                 refiner_pool: str | None = None,
                 qa_budget_fraction: float = 0.7,
                 low_agreement: float = 0.3,
                 obs_registry=None) -> None:
        from edgemesh.obs import get_registry

        self.router = router
        self.qa_pools = list(qa_pools) if qa_pools else None
        self.refiner_pool = refiner_pool
        # QA branches get this fraction of the request budget; the rest is
        # reserved for the refiner hop (the whole remaining budget when a
        # branch finishes early). With no refiner the branches get it all.
        self.qa_budget_fraction = float(qa_budget_fraction)
        reg = obs_registry or get_registry()
        self._total = reg.counter(
            "edgemesh_ensemble_total",
            "Ensemble requests by terminal outcome "
            "(ok/degraded_qa/refiner_fallback/no_refiner/failed — plus "
            "admission sheds as shed/ratelimited)", ("outcome",),
        )
        self._branches = reg.counter(
            "edgemesh_ensemble_branch_total",
            "QA fan-out branches by pool and fate", ("pool", "outcome"),
        )
        self._latency = reg.histogram(
            "edgemesh_ensemble_seconds",
            "End-to-end ensemble latency by terminal outcome", ("outcome",),
        )
        # The quality observatory's ensemble signals (obs/quality.py):
        # pairwise token-F1 between independent QA drafts of the SAME
        # question — a free consistency probe no single-replica signal
        # gives — and which pools were party to low-agreement requests.
        self.low_agreement = float(low_agreement)
        self._agreement = reg.histogram(
            "edgemesh_ensemble_agreement",
            "Pairwise token-F1 agreement between QA branch answers "
            "(requests with >= 2 surviving branches)", (),
            buckets=UNIT_BUCKETS,
        )
        self._low_agreement = reg.counter(
            "edgemesh_ensemble_low_agreement_total",
            "Low-agreement ensemble requests attributed to each "
            "participating QA pool", ("pool",),
        )
        self._stats_lock = threading.Lock()
        self._outcome_counts: dict[str, int] = {}  # guarded by: _stats_lock
        self._agreement_ewma: float | None = None  # guarded by: _stats_lock

    # -- topology ------------------------------------------------------------

    def topology(self) -> tuple[list[str | None], str | None]:
        """(qa_pools, refiner_pool) for this request — pinned config wins,
        else discovered from the registry's live model descriptors."""
        qa = list(self.qa_pools) if self.qa_pools else None
        refiner = self.refiner_pool
        if qa is None or refiner is None:
            pools = self.router.registry.pools()
            if qa is None:
                qa = sorted(
                    n for n, e in pools.items()
                    if e.get("role") != REFINER_ROLE
                )
            if refiner is None:
                refiners = sorted(
                    n for n, e in pools.items()
                    if e.get("role") == REFINER_ROLE
                )
                refiner = refiners[0] if refiners else None
        if not qa:
            qa = [None]
        return qa, refiner

    # -- request path --------------------------------------------------------

    def handle(self, payload, deadline_s: float | None = None,
               trace: TraceContext | None = None,
               tenant: str | None = None,
               session: str | None = None):
        """Serve one ``POST /ensemble``. Returns ``(status, body,
        headers)`` exactly like ``FleetRouter.handle_generate`` — the
        frontend writes them verbatim. One admission slot covers the whole
        fan-out: the ensemble is one request's worth of client demand, and
        admitting each branch separately would let N-pool requests starve
        single-pool tenants N-to-one."""
        router = self.router
        question = payload.get("question") if isinstance(payload, dict) else None
        if not isinstance(question, str) or not question:
            return 400, {"error": "missing question"}, {}
        label = bounded_label(tenant)
        ctx = trace or TraceContext.mint(
            sampled=sample(router.trace_sample, router._trace_rng)
        )
        spans: list[dict] = [{
            "name": "ensemble", "span_id": ctx.span_id,
            "outcome": "pending", "t0": time.time(), "t1": None,
            # Quality attrs, pre-seeded so the dict never grows while a
            # concurrent dump iterates it: cross-branch answer agreement
            # and how far the refiner moved off the best draft.
            "agreement": None, "refiner_divergence": None,
        }]
        t0 = time.monotonic()
        budget = deadline_s if deadline_s is not None else router.default_deadline_s
        verdict = router.admission.acquire(
            label, wait_s=min(router.admission_wait_s, budget)
        )
        if verdict == "ratelimited":
            self._total.labels(outcome="ratelimited").inc()
            router._tenant_ratelimited.labels(tenant=label).inc()
            router._account_tenant(label, "shed", 429, time.monotonic() - t0)
            return 429, {
                "error": "tenant rate limit exceeded", "tenant": label,
            }, {RETRY_AFTER_HEADER: "1"}
        if verdict != "ok":
            reason = "overload" if verdict == "overload" else "queue_timeout"
            self._total.labels(outcome="shed").inc()
            router._account_tenant(label, "shed", 503, time.monotonic() - t0)
            return 503, {
                "error": "router at capacity", "kind": "overloaded",
                "reason": reason,
                "max_inflight": router.admission.max_inflight,
            }, {RETRY_AFTER_HEADER: "1"}
        router._inflight_gauge.inc()
        try:
            status, body, outcome = self._fan_out(
                payload, question, t0, budget, ctx, spans,
                tenant=tenant, session=session,
            )
        finally:
            router._inflight_gauge.dec()
            router.admission.release()
        latency = time.monotonic() - t0
        self._total.labels(outcome=outcome).inc()
        self._latency.labels(outcome=outcome).observe(latency)
        with self._stats_lock:
            self._outcome_counts[outcome] = (
                self._outcome_counts.get(outcome, 0) + 1
            )
        router._account_tenant(label, outcome, status, latency)
        spans[0]["outcome"] = outcome
        headers = {TRACE_HEADER: ctx.to_header()}
        router._finish_trace(ctx, spans, status, tenant=tenant)
        return status, body, headers

    def _fan_out(self, payload, question, t0, budget, ctx, spans,
                 tenant=None, session=None):
        """The fan-out + refine pipeline under an already-acquired
        admission slot. Returns ``(status, body, outcome)``."""
        router = self.router
        qa_pools, refiner_pool = self.topology()
        deadline = t0 + budget
        qa_budget = (
            budget * self.qa_budget_fraction
            if refiner_pool is not None else budget
        )
        branch_payload = {"question": question}
        if isinstance(payload, dict) and payload.get("max_new") is not None:
            branch_payload["max_new"] = payload["max_new"]

        # One span per branch, appended with EVERY key it will ever have
        # BEFORE its thread starts (concurrent JSON dumps must never see a
        # dict growing), closed exactly once under span_lock — the worker
        # and the timeout sweep below race for it.
        span_lock = threading.Lock()
        results: list[tuple[int, dict] | None] = [None] * len(qa_pools)
        branch_spans: list[dict] = []

        def close_span(span, outcome, status=None):
            with span_lock:
                if span["outcome"] != "pending":
                    return
                span["t1"] = time.time()
                span["outcome"] = outcome
                span["status"] = status

        def run_branch(i, pool, bctx, span):
            status, body, _hdrs = router._route(
                branch_payload, t0, qa_budget, "/generate", bctx, spans,
                meta={"outcome": "shed"}, tenant=tenant, session=session,
                pool=pool,
            )
            results[i] = (status, body)  # distinct slots: no lock needed
            if status == 200 and isinstance(body, dict):
                answer = body.get("answer")
                conf = body.get("confidence")
                with span_lock:
                    if isinstance(answer, str):
                        span["answer_len"] = len(answer)
                    if isinstance(conf, (int, float)):
                        span["confidence"] = round(float(conf), 4)
            close_span(span, "ok" if status == 200 else "failed", status)

        threads = []
        for i, pool in enumerate(qa_pools):
            bctx = ctx.child()
            span = {
                "name": "branch", "span_id": bctx.span_id,
                "pool": pool, "outcome": "pending", "status": None,
                "t0": time.time(), "t1": None,
                # Quality attrs the worker fills on success (pre-seeded —
                # see the growth rule above): the draft's length and the
                # engine's device-side confidence for it.
                "answer_len": None, "confidence": None,
            }
            spans.append(span)
            branch_spans.append(span)
            th = threading.Thread(
                target=run_branch, args=(i, pool, bctx, span),
                name=f"ensemble-branch-{pool or 'fleet'}", daemon=True,
            )
            threads.append(th)
            th.start()
        qa_deadline = t0 + qa_budget
        for th in threads:
            # Small slack past the branch budget: _route answers within its
            # own deadline, so a join expiring here means a genuinely
            # wedged branch — abandon it (daemon thread) like a lost hedge.
            th.join(timeout=max(0.0, qa_deadline - time.monotonic()) + 0.25)
        for span in branch_spans:
            close_span(span, "timeout")

        candidates = []
        branches = []
        for pool, span, res in zip(qa_pools, branch_spans, results):
            pool_label = pool or "fleet"
            outcome = span["outcome"]
            status = None
            if res is not None:
                status, body = res
                if (status == 200 and isinstance(body, dict)
                        and body.get("answer") is not None):
                    candidates.append({
                        "pool": pool_label,
                        "answer": body["answer"],
                        "confidence": float(body.get("confidence") or 0.0),
                    })
                else:
                    outcome = "failed"
            self._branches.labels(pool=pool_label, outcome=outcome).inc()
            branches.append(
                {"pool": pool_label, "outcome": outcome, "status": status}
            )
        degraded = any(b["outcome"] != "ok" for b in branches)

        # Cross-branch agreement (obs/quality.py): independent drafts of
        # the SAME question disagreeing is a quality signal no single
        # replica can emit — a pool serving a corrupted checkpoint drags
        # this down while its own latency and confidence look plausible.
        agreement = pairwise_agreement(
            [c["answer"] for c in candidates if isinstance(c["answer"], str)]
        )
        if agreement is not None:
            spans[0]["agreement"] = agreement
            self._agreement.labels().observe(agreement)
            with self._stats_lock:
                prev = self._agreement_ewma
                self._agreement_ewma = (
                    agreement if prev is None
                    else round(0.2 * agreement + 0.8 * prev, 4)
                )
            if agreement < self.low_agreement:
                # Attributed to EVERY participating pool: agreement is a
                # property of the set, and which member lies is exactly
                # what the canary prober exists to disambiguate.
                for c in candidates:
                    self._low_agreement.labels(pool=c["pool"]).inc()

        if not candidates:
            # The ONLY client-visible ensemble failure: nothing to refine,
            # nothing to fall back on.
            return 502, {
                "error": "every QA branch failed", "kind": "ensemble_failed",
                "branches": branches,
            }, "failed"

        best = max(candidates, key=lambda c: c["confidence"])
        base_body = {
            "candidates": candidates, "branches": branches,
            "agreement": agreement, "refiner_divergence": None,
        }
        if refiner_pool is None:
            return 200, {
                **base_body, "answer": best["answer"],
                "confidence": best["confidence"],
                "outcome": "no_refiner", "refined": False,
            }, "no_refiner"

        # Refine over the survivors — a single-candidate refine is the
        # degraded-QA path, not an error. The refiner pool's replicas
        # serve a passthrough template, so the composed prompt (the SAME
        # agents/prompts.py template the in-process ensemble uses) rides
        # the wire as the question.
        refine_payload = {
            "question": format_refiner_prompt(
                question, [c["answer"] for c in candidates]
            ),
        }
        if "max_new" in branch_payload:
            refine_payload["max_new"] = branch_payload["max_new"]
        rctx = ctx.child()
        rspan = {
            "name": "refine", "span_id": rctx.span_id,
            "pool": refiner_pool, "outcome": "pending", "status": None,
            "t0": time.time(), "t1": None,
        }
        spans.append(rspan)
        if deadline - time.monotonic() <= 0:
            close_span(rspan, "timeout")
            return 200, {
                **base_body, "answer": best["answer"],
                "confidence": best["confidence"],
                "outcome": "refiner_fallback", "refined": False,
            }, "refiner_fallback"
        status, body, _hdrs = router._route(
            refine_payload, t0, budget, "/generate", rctx, spans,
            meta={"outcome": "shed"}, tenant=tenant, session=session,
            pool=refiner_pool,
        )
        if (status == 200 and isinstance(body, dict)
                and body.get("answer") is not None):
            close_span(rspan, "ok", status)
            outcome = "degraded_qa" if degraded else "ok"
            # How far the refiner moved off the best draft (1 - token-F1):
            # near 0 means it echoed a candidate, near 1 it went its own
            # way — either extreme sustained fleet-wide is worth a look.
            divergence = None
            if isinstance(body["answer"], str) and isinstance(
                    best["answer"], str):
                divergence = round(
                    1.0 - token_f1(body["answer"], best["answer"]), 4)
            spans[0]["refiner_divergence"] = divergence
            return 200, {
                **base_body, "answer": body["answer"],
                "confidence": float(
                    body.get("confidence") or best["confidence"]
                ),
                "outcome": outcome, "refined": True,
                "refiner_divergence": divergence,
            }, outcome
        close_span(rspan, "failed", status)
        return 200, {
            **base_body, "answer": best["answer"],
            "confidence": best["confidence"],
            "outcome": "refiner_fallback", "refined": False,
        }, "refiner_fallback"

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """The /fleetz view: live topology + per-outcome request counts."""
        qa_pools, refiner_pool = self.topology()
        with self._stats_lock:
            outcomes = dict(sorted(self._outcome_counts.items()))
            agreement = self._agreement_ewma
        return {
            "qa_pools": [p or "fleet" for p in qa_pools],
            "refiner_pool": refiner_pool,
            "qa_budget_fraction": self.qa_budget_fraction,
            "outcomes": outcomes or None,
            # None until a multi-branch request has been served — the
            # single-pool fleet has no agreement signal to report.
            "agreement_ewma": agreement,
        }
