"""Periodic health probes: active membership truth for the registry.

The prober hits each replica's ``/readyz`` (NOT ``/healthz``) on a fixed
interval: readiness is the routing question — a draining replica is alive
(``/healthz`` 200) but must leave rotation, and ``/readyz`` is the endpoint
that encodes that distinction (serve/rest.py). Probe outcomes feed the same
consecutive-failure/success accounting the router's passive checks use
(fleet/registry.py ``probe_result``):

- ``unhealthy_after`` consecutive failures demote healthy → unhealthy;
- ``healthy_after`` consecutive successes promote unhealthy → healthy —
  recovery is automatic, a restarted/un-stalled replica rejoins rotation
  without operator action;
- draining/removed replicas are still probed (their inflight count rides
  the ``/readyz`` body, which ``drain_replica`` polls) but never change
  state from here;
- the ``/readyz`` body also piggybacks the replica's **load digest**
  (queue depth, latency EWMAs, SLO goodput — serve/rest.py), which each
  probe stores via ``registry.update_load`` — the telemetry balancer's
  signal refreshes on the probe cadence with zero extra requests.

Per-replica obs: ``edgemesh_fleet_probes_total{replica,result}`` and an
``edgemesh_fleet_replica_up{replica}`` gauge (1 healthy / 0 anything else)
so a scrape shows rotation membership directly.
"""

from __future__ import annotations

import logging
import threading

from edgemesh.fleet.transport import HttpTransport, TransportError

log = logging.getLogger("edgemesh.fleet")


class HealthProber:
    """Background ``/readyz`` prober driving registry state transitions."""

    def __init__(self, registry, transport=None, interval_s: float = 2.0,
                 timeout_s: float = 1.0, unhealthy_after: int = 2,
                 healthy_after: int = 1, obs_registry=None,
                 on_incident=None, on_digest=None) -> None:
        from edgemesh.obs import get_registry

        self.registry = registry
        self.transport = transport or HttpTransport()
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.unhealthy_after = unhealthy_after
        self.healthy_after = healthy_after
        #: Called ``(rid, incident_dict)`` when a probed load digest
        #: carries an ``incident`` field (a replica's anomaly trigger
        #: fired — obs/anomaly.py). The fleet CLI wires this to
        #: ``FleetRouter.observe_incident`` so the id fans out to every
        #: replica; the callback dedupes, so re-probing the same incident
        #: on every cadence tick is free.
        self.on_incident = on_incident
        #: Called ``(rid, digest_dict)`` after every stored digest refresh.
        #: The tiered router wires this to ``FleetRouter.note_digest`` so
        #: prefill/decode tier membership re-derives from fresh phase
        #: EWMAs on the probe cadence (docs/FLEET.md "Tiered serving").
        self.on_digest = on_digest
        reg = obs_registry or get_registry()
        self._probes = reg.counter(
            "edgemesh_fleet_probes_total",
            "Health probes by replica and result", ("replica", "result"),
        )
        self._up = reg.gauge(
            "edgemesh_fleet_replica_up",
            "1 when the replica is in rotation (healthy), else 0",
            ("replica",),
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- one pass (directly callable from tests) -----------------------------

    def probe_once(self) -> dict[str, str]:
        """Probe every registered replica once; returns {rid: state}."""
        states: dict[str, str] = {}
        for rep in self.registry.replicas():
            ok, err, load = self._probe(rep)
            self._probes.labels(replica=rep.rid, result="ok" if ok else "fail").inc()
            if load is not None:
                # The digest piggybacks on the /readyz body (serve/rest.py)
                # so the telemetry balancer's signal refreshes for free on
                # the existing probe cadence — zero extra requests.
                self.registry.update_load(rep.rid, load)
                if self.on_digest is not None:
                    try:
                        self.on_digest(rep.rid, load)
                    except Exception:  # telemetry must never break probing
                        log.exception("digest callback failed for %s",
                                      rep.rid)
                incident = load.get("incident")
                if incident and self.on_incident is not None:
                    try:
                        self.on_incident(rep.rid, incident)
                    except Exception:  # propagation must never break probing
                        log.exception("incident callback failed for %s",
                                      rep.rid)
            state = self.registry.probe_result(
                rep.rid, ok, healthy_after=self.healthy_after,
                unhealthy_after=self.unhealthy_after, error=err,
            )
            if state is not None:
                states[rep.rid] = state
                self._up.labels(replica=rep.rid).set(1.0 if state == "healthy" else 0.0)
        return states

    def _probe(self, rep) -> tuple[bool, str, dict | None]:
        try:
            status, body = self.transport.get_json(
                rep.url("/readyz"), timeout_s=self.timeout_s
            )
        except TransportError as e:
            return False, str(e), None
        load = body.get("load") if isinstance(body, dict) else None
        if not isinstance(load, dict):
            load = None  # pre-digest replicas: probe still works, no telemetry
        # /readyz answers 503 while draining — alive but not routable. The
        # registry keeps its draining state either way; for healthy/unhealthy
        # replicas only a 200 counts as ready.
        return status == 200, "" if status == 200 else f"readyz status {status}", load

    # -- background loop -----------------------------------------------------

    def start(self) -> "HealthProber":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-prober", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s + self.timeout_s + 1.0)
            if t.is_alive():
                # Mid-pass on stalled replicas: keep the handle so a
                # subsequent start() cannot clear _stop under the old loop
                # and leave two probers racing the same registry.
                return
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # a probe pass must never kill the loop
                log.exception("health probe pass failed")
            self._stop.wait(self.interval_s)
