"""``edgemesh fleet`` — spawn, front, and inspect a local replica fleet.

Subcommands:

- ``serve``: spawn N local ``serve_rest`` replicas (each a full
  ``edgemesh serve`` subprocess on its own port), wait for their
  ``/readyz``, register them, start the health prober, and front them with
  the fleet router. Ctrl-C drains every replica (in-flight requests
  finish) before the subprocesses are stopped.
- ``status``: query a running fleet's ``/fleetz``; ``--json`` prints the
  raw machine-readable document (scripts parse this — the shape is
  ``{"balancer", "replicas": [...], "metrics": {...}}``), otherwise a
  human table.

The router itself never imports jax; only the replica subprocesses own
devices, so the frontend stays responsive while replicas compile/restart.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time

log = logging.getLogger("edgemesh.fleet")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="edgemesh fleet",
        description="multi-replica serving fabric: router + replica "
        "registry + health probes (docs/FLEET.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    srv = sub.add_parser("serve", help="spawn N local replicas and front them")
    srv.add_argument("--config", default=None, help="replica YAML config "
                     "(passed through to each `edgemesh serve`)")
    srv.add_argument("--replicas", type=int, default=2)
    srv.add_argument("--pool", action="append", default=[],
                     metavar="NAME=COUNT[:CONFIG]",
                     help="heterogeneous model pool, repeatable — e.g. "
                     "'--pool qa-a=2 --pool qa-b=1:other.yaml --pool "
                     "refiner=1'. Each pool spawns COUNT replicas (with "
                     "CONFIG overriding --config) registered under a model "
                     "descriptor {pool, role}; the pool named 'refiner' "
                     "takes the refiner role, everything else is a QA pool "
                     "fanned out by POST /ensemble. When given, --replicas "
                     "is ignored (docs/FLEET.md 'Ensemble serving')")
    srv.add_argument("--host", default="0.0.0.0")
    srv.add_argument("--port", type=int, default=8000, help="router port")
    srv.add_argument("--replica-port-base", type=int, default=0,
                     help="first replica port (0 = pick free ports)")
    srv.add_argument("--balancer", default="least_outstanding",
                     choices=["round_robin", "least_outstanding",
                              "prefix_affinity", "telemetry"])
    srv.add_argument("--max-attempts", type=int, default=3)
    srv.add_argument("--deadline-s", type=float, default=60.0,
                     help="default per-request deadline (clients override "
                     "via X-Edgemesh-Deadline-S)")
    srv.add_argument("--attempt-timeout-s", type=float, default=30.0)
    srv.add_argument("--hedge-after-s", type=float, default=0.0,
                     help="fixed tail-latency hedge delay (0 = off)")
    srv.add_argument("--hedge-percentile", type=float, default=0.0,
                     help="adaptive hedge at this observed-latency "
                     "percentile, e.g. 0.95 (0 = off)")
    srv.add_argument("--hedge-auto", action="store_true",
                     help="zero-config hedging: the delay auto-tunes to the "
                     "live p95 of a time-decayed latency histogram "
                     "(docs/FLEET.md 'Adaptive routing')")
    srv.add_argument("--max-inflight", type=int, default=64)
    srv.add_argument("--admission", default="static",
                     choices=["static", "auto"],
                     help="'auto' = knee-tracking admission: max_inflight "
                     "(and per-tenant rates) auto-tune toward the live "
                     "goodput-vs-load knee instead of the static "
                     "--max-inflight guess (docs/FLEET.md 'Knee-tracking "
                     "admission')")
    srv.add_argument("--admission-floor", type=int, default=2,
                     help="--admission auto: the tuner never cuts "
                     "max_inflight below this")
    srv.add_argument("--admission-ceiling", type=int, default=256,
                     help="--admission auto: the tuner never grows "
                     "max_inflight above this")
    srv.add_argument("--autoscale", action="store_true",
                     help="drive replica spawn/drain from the live load "
                     "digests (arrival rate vs capacity estimate) and "
                     "scale up on propagated incidents; spawned replicas "
                     "warm-start from --compile-cache-dir (docs/FLEET.md "
                     "'Autoscaling with warm starts')")
    srv.add_argument("--min-replicas", type=int, default=0,
                     help="--autoscale floor (default: the initial "
                     "--replicas count)")
    srv.add_argument("--max-replicas", type=int, default=0,
                     help="--autoscale ceiling (default: 2x the initial "
                     "--replicas count)")
    srv.add_argument("--autoscale-cooldown-s", type=float, default=20.0,
                     help="minimum seconds between autoscale actions")
    srv.add_argument("--compile-cache-dir", default=None,
                     help="persistent XLA compilation cache shared by "
                     "every replica spawn (passed to each `edgemesh serve` "
                     "subprocess): scale-up replicas compile from disk "
                     "hits, so cold-start-to-first-token is seconds")
    srv.add_argument("--tiered", action="store_true",
                     help="prefill/decode disaggregation: long prefills "
                     "route to prefill-tier replicas and their KV streams "
                     "to decode-tier ones (replicas must serve --continuous "
                     "--kv-backend paged; docs/FLEET.md 'Tiered serving')")
    srv.add_argument("--prefill-threshold-chars", type=int, default=512,
                     help="prompts at/above this length count as long "
                     "prefills for tiered routing")
    srv.add_argument("--tier-prefill-fraction", type=float, default=1 / 3,
                     help="share of the fleet assigned to the prefill tier "
                     "(membership itself is dynamic, digest-EWMA-driven)")
    srv.add_argument("--tenant-policy", action="append", default=[],
                     metavar="TENANT=LANE:WEIGHT[:RATE[:BURST]]",
                     help="per-tenant admission policy, repeatable — e.g. "
                     "'chat=interactive:4' (weight 4, no rate limit) or "
                     "'bulk=batch:1:5:10' (batch lane, weight 1, 5 rps, "
                     "burst 10); unknown tenants get the default policy "
                     "(docs/FLEET.md 'Admission')")
    srv.add_argument("--admission-queue-cap", type=int, default=0,
                     help="PER-TENANT admission queue slots (0 = legacy "
                     "immediate shed at capacity); >0 enables weighted-"
                     "fair queueing + priority lanes")
    srv.add_argument("--admission-wait-s", type=float, default=10.0,
                     help="max time one queued request may wait for a slot "
                     "(always also capped by the request deadline)")
    srv.add_argument("--span-log", default=None,
                     help="router span JSONL: one router_spans record per "
                     "sampled request, assembled across processes with "
                     "`edgemesh obs trace` (docs/OBSERVABILITY.md)")
    srv.add_argument("--trace-sample", type=float, default=1.0,
                     help="trace sampling rate in [0,1]: sampled-out "
                     "requests cost zero span I/O (here and on replicas) "
                     "but still count in every metric")
    srv.add_argument("--probe-interval-s", type=float, default=2.0)
    srv.add_argument("--canary", action="store_true",
                     help="start the golden-set canary prober "
                     "(fleet/canary.py) with the built-in fallback set; "
                     "implied by --canary-golden")
    srv.add_argument("--canary-golden", default=None,
                     help="golden-set JSONL path ({'question','reference'} "
                     "per line), typically pinned from a known-good "
                     "build's own answers")
    srv.add_argument("--canary-interval-s", type=float, default=30.0)
    srv.add_argument("--canary-collapse-below", type=float, default=0.2,
                     help="canary EWMA below this mints a quality_drift "
                     "incident for that replica")
    srv.add_argument("--boot-timeout-s", type=float, default=300.0,
                     help="per-replica readiness wait (first jit compile "
                     "of a real checkpoint can take minutes)")
    srv.add_argument("--replica-extra", default="",
                     help="extra args appended to each replica's `edgemesh "
                     "serve` command line, e.g. '--continuous --batch 8'")

    st = sub.add_parser("status", help="query a running fleet's /fleetz")
    st.add_argument("--url", default="http://127.0.0.1:8000")
    st.add_argument("--json", action="store_true", dest="as_json",
                    help="print the raw machine-readable /fleetz document")
    return p


def _free_ports(n: int) -> list[int]:
    """Pick n distinct free ports, holding every probe socket open until
    all are bound — releasing between picks lets the kernel hand the same
    port out twice. The remaining close→replica-bind window is unavoidable
    without `--port 0` readback; a collision surfaces as a replica crash,
    which _wait_ready reports with its exit code instead of hanging."""
    socks: list[socket.socket] = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _replica_cmd(args, port: int, config: str | None = None) -> list[str]:
    """One replica's `edgemesh serve` command line — shared by the boot
    spawn and the autoscaler's launcher so a scale-up replica is
    configured identically to the originals (including the shared
    compilation cache, which is what makes its start warm). ``config``
    overrides ``--config`` for a pool with its own model YAML."""
    cmd = [sys.executable, "-m", "edgemesh.cli", "serve", "--port", str(port)]
    config = config or args.config
    if config:
        cmd += ["--config", config]
    if getattr(args, "compile_cache_dir", None):
        cmd += ["--compile-cache-dir", args.compile_cache_dir]
    cmd += args.replica_extra.split()
    return cmd


def _parse_pools(specs: list[str]) -> list[tuple[str, int, str | None]]:
    """``NAME=COUNT[:CONFIG]`` pool specs → (name, count, config) rows.
    The pool named ``refiner`` carries the refiner role (matching
    agents/prompts.REFINER_ROLE); every other pool is a QA pool."""
    pools = []
    for spec in specs:
        name, eq, rest = spec.partition("=")
        count, _, config = rest.partition(":")
        if not name or not eq or not count.isdigit() or int(count) < 1:
            raise SystemExit(
                f"error: malformed --pool {spec!r} (want NAME=COUNT[:CONFIG])"
            )
        pools.append((name, int(count), config or None))
    return pools


def _spawn_replicas(args) -> list[tuple[str, int, subprocess.Popen, dict | None]]:
    """Spawn the fleet's replica subprocesses. Homogeneous mode
    (``--replicas N``) yields no model descriptors; ``--pool`` mode yields
    one descriptor per replica, which registration ships to the registry's
    model-keyed pools."""
    if args.pool:
        plan = []
        for name, count, config in _parse_pools(args.pool):
            role = "refiner" if name == "refiner" else "qa"
            for i in range(count):
                plan.append((f"{name}-{i}", config,
                             {"pool": name, "role": role}))
    else:
        plan = [(f"replica-{i}", None, None) for i in range(args.replicas)]
    if args.replica_port_base:
        ports = [args.replica_port_base + i for i in range(len(plan))]
    else:
        ports = _free_ports(len(plan))
    procs: list[tuple[str, int, subprocess.Popen, dict | None]] = []
    for (rid, config, model), port in zip(plan, ports):
        proc = subprocess.Popen(_replica_cmd(args, port, config=config),
                                env=os.environ.copy())
        procs.append((rid, port, proc, model))
        log.info("spawned %s on port %d (pid %d)%s", rid, port, proc.pid,
                 f" pool={model['pool']}" if model else "")
    return procs


class SubprocessLauncher:
    """The autoscaler's spawn/stop seam over real `edgemesh serve`
    subprocesses (fleet/autoscale.py documents the contract).

    ``spawn`` is NON-blocking: the subprocess starts immediately and a
    waiter thread registers it with the registry once ``/readyz`` answers,
    then fires one warmup ``/generate`` — stamping the spawn→ready and
    spawn→first-token walls into ``edgemesh_cold_start_seconds{phase}``,
    the cold-start telemetry the warm-start story is judged by. Until
    registration lands the spawn counts in ``pending()``, which the
    scaler adds to the replica bound so one slow boot cannot trigger a
    second."""

    def __init__(self, args, registry, transport, obs_registry=None,
                 boot_timeout_s: float = 300.0) -> None:
        from edgemesh.obs import get_registry

        self.args = args
        self.registry = registry
        self.transport = transport
        self.boot_timeout_s = boot_timeout_s
        self._lock = threading.Lock()
        self._n = 0  # guarded by: _lock
        self._pending = 0  # guarded by: _lock
        self.procs: dict[str, subprocess.Popen] = {}  # guarded by: _lock
        reg = obs_registry or get_registry()
        self._cold_start = reg.histogram(
            "edgemesh_cold_start_seconds",
            "Replica spawn wall time, by phase (ready = /readyz 200; "
            "first_token = warmup /generate answered)", ("phase",),
        )

    def pending(self) -> int:
        with self._lock:
            return self._pending

    def owns(self, rid: str) -> bool:
        """Scale-down eligibility: the scaler may only reap processes this
        launcher spawned — boot-time replicas belong to cmd_serve's
        lifecycle and a drain the launcher cannot follow with a stop
        would leave a zombie out of rotation."""
        with self._lock:
            return rid in self.procs

    def spawn(self) -> str:
        from edgemesh.fleet.transport import TransportError

        port = _free_ports(1)[0]
        with self._lock:
            self._n += 1
            rid = f"replica-scale-{self._n}"
            self._pending += 1
        t0 = time.monotonic()
        proc = subprocess.Popen(_replica_cmd(self.args, port),
                                env=os.environ.copy())
        with self._lock:
            self.procs[rid] = proc
        log.info("autoscale spawning %s on port %d (pid %d)", rid, port,
                 proc.pid)

        def wait_ready():
            url = f"http://127.0.0.1:{port}"
            deadline = time.monotonic() + self.boot_timeout_s
            try:
                while time.monotonic() < deadline:
                    if proc.poll() is not None:
                        log.error("%s exited rc=%s during boot", rid,
                                  proc.returncode)
                        with self._lock:
                            self.procs.pop(rid, None)
                        return
                    try:
                        status, _ = self.transport.get_json(
                            f"{url}/readyz", timeout_s=2.0)
                    except TransportError:
                        time.sleep(0.25)
                        continue
                    if status == 200:
                        break
                    time.sleep(0.25)
                else:
                    # Reap the straggler: a replica still booting past the
                    # timeout would otherwise live on unregistered — out
                    # of rotation, holding a resident model — while
                    # pending() drops and the scaler spawns another.
                    log.error("%s never became ready — stopping it", rid)
                    self.stop(rid)
                    return
                self._cold_start.labels(phase="ready").observe(
                    time.monotonic() - t0)
                # First token before rotation: the warmup pays any residual
                # compile OFF the request path, and the wall it measures IS
                # cold-start-to-first-token (docs/PERFORMANCE.md).
                try:
                    self.transport.post_json(
                        f"{url}/generate", {"question": "autoscale warmup?"},
                        timeout_s=max(60.0, self.boot_timeout_s))
                    self._cold_start.labels(phase="first_token").observe(
                        time.monotonic() - t0)
                except TransportError as e:
                    log.warning("%s warmup failed: %s", rid, e)
                self.registry.register(rid, url, pid=proc.pid)
                log.info("autoscale %s ready in %.1fs", rid,
                         time.monotonic() - t0)
            finally:
                with self._lock:
                    self._pending -= 1

        threading.Thread(target=wait_ready, name=f"spawn-{rid}",
                         daemon=True).start()
        return rid

    def stop(self, rid: str) -> None:
        with self._lock:
            proc = self.procs.pop(rid, None)
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    def stop_all(self) -> None:
        with self._lock:
            rids = list(self.procs)
        for rid in rids:
            self.stop(rid)


def _wait_ready(transport, procs, boot_timeout_s: float) -> None:
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + boot_timeout_s
    pending = {rid: port for rid, port, *_ in procs}
    by_rid = {rid: proc for rid, _, proc, *_ in procs}
    while pending and time.monotonic() < deadline:
        for rid, port in list(pending.items()):
            rc = by_rid[rid].poll()
            if rc is not None:
                # Fail fast with the real cause (bad config, port
                # collision, ...) instead of polling a dead port for the
                # whole boot timeout.
                raise RuntimeError(
                    f"{rid} exited with rc={rc} during boot — see its log "
                    "output above"
                )
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0
                )
            except TransportError:
                continue
            if status == 200:
                log.info("%s ready on port %d", rid, port)
                del pending[rid]
        if pending:
            time.sleep(0.5)
    if pending:
        raise RuntimeError(
            f"replicas never became ready within {boot_timeout_s:.0f}s: "
            f"{sorted(pending)}"
        )


def cmd_serve(args) -> int:
    from edgemesh.fleet import (
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )

    procs = _spawn_replicas(args)
    transport = HttpTransport()
    registry = ReplicaRegistry()
    router = None
    try:
        _wait_ready(transport, procs, args.boot_timeout_s)
        for rid, port, proc, model in procs:
            registry.register(rid, f"http://127.0.0.1:{port}", model=model,
                              pid=proc.pid)
        admission = None
        if args.tenant_policy or args.admission_queue_cap:
            from edgemesh.fleet.admission import AdmissionController, TenantPolicy

            policies = dict(
                TenantPolicy.parse(spec) for spec in args.tenant_policy
            )
            admission = AdmissionController(
                max_inflight=args.max_inflight, policies=policies,
                queue_cap=args.admission_queue_cap,
            )
        tier_manager = None
        if args.tiered:
            from edgemesh.fleet.balancer import TierManager

            tier_manager = TierManager(
                prefill_fraction=args.tier_prefill_fraction)
        router = FleetRouter(
            registry,
            balancer=args.balancer,
            transport=transport,
            max_attempts=args.max_attempts,
            default_deadline_s=args.deadline_s,
            attempt_timeout_s=args.attempt_timeout_s,
            hedge_after_s=args.hedge_after_s,
            hedge_percentile=args.hedge_percentile,
            hedge_auto=args.hedge_auto,
            max_inflight=args.max_inflight,
            admission=admission,
            admission_auto=args.admission == "auto",
            admission_floor=args.admission_floor,
            admission_ceiling=args.admission_ceiling,
            admission_wait_s=args.admission_wait_s,
            span_log=args.span_log,
            trace_sample=args.trace_sample,
            tiered=args.tiered,
            tier_manager=tier_manager,
            prefill_threshold_chars=args.prefill_threshold_chars,
        )
        scaler = None
        if args.autoscale:
            from edgemesh.fleet.autoscale import AutoScaler

            launcher = SubprocessLauncher(
                args, registry, transport, obs_registry=router.obs,
                boot_timeout_s=args.boot_timeout_s,
            )
            scaler = AutoScaler(
                registry, launcher, router=router,
                min_replicas=args.min_replicas or args.replicas,
                max_replicas=args.max_replicas or 2 * args.replicas,
                cooldown_s=args.autoscale_cooldown_s,
                obs_registry=router.obs,
            )
            # The router forwards propagated incidents to the scaler — the
            # scale-up-on-incident path (docs/FLEET.md "Autoscaling").
            router.autoscaler = scaler
            scaler.start()
        prober = HealthProber(registry, transport=transport,
                              interval_s=args.probe_interval_s,
                              # Replica-fired incidents (flight recorder
                              # dumps) fan out fleet-wide via the router.
                              on_incident=router.observe_incident,
                              # Fresh digests re-derive tier membership on
                              # the probe cadence (no-op untiered).
                              on_digest=router.note_digest).start()
        canary = None
        if args.canary or args.canary_golden:
            from edgemesh.fleet.canary import CanaryProber

            canary = CanaryProber(
                registry, transport=transport, router=router,
                golden_path=args.canary_golden,
                interval_s=args.canary_interval_s,
                collapse_below=args.canary_collapse_below,
                obs_registry=router.obs,
                # Canary rounds join the router's span log so `edgemesh
                # obs quality` sees the probe timeline beside the spans.
                trace_log=router._trace_log,
            ).start()
        print(
            f"edgemesh fleet: {len(procs)} replicas behind "
            f"http://{args.host}:{args.port} (balancer={args.balancer}); "
            f"`edgemesh fleet status --url http://127.0.0.1:{args.port}`",
            flush=True,
        )
        try:
            serve_fleet(router, host=args.host, port=args.port, block=True)
        except KeyboardInterrupt:
            pass
        finally:
            prober.stop()
            if canary is not None:
                canary.stop()
            if scaler is not None:
                scaler.stop()
                # Scale-up replicas drain like the originals, then stop.
                for rid in list(scaler.launcher.procs):
                    if router is not None:
                        print(f"draining {rid} ...", flush=True)
                        router.drain_replica(rid, timeout_s=30.0)
                scaler.launcher.stop_all()
        return 0
    finally:
        for rid, _, proc, _model in procs:
            if router is not None and proc.poll() is None:
                # Graceful: finish in-flight work before the process dies.
                print(f"draining {rid} ...", flush=True)
                router.drain_replica(rid, timeout_s=30.0)
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for _, _, proc, _model in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def cmd_status(url: str, as_json: bool) -> int:
    from edgemesh.fleet.transport import HttpTransport, TransportError

    try:
        status, body = HttpTransport().get_json(
            url.rstrip("/") + "/fleetz", timeout_s=5.0
        )
    except TransportError as e:
        print(f"error: fleet unreachable: {e}", file=sys.stderr)
        return 2
    if status != 200:
        print(f"error: /fleetz answered {status}: {body}", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(body, indent=2))
        return 0
    print(f"balancer: {body.get('balancer')}   "
          f"max_inflight: {body.get('max_inflight')}")
    tuner = (body.get("admission") or {}).get("tuner")
    if tuner:
        knee = tuner.get("knee") or {}
        print(f"admission: auto (limit={tuner.get('limit')} "
              f"floor={tuner.get('floor')} ceiling={tuner.get('ceiling')} "
              f"frozen={tuner.get('frozen')}) "
              f"knee={knee.get('knee_offered_rps')} rps")
    autoscale = body.get("autoscale")
    if autoscale:
        ev = autoscale.get("last_eval") or {}
        print(f"autoscale: [{autoscale.get('min_replicas')}, "
              f"{autoscale.get('max_replicas')}] "
              f"util={ev.get('utilization')} "
              f"demand={ev.get('demand_rps')} rps "
              f"supply={ev.get('supply_rps')} rps")
    print(f"{'REPLICA':<12} {'STATE':<10} {'URL':<28} "
          f"{'OUT':>4} {'ROUTED':>7} {'FAILED':>7}")
    for r in body.get("replicas", []):
        print(f"{r['id']:<12} {r['state']:<10} {r['url']:<28} "
              f"{r['outstanding']:>4} {r['total_routed']:>7} "
              f"{r['total_failures']:>7}")
    tenants = body.get("tenants") or {}
    if tenants:
        print(f"\n{'TENANT':<16} {'REQS':>6} {'GOODPUT':>8} {'SHED':>6} "
              f"{'RATELIM':>8}")
        for name, cell in tenants.items():
            gp = cell.get("goodput_ratio")
            print(f"{name:<16} {cell.get('requests', 0):>6} "
                  f"{'-' if gp is None else f'{gp:.3f}':>8} "
                  f"{cell.get('shed', 0):>6} {cell.get('ratelimited', 0):>8}")
    return 0


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    args = build_parser().parse_args(argv)
    if args.cmd == "serve":
        return cmd_serve(args)
    return cmd_status(args.url, args.as_json)


if __name__ == "__main__":
    sys.exit(main())
