"""``edgemesh fleet`` — spawn, front, and inspect a local replica fleet.

Subcommands:

- ``serve``: spawn N local ``serve_rest`` replicas (each a full
  ``edgemesh serve`` subprocess on its own port), wait for their
  ``/readyz``, register them, start the health prober, and front them with
  the fleet router. Ctrl-C drains every replica (in-flight requests
  finish) before the subprocesses are stopped.
- ``status``: query a running fleet's ``/fleetz``; ``--json`` prints the
  raw machine-readable document (scripts parse this — the shape is
  ``{"balancer", "replicas": [...], "metrics": {...}}``), otherwise a
  human table.

The router itself never imports jax; only the replica subprocesses own
devices, so the frontend stays responsive while replicas compile/restart.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import time

log = logging.getLogger("edgemesh.fleet")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="edgemesh fleet",
        description="multi-replica serving fabric: router + replica "
        "registry + health probes (docs/FLEET.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    srv = sub.add_parser("serve", help="spawn N local replicas and front them")
    srv.add_argument("--config", default=None, help="replica YAML config "
                     "(passed through to each `edgemesh serve`)")
    srv.add_argument("--replicas", type=int, default=2)
    srv.add_argument("--host", default="0.0.0.0")
    srv.add_argument("--port", type=int, default=8000, help="router port")
    srv.add_argument("--replica-port-base", type=int, default=0,
                     help="first replica port (0 = pick free ports)")
    srv.add_argument("--balancer", default="least_outstanding",
                     choices=["round_robin", "least_outstanding",
                              "prefix_affinity", "telemetry"])
    srv.add_argument("--max-attempts", type=int, default=3)
    srv.add_argument("--deadline-s", type=float, default=60.0,
                     help="default per-request deadline (clients override "
                     "via X-Edgemesh-Deadline-S)")
    srv.add_argument("--attempt-timeout-s", type=float, default=30.0)
    srv.add_argument("--hedge-after-s", type=float, default=0.0,
                     help="fixed tail-latency hedge delay (0 = off)")
    srv.add_argument("--hedge-percentile", type=float, default=0.0,
                     help="adaptive hedge at this observed-latency "
                     "percentile, e.g. 0.95 (0 = off)")
    srv.add_argument("--hedge-auto", action="store_true",
                     help="zero-config hedging: the delay auto-tunes to the "
                     "live p95 of a time-decayed latency histogram "
                     "(docs/FLEET.md 'Adaptive routing')")
    srv.add_argument("--max-inflight", type=int, default=64)
    srv.add_argument("--tiered", action="store_true",
                     help="prefill/decode disaggregation: long prefills "
                     "route to prefill-tier replicas and their KV streams "
                     "to decode-tier ones (replicas must serve --continuous "
                     "--kv-backend paged; docs/FLEET.md 'Tiered serving')")
    srv.add_argument("--prefill-threshold-chars", type=int, default=512,
                     help="prompts at/above this length count as long "
                     "prefills for tiered routing")
    srv.add_argument("--tier-prefill-fraction", type=float, default=1 / 3,
                     help="share of the fleet assigned to the prefill tier "
                     "(membership itself is dynamic, digest-EWMA-driven)")
    srv.add_argument("--tenant-policy", action="append", default=[],
                     metavar="TENANT=LANE:WEIGHT[:RATE[:BURST]]",
                     help="per-tenant admission policy, repeatable — e.g. "
                     "'chat=interactive:4' (weight 4, no rate limit) or "
                     "'bulk=batch:1:5:10' (batch lane, weight 1, 5 rps, "
                     "burst 10); unknown tenants get the default policy "
                     "(docs/FLEET.md 'Admission')")
    srv.add_argument("--admission-queue-cap", type=int, default=0,
                     help="PER-TENANT admission queue slots (0 = legacy "
                     "immediate shed at capacity); >0 enables weighted-"
                     "fair queueing + priority lanes")
    srv.add_argument("--admission-wait-s", type=float, default=10.0,
                     help="max time one queued request may wait for a slot "
                     "(always also capped by the request deadline)")
    srv.add_argument("--span-log", default=None,
                     help="router span JSONL: one router_spans record per "
                     "sampled request, assembled across processes with "
                     "`edgemesh obs trace` (docs/OBSERVABILITY.md)")
    srv.add_argument("--trace-sample", type=float, default=1.0,
                     help="trace sampling rate in [0,1]: sampled-out "
                     "requests cost zero span I/O (here and on replicas) "
                     "but still count in every metric")
    srv.add_argument("--probe-interval-s", type=float, default=2.0)
    srv.add_argument("--boot-timeout-s", type=float, default=300.0,
                     help="per-replica readiness wait (first jit compile "
                     "of a real checkpoint can take minutes)")
    srv.add_argument("--replica-extra", default="",
                     help="extra args appended to each replica's `edgemesh "
                     "serve` command line, e.g. '--continuous --batch 8'")

    st = sub.add_parser("status", help="query a running fleet's /fleetz")
    st.add_argument("--url", default="http://127.0.0.1:8000")
    st.add_argument("--json", action="store_true", dest="as_json",
                    help="print the raw machine-readable /fleetz document")
    return p


def _free_ports(n: int) -> list[int]:
    """Pick n distinct free ports, holding every probe socket open until
    all are bound — releasing between picks lets the kernel hand the same
    port out twice. The remaining close→replica-bind window is unavoidable
    without `--port 0` readback; a collision surfaces as a replica crash,
    which _wait_ready reports with its exit code instead of hanging."""
    socks: list[socket.socket] = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _spawn_replicas(args) -> list[tuple[str, int, subprocess.Popen]]:
    if args.replica_port_base:
        ports = [args.replica_port_base + i for i in range(args.replicas)]
    else:
        ports = _free_ports(args.replicas)
    procs: list[tuple[str, int, subprocess.Popen]] = []
    for i, port in enumerate(ports):
        cmd = [sys.executable, "-m", "edgemesh.cli", "serve", "--port", str(port)]
        if args.config:
            cmd += ["--config", args.config]
        cmd += args.replica_extra.split()
        proc = subprocess.Popen(cmd, env=os.environ.copy())
        procs.append((f"replica-{i}", port, proc))
        log.info("spawned %s on port %d (pid %d)", f"replica-{i}", port, proc.pid)
    return procs


def _wait_ready(transport, procs, boot_timeout_s: float) -> None:
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + boot_timeout_s
    pending = {rid: port for rid, port, _ in procs}
    by_rid = {rid: proc for rid, _, proc in procs}
    while pending and time.monotonic() < deadline:
        for rid, port in list(pending.items()):
            rc = by_rid[rid].poll()
            if rc is not None:
                # Fail fast with the real cause (bad config, port
                # collision, ...) instead of polling a dead port for the
                # whole boot timeout.
                raise RuntimeError(
                    f"{rid} exited with rc={rc} during boot — see its log "
                    "output above"
                )
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0
                )
            except TransportError:
                continue
            if status == 200:
                log.info("%s ready on port %d", rid, port)
                del pending[rid]
        if pending:
            time.sleep(0.5)
    if pending:
        raise RuntimeError(
            f"replicas never became ready within {boot_timeout_s:.0f}s: "
            f"{sorted(pending)}"
        )


def cmd_serve(args) -> int:
    from edgemesh.fleet import (
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )

    procs = _spawn_replicas(args)
    transport = HttpTransport()
    registry = ReplicaRegistry()
    router = None
    try:
        _wait_ready(transport, procs, args.boot_timeout_s)
        for rid, port, proc in procs:
            registry.register(rid, f"http://127.0.0.1:{port}", pid=proc.pid)
        admission = None
        if args.tenant_policy or args.admission_queue_cap:
            from edgemesh.fleet.admission import AdmissionController, TenantPolicy

            policies = dict(
                TenantPolicy.parse(spec) for spec in args.tenant_policy
            )
            admission = AdmissionController(
                max_inflight=args.max_inflight, policies=policies,
                queue_cap=args.admission_queue_cap,
            )
        tier_manager = None
        if args.tiered:
            from edgemesh.fleet.balancer import TierManager

            tier_manager = TierManager(
                prefill_fraction=args.tier_prefill_fraction)
        router = FleetRouter(
            registry,
            balancer=args.balancer,
            transport=transport,
            max_attempts=args.max_attempts,
            default_deadline_s=args.deadline_s,
            attempt_timeout_s=args.attempt_timeout_s,
            hedge_after_s=args.hedge_after_s,
            hedge_percentile=args.hedge_percentile,
            hedge_auto=args.hedge_auto,
            max_inflight=args.max_inflight,
            admission=admission,
            admission_wait_s=args.admission_wait_s,
            span_log=args.span_log,
            trace_sample=args.trace_sample,
            tiered=args.tiered,
            tier_manager=tier_manager,
            prefill_threshold_chars=args.prefill_threshold_chars,
        )
        prober = HealthProber(registry, transport=transport,
                              interval_s=args.probe_interval_s,
                              # Replica-fired incidents (flight recorder
                              # dumps) fan out fleet-wide via the router.
                              on_incident=router.observe_incident,
                              # Fresh digests re-derive tier membership on
                              # the probe cadence (no-op untiered).
                              on_digest=router.note_digest).start()
        print(
            f"edgemesh fleet: {len(procs)} replicas behind "
            f"http://{args.host}:{args.port} (balancer={args.balancer}); "
            f"`edgemesh fleet status --url http://127.0.0.1:{args.port}`",
            flush=True,
        )
        try:
            serve_fleet(router, host=args.host, port=args.port, block=True)
        except KeyboardInterrupt:
            pass
        finally:
            prober.stop()
        return 0
    finally:
        for rid, _, proc in procs:
            if router is not None and proc.poll() is None:
                # Graceful: finish in-flight work before the process dies.
                print(f"draining {rid} ...", flush=True)
                router.drain_replica(rid, timeout_s=30.0)
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for _, _, proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def cmd_status(url: str, as_json: bool) -> int:
    from edgemesh.fleet.transport import HttpTransport, TransportError

    try:
        status, body = HttpTransport().get_json(
            url.rstrip("/") + "/fleetz", timeout_s=5.0
        )
    except TransportError as e:
        print(f"error: fleet unreachable: {e}", file=sys.stderr)
        return 2
    if status != 200:
        print(f"error: /fleetz answered {status}: {body}", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(body, indent=2))
        return 0
    print(f"balancer: {body.get('balancer')}   "
          f"max_inflight: {body.get('max_inflight')}")
    print(f"{'REPLICA':<12} {'STATE':<10} {'URL':<28} "
          f"{'OUT':>4} {'ROUTED':>7} {'FAILED':>7}")
    for r in body.get("replicas", []):
        print(f"{r['id']:<12} {r['state']:<10} {r['url']:<28} "
              f"{r['outstanding']:>4} {r['total_routed']:>7} "
              f"{r['total_failures']:>7}")
    tenants = body.get("tenants") or {}
    if tenants:
        print(f"\n{'TENANT':<16} {'REQS':>6} {'GOODPUT':>8} {'SHED':>6} "
              f"{'RATELIM':>8}")
        for name, cell in tenants.items():
            gp = cell.get("goodput_ratio")
            print(f"{name:<16} {cell.get('requests', 0):>6} "
                  f"{'-' if gp is None else f'{gp:.3f}':>8} "
                  f"{cell.get('shed', 0):>6} {cell.get('ratelimited', 0):>8}")
    return 0


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    args = build_parser().parse_args(argv)
    if args.cmd == "serve":
        return cmd_serve(args)
    return cmd_status(args.url, args.as_json)


if __name__ == "__main__":
    sys.exit(main())
