"""HTTP transport for the fleet: every call carries an explicit timeout.

One thin seam between the router/prober and the network, for two reasons:

- **Fault semantics.** HTTP status codes are *answers* (a replica's 400 is
  the client's 400; its 503 is load-shed signal) and come back as values;
  only transport-level failures — refused connections, resets, timeouts,
  DNS — raise :class:`TransportError`, which is the router's retry
  trigger. Collapsing both into exceptions (urllib's default) would make
  the retry loop re-send requests a replica already answered.
- **Testability.** Fast-tier tests swap in a fake with the same two
  methods and script failures without sockets (tests/test_fleet.py).

Timeouts are mandatory by construction (no default-None parameter exists)
and enforced by lint: the wire pass (EM502) flags any bare outbound call inside
``edgemesh/fleet/`` — a stalled replica must cost one bounded attempt,
never a pinned router thread. Caveat: urllib's timeout is per socket
operation, not per request — a replica trickling one byte per read never
trips it. The router layers the request DEADLINE on top (hedge waits and
result drains are deadline-capped) so even a trickling replica cannot
hold a client past its budget.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request


class TransportError(RuntimeError):
    """Connect/read-level failure (retryable); HTTP statuses are returned,
    not raised."""


def _parse_body(raw: bytes) -> dict:
    try:
        payload = json.loads(raw or b"{}")
    except json.JSONDecodeError:
        return {"raw": raw.decode("utf-8", "replace")}
    return payload if isinstance(payload, dict) else {"raw": payload}


class HttpTransport:
    """stdlib-urllib JSON transport (zero extra dependencies, like rest.py)."""

    def get_json(self, url: str, timeout_s: float,
                 headers: dict | None = None) -> tuple[int, dict]:
        req = urllib.request.Request(url, headers=dict(headers or {}))
        return self._run(req, timeout_s)

    def post_json(self, url: str, payload: dict, timeout_s: float,
                  headers: dict | None = None) -> tuple[int, dict]:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        return self._run(req, timeout_s)

    @staticmethod
    def _run(req: urllib.request.Request, timeout_s: float) -> tuple[int, dict]:
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return resp.status, _parse_body(resp.read())
        except urllib.error.HTTPError as e:
            # A status line made it back: that IS the replica's answer.
            return e.code, _parse_body(e.read())
        except (urllib.error.URLError, TimeoutError, ConnectionError, OSError) as e:
            reason = getattr(e, "reason", None) or e
            raise TransportError(f"{req.full_url}: {reason}") from e
