"""Fleet HTTP frontend: the one listener clients talk to.

Same stdlib ``ThreadingHTTPServer`` shape as the replica gateway
(serve/rest.py) — zero extra dependencies, one thread per request — but
every request is answered by the router, never by a local model:

- ``GET  /``, ``/healthz``  → router liveness
- ``GET  /readyz``          → 200 only while ≥1 replica is in rotation
- ``GET  /fleetz``          → JSON fleet status (replicas, balancer,
  per-replica counters, recent-trace summaries, recent replica-fired
  incidents) — what ``edgemesh fleet status --json`` prints
- ``GET  /debug/traces/<id>`` → one recent request's assembled trace
  (router-side view; unique id prefixes accepted; cross-process assembly
  with replica spans is ``edgemesh obs trace``)
- ``GET  /metrics``         → Prometheus text exposition of the router's
  obs registry (routed/retried/hedged/shed counters, latency histogram)
- ``POST /generate``        → routed to a replica (retries/hedging/drain
  semantics in fleet/router.py); optional ``X-Edgemesh-Deadline-S`` header
  caps this request's total budget; optional ``X-Edgemesh-Trace`` joins a
  client trace, and the response always carries the trace id back;
  optional ``X-Edgemesh-Tenant`` selects the admission policy (rate
  limits, fairness weight, priority lane — fleet/admission.py) and labels
  the per-tenant counters ``/fleetz`` summarizes
- ``POST /ensemble``        → parallel QA fan-out across the model pools +
  the refiner pipeline (fleet/ensemble.py), with graceful degradation —
  same deadline/trace/tenant/session header plumbing as ``/generate``
- ``POST /replicas/register``   {"id": ..., "url": ..., "model": {...}?}
  — the optional model descriptor enrolls the replica in a model pool
- ``POST /replicas/deregister`` {"id": ...}
- ``POST /replicas/drain``      {"id": ...} → graceful drain (blocks until
  drained or the drain timeout; the threaded server keeps routing)
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from edgemesh.serve import httputil

log = logging.getLogger("edgemesh.fleet")

#: Every route this frontend answers, by method — consulted for the
#: unknown-path 404 and cross-checked against ``httputil.WIRE_CONTRACT``
#: by the wire dryrun (analysis/wire.py, EM506). The trailing-``/`` entry
#: is a prefix route: ``/debug/traces/<id>``.
SERVED_ROUTES: dict[str, tuple[str, ...]] = {
    "GET": ("/", "/healthz", "/readyz", "/fleetz", "/metrics",
            "/debug/traces/"),
    "POST": ("/generate", "/ensemble", "/replicas/register",
             "/replicas/deregister", "/replicas/drain"),
}


def _make_handler(router, request_timeout_s: float | None):
    class Handler(BaseHTTPRequestHandler):
        # Per-connection socket timeout (StreamRequestHandler.setup applies
        # it): a stalled client costs one bounded read, not a pinned thread.
        timeout = request_timeout_s

        def _send(self, code: int, payload: dict, extra: dict | None = None):
            httputil.send_json(self, code, payload, extra=extra)

        def _send_text(self, code: int, text: str, content_type: str):
            httputil.send_text(self, code, text, content_type=content_type)

        def do_GET(self):
            # Unknown paths 404 through the declared dispatch table (the
            # wire dryrun's inventory) — same shape as serve/rest.py.
            if not httputil.route_matches(httputil.route_base(self.path),
                                          SERVED_ROUTES["GET"]):
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            if self.path in ("/", "/healthz"):
                self._send(200, {"status": "ok", "service": "edgemesh-fleet"})
            elif self.path == "/readyz":
                n = len(router.registry.available())
                self._send(200 if n else 503, {"ready": n > 0, "available": n})
            elif self.path == "/fleetz":
                self._send(200, router.status())
            elif self.path == "/metrics":
                self._send_text(
                    200, router.obs.render(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )
            elif self.path.startswith("/debug/traces/"):
                trace_id = self.path.removeprefix("/debug/traces/").strip("/")
                doc = router.get_trace(trace_id) if trace_id else None
                if doc is None:
                    self._send(404, {
                        "error": f"no recent sampled trace {trace_id!r} "
                        "(router-side ring holds the last 64; full "
                        "cross-process assembly: `edgemesh obs trace`)",
                    })
                else:
                    self._send(200, doc)
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def _read_json(self) -> dict | None:
            """Parse the request body; answers the 400 itself on bad input
            (shared with the replica gateway via serve/httputil.py)."""
            return httputil.read_json_body(self)

        def do_POST(self):
            try:
                if not httputil.route_matches(
                        httputil.route_base(self.path),
                        SERVED_ROUTES["POST"]):
                    self._send(404, {"error": f"unknown path {self.path}"})
                    return
                if self.path == "/generate":
                    payload = self._read_json()
                    if payload is None:
                        return
                    ok, deadline_s = httputil.read_deadline_header(self)
                    if not ok:
                        return
                    status, body, extra = router.handle_generate(
                        payload, deadline_s=deadline_s,
                        # A client-supplied trace context joins its trace;
                        # otherwise the router mints one. Either way the
                        # response carries X-Edgemesh-Trace back.
                        trace=httputil.read_trace_header(self),
                        # Tenant identity: admission policy + per-tenant
                        # telemetry (docs/FLEET.md "Admission").
                        tenant=httputil.read_tenant_header(self),
                        # Session identity: span-record-only (replay
                        # grouping); forwarded to the replica verbatim.
                        session=httputil.read_session_header(self),
                    )
                    self._send(status, body, extra=extra)
                elif self.path == "/ensemble":
                    # Parallel QA fan-out + refiner pipeline over the model
                    # pools (fleet/ensemble.py) — same header plumbing as
                    # /generate, one admission slot for the whole fan-out.
                    payload = self._read_json()
                    if payload is None:
                        return
                    ok, deadline_s = httputil.read_deadline_header(self)
                    if not ok:
                        return
                    status, body, extra = router.ensemble.handle(
                        payload, deadline_s=deadline_s,
                        trace=httputil.read_trace_header(self),
                        tenant=httputil.read_tenant_header(self),
                        session=httputil.read_session_header(self),
                    )
                    self._send(status, body, extra=extra)
                elif self.path in ("/replicas/register", "/replicas/deregister",
                                   "/replicas/drain"):
                    payload = self._read_json()
                    if payload is None:
                        return
                    self._admin(payload)
                else:
                    self._send(404, {"error": f"unknown path {self.path}"})
            except TimeoutError:  # stalled client: drop, don't pin the thread
                log.warning("client socket timeout on %s", self.path)
                self.close_connection = True
            except Exception as exc:  # the frontend must survive bad requests
                log.exception("fleet frontend request failed")
                try:
                    self._send(500, {"error": str(exc), "kind": "internal"})
                except OSError:
                    pass

        def _admin(self, payload: dict):
            rid = payload.get("id")
            if not rid:
                self._send(400, {"error": "missing 'id' field"})
                return
            if self.path == "/replicas/register":
                url = payload.get("url")
                if not url:
                    self._send(400, {"error": "missing 'url' field"})
                    return
                # The optional model descriptor ({"pool", "role", ...})
                # enrolls the replica in a model-keyed pool; absent, the
                # replica serves the homogeneous fleet (docs/FLEET.md
                # "Ensemble serving").
                model = payload.get("model")
                router.registry.register(
                    rid, url, model=model if isinstance(model, dict) else None,
                )
                self._send(200, {"registered": rid, "url": url})
            elif self.path == "/replicas/deregister":
                # Through the router, not the bare registry: forget_replica
                # also purges the dead replica's tier membership and
                # incident bookkeeping — a plain pop left those behind.
                self._send(200, {"deregistered": router.forget_replica(rid)})
            else:  # /replicas/drain
                self._send(200, router.drain_replica(rid))

        def log_message(self, fmt, *args):
            log.info("%s %s", self.address_string(), fmt % args)

    return Handler


def serve_fleet(router, host: str = "0.0.0.0", port: int = 8000,
                block: bool = True, request_timeout_s: float | None = 300.0):
    """Start the fleet frontend. ``srv.router`` exposes the router for
    lifecycle management; non-blocking mode returns the live server (same
    contract as serve_rest)."""
    server = ThreadingHTTPServer((host, port), _make_handler(router, request_timeout_s))
    server.router = router
    log.info("edgemesh fleet frontend on %s:%d", host, port)
    if block:
        server.serve_forever()
        return server
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
