"""Multi-tenant admission: rate limits, weighted fairness, priority lanes.

The open-loop load observatory (edgemesh/loadgen/) exposes exactly what a
bounded-semaphore admission gate cannot express: one abusive batch tenant
flooding the frontend starves every compliant interactive tenant long
before the fleet itself saturates, because FIFO slot checkout serves
whoever arrives most often. This module is the router-side answer
(docs/FLEET.md "Admission: rate limits, weighted fairness, priority
lanes"):

- **Per-tenant token buckets** (:class:`TokenBucket`): a tenant past its
  configured rate is refused with 429 before it costs a slot — the only
  admission verdict that consumes zero fleet capacity.
- **Weighted-fair queueing** across tenants (start-time fair queueing):
  when the in-flight slot pool is full, requests wait in per-tenant FIFO
  queues and freed slots are granted to the backlogged tenant with the
  lowest virtual time; each grant advances that tenant's virtual time by
  ``1/weight``, so long-run slot shares converge to the weight ratio no
  matter how asymmetric the offered load is.
- **Priority lanes**: ``interactive`` beats ``batch`` at every grant — an
  arriving interactive request preempts queued batch work in the ADMISSION
  queue, never mid-flight (a granted slot is never revoked; latency-sensitive
  work jumps the queue, it does not kill running requests).

Default construction (no policies, ``queue_cap=0``) reproduces the
pre-admission router exactly: non-blocking slot checkout, immediate shed at
``max_inflight`` — so single-tenant deployments keep their semantics and
their metrics byte-for-byte.

No jax imports (the router-stack contract); every clock is injectable so
tests pin bucket refill and fairness deterministically.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

LANES = ("interactive", "batch")


class TokenBucket:
    """Classic token bucket: ``rate_per_s`` sustained, ``burst`` peak.

    ``try_take`` is non-blocking — admission answers 429 immediately
    instead of queueing rate-limited work (a queue in front of a rate
    limit is just a slower rate limit with worse latency)."""

    def __init__(self, rate_per_s: float, burst: float | None = None,
                 now=time.monotonic) -> None:
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst) if burst is not None else max(1.0, rate_per_s)
        self._now = now
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = now()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            t = self._now()
            self._tokens = min(
                self.burst, self._tokens + (t - self._last) * self.rate_per_s
            )
            self._last = t
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def tokens(self) -> float:
        with self._lock:
            t = self._now()
            return min(self.burst,
                       self._tokens + (t - self._last) * self.rate_per_s)

    def rescale(self, rate_per_s: float, burst: float | None = None) -> None:
        """Retune the bucket IN PLACE, preserving the current token level
        (clamped to the new burst). The knee tracker retunes on every
        control action — rebuilding the bucket would refund a full burst
        each time, which under a steady tuning ramp disables the rate
        limit entirely."""
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        with self._lock:
            t = self._now()
            self._tokens = min(
                self.burst, self._tokens + (t - self._last) * self.rate_per_s
            )
            self._last = t
            self.rate_per_s = float(rate_per_s)
            self.burst = (
                float(burst) if burst is not None else max(1.0, rate_per_s)
            )
            self._tokens = min(self._tokens, self.burst)


@dataclass(frozen=True)
class TenantPolicy:
    """One tenant's admission contract. ``rate_per_s=0`` means unlimited
    (the bucket is never built); ``weight`` is the fair-share ratio under
    contention; ``lane`` picks the priority class."""

    rate_per_s: float = 0.0
    burst: float | None = None
    weight: float = 1.0
    lane: str = "interactive"

    def __post_init__(self) -> None:
        if self.lane not in LANES:
            raise ValueError(f"lane must be one of {LANES}, got {self.lane!r}")
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")

    @classmethod
    def parse(cls, spec: str) -> tuple[str, "TenantPolicy"]:
        """Parse one ``tenant=lane:weight[:rate[:burst]]`` CLI spec, e.g.
        ``bulk=batch:1:5`` (batch lane, weight 1, 5 rps) or
        ``chat=interactive:4`` (interactive, weight 4, unlimited)."""
        name, _, rest = spec.partition("=")
        if not name or not rest:
            raise ValueError(
                f"bad tenant policy {spec!r} (want tenant=lane:weight[:rate[:burst]])"
            )
        parts = rest.split(":")
        lane = parts[0] or "interactive"
        weight = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
        rate = float(parts[2]) if len(parts) > 2 and parts[2] else 0.0
        burst = float(parts[3]) if len(parts) > 3 and parts[3] else None
        return name, cls(rate_per_s=rate, burst=burst, weight=weight, lane=lane)


@dataclass
class _Waiter:
    """One queued admission request; granted under the controller lock."""

    tenant: str
    lane: str
    granted: bool = False
    abandoned: bool = False
    enq_t: float = field(default=0.0)


class AdmissionController:
    """Slot pool + per-tenant rate limits + weighted-fair, two-lane queue.

    ``acquire(tenant, wait_s)`` returns one of:

    - ``"ok"``          — a slot is checked out; pair with :meth:`release`.
    - ``"ratelimited"`` — the tenant's token bucket is empty (429).
    - ``"overload"``    — pool full and no queue budget (the PER-TENANT
      ``queue_cap`` hit, or ``wait_s`` ≤ 0) — the legacy shed verdict. The
      cap is per tenant by design: a flooding tenant filling a shared
      queue would lock everyone else out at the door.
    - ``"queue_timeout"`` — queued but no slot freed within ``wait_s``.

    Fairness state is start-time fair queueing: per-tenant virtual time,
    advanced ``1/weight`` per grant, re-synced to the global floor when an
    idle tenant returns (an hour of idleness must not bank an hour of
    burst credit)."""

    def __init__(self, max_inflight: int = 64,
                 policies: dict[str, TenantPolicy] | None = None,
                 default_policy: TenantPolicy | None = None,
                 queue_cap: int = 0,
                 mem_horizon_s: float | None = None,
                 now=time.monotonic) -> None:
        from edgemesh.obs.metrics import bounded_label

        self.max_inflight = int(max_inflight)
        # Policy keys are normalized through the SAME bounded_label the
        # router normalizes incoming tenants through — and doing it at
        # construction pre-seeds the label namespace, so a configured
        # tenant can never collapse into the 'other' overflow bucket and
        # silently lose its rate limit / weight / lane to a flood of
        # client-minted ids arriving first.
        self.policies = {
            bounded_label(name): pol for name, pol in (policies or {}).items()
        }
        self.default_policy = default_policy or TenantPolicy()
        self.queue_cap = int(queue_cap)
        self._now = now
        self._cond = threading.Condition()
        self._inflight = 0
        # Knee-tracker seam (fleet/autotune.py): configured tenant rates
        # scale with the tuned limit so a measured-down fleet tightens
        # every bucket proportionally. 1.0 = rates as configured.
        self._rate_scale = 1.0  # guarded by: _cond
        self._buckets: dict[str, TokenBucket] = {}
        self._vtime: dict[str, float] = {}
        self._queues: dict[str, deque[_Waiter]] = {}
        self._waiting = 0
        self._ratelimit_hits: dict[str, int] = {}
        self._queue_timeouts: dict[str, int] = {}
        # Exhaustion-aware admission (docs/FLEET.md): when any routable
        # replica's pool-exhaustion forecast (obs/memory.py, riding the
        # load digest's ``mem`` block) drops below this horizon, batch-lane
        # admissions defer — queued behind interactive work, never granted
        # while the forecast stays short — so bulk tenants cannot wedge
        # the page pool that interactive traffic needs to keep flowing.
        # 0 disables (the default: single-replica deployments without the
        # digest feed keep legacy verdicts byte-for-byte).
        if mem_horizon_s is None:
            mem_horizon_s = float(
                os.environ.get("EDGEMESH_ADMIT_MEM_HORIZON_S", "0") or 0
            )
        self.mem_horizon_s = max(0.0, float(mem_horizon_s))
        self._mem_forecast: dict[str, float] = {}  # guarded by: _cond
        self._mem_deferrals = 0  # guarded by: _cond

    def policy_for(self, tenant: str) -> TenantPolicy:
        return self.policies.get(tenant, self.default_policy)

    def _bucket_for(self, tenant: str) -> TokenBucket | None:
        pol = self.policy_for(tenant)
        if pol.rate_per_s <= 0:
            return None
        with self._cond:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                scale = self._rate_scale
                bucket = self._buckets[tenant] = TokenBucket(
                    pol.rate_per_s * scale,
                    None if pol.burst is None else pol.burst * scale,
                    now=self._now,
                )
        return bucket

    # -- knee-tracker seams (fleet/autotune.py) -------------------------------

    def set_max_inflight(self, n: int) -> None:
        """Retune the slot pool live. Growing it immediately grants queued
        waiters (the freed-capacity path); shrinking it never revokes a
        granted slot — in-flight work finishes, and the pool drains down to
        the new bound as requests release."""
        with self._cond:
            self.max_inflight = max(1, int(n))
            self._grant_locked()

    def set_rate_scale(self, scale: float) -> None:
        """Scale every configured tenant rate by ``scale`` (1.0 = as
        configured). Existing buckets rescale IN PLACE — their current
        token level survives (clamped to the new burst), so a tuner
        adjusting every window cannot refund anyone a fresh burst per
        action. Unlimited tenants (rate 0) stay unlimited."""
        scale = max(1e-6, float(scale))
        with self._cond:
            if scale == self._rate_scale:
                return
            self._rate_scale = scale
            for tenant, bucket in self._buckets.items():
                pol = self.policy_for(tenant)
                bucket.rescale(
                    pol.rate_per_s * scale,
                    None if pol.burst is None else pol.burst * scale,
                )

    # -- memory-observatory seam (obs/memory.py → load digest ``mem``) -------

    def note_mem_forecast(self, load: dict | None,
                          replica: str = "default") -> None:
        """Feed one replica's load digest. Reads ``mem.forecast_s`` (the
        pool time-to-empty from :meth:`PoolLedger.digest_mem`); a digest
        without a usable forecast CLEARS the replica's entry — stale
        pressure from a replica that stopped reporting must not defer
        batch work forever. Waking the queue on every update lets deferred
        batch waiters proceed the moment the forecast recovers."""
        forecast = None
        mem = (load or {}).get("mem")
        if isinstance(mem, dict):
            raw = mem.get("forecast_s")
            if isinstance(raw, (int, float)) and raw >= 0:
                forecast = float(raw)
        with self._cond:
            if forecast is None:
                self._mem_forecast.pop(replica, None)
            else:
                self._mem_forecast[replica] = forecast
            self._grant_locked()

    def _mem_pressure_locked(self) -> bool:  # guarded by: _cond
        if self.mem_horizon_s <= 0 or not self._mem_forecast:
            return False
        return min(self._mem_forecast.values()) < self.mem_horizon_s

    # -- the admission verdict ----------------------------------------------

    def acquire(self, tenant: str = "default", wait_s: float = 0.0) -> str:
        bucket = self._bucket_for(tenant)
        if bucket is not None and not bucket.try_take():
            with self._cond:
                self._ratelimit_hits[tenant] = (
                    self._ratelimit_hits.get(tenant, 0) + 1
                )
            return "ratelimited"
        pol = self.policy_for(tenant)
        with self._cond:
            # Fast path: free capacity and nobody queued ahead — grant
            # without touching fairness state (the uncontended case must
            # stay as cheap as the old semaphore). Batch work under memory
            # pressure skips the fast path and defers into the queue: a
            # granted slot is a promise of pool pages the exhaustion
            # forecast says the fleet is about to run out of.
            deferred = pol.lane == "batch" and self._mem_pressure_locked()
            if deferred:
                self._mem_deferrals += 1
            if not deferred and self._inflight < self.max_inflight \
                    and self._waiting == 0:
                self._inflight += 1
                return "ok"
            # queue_cap is PER TENANT, not global: a flooding tenant
            # filling a shared queue would lock every other tenant out at
            # the door — exactly the starvation the queue exists to
            # prevent. Each tenant gets its own bounded backlog.
            q = self._queues.setdefault(tenant, deque())
            if self.queue_cap <= 0 or wait_s <= 0 or \
                    sum(1 for w in q if not w.abandoned) >= self.queue_cap:
                return "overload"
            waiter = _Waiter(tenant=tenant, lane=pol.lane, enq_t=self._now())
            q.append(waiter)
            self._waiting += 1
            # An idle tenant re-enters at the current floor: fairness is
            # about SHARES under contention, not banked idle credit.
            floor = min(self._vtime.values()) if self._vtime else 0.0
            self._vtime[tenant] = max(self._vtime.get(tenant, 0.0), floor)
            self._grant_locked()
            deadline = self._now() + wait_s
            while not waiter.granted:
                remaining = deadline - self._now()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    if waiter.granted:  # granted in the race with timeout
                        break
                    waiter.abandoned = True
                    self._waiting -= 1
                    self._queue_timeouts[tenant] = (
                        self._queue_timeouts.get(tenant, 0) + 1
                    )
                    return "queue_timeout"
            return "ok"

    def release(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._grant_locked()
            self._cond.notify_all()

    def _grant_locked(self) -> None:  # guarded by: _cond
        """Hand free slots to queued waiters: interactive lane strictly
        before batch, then lowest virtual time among backlogged tenants
        (ties: tenant name, for determinism). Abandoned waiters (queue
        timeouts) are garbage-collected as their queue head surfaces."""
        while self._inflight < self.max_inflight and self._waiting > 0:
            chosen: str | None = None
            for lane in LANES:
                # Deferral: batch grants pause while any replica's pool
                # forecast is under the horizon; interactive grants (and
                # queue-timeout expiry on the waiters themselves) proceed.
                if lane == "batch" and self._mem_pressure_locked():
                    continue
                backlog = []
                for tenant, q in self._queues.items():
                    while q and q[0].abandoned:
                        q.popleft()
                    if q and q[0].lane == lane:
                        backlog.append((self._vtime.get(tenant, 0.0), tenant))
                if backlog:
                    chosen = min(backlog)[1]
                    break
            if chosen is None:
                # Only abandoned entries remained; queues are now clean.
                break
            waiter = self._queues[chosen].popleft()
            waiter.granted = True
            self._inflight += 1
            self._waiting -= 1
            self._vtime[chosen] = (
                self._vtime.get(chosen, 0.0)
                + 1.0 / self.policy_for(chosen).weight
            )
        self._cond.notify_all()

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """Live admission state for ``/fleetz``: slot occupancy, queue
        depth per tenant, rate-limit / queue-timeout hit counts, and the
        configured policies."""
        with self._cond:
            return {
                "max_inflight": self.max_inflight,
                "inflight": self._inflight,
                "rate_scale": round(self._rate_scale, 4),
                "queue_cap": self.queue_cap,
                "waiting": {
                    t: sum(1 for w in q if not w.abandoned)
                    for t, q in self._queues.items()
                    if any(not w.abandoned for w in q)
                },
                "ratelimit_hits": dict(self._ratelimit_hits),
                "queue_timeouts": dict(self._queue_timeouts),
                "mem_horizon_s": self.mem_horizon_s,
                "mem_forecast_s": (
                    round(min(self._mem_forecast.values()), 3)
                    if self._mem_forecast else None
                ),
                "mem_deferrals": self._mem_deferrals,
                "policies": {
                    t: {"lane": p.lane, "weight": p.weight,
                        "rate_per_s": p.rate_per_s}
                    for t, p in sorted(self.policies.items())
                },
            }
