"""FleetRouter — the request path that makes multiple replicas one service.

TPI-LLM and the profiling-driven edge-inference line both land on the same
conclusion: once more than one serving unit exists, the router layer — not
the kernels — owns tail latency. This router gives the request path real
robustness semantics on top of the replica registry:

- **Deadlines.** Every request carries a deadline (client-supplied or
  ``default_deadline_s``); the remaining budget is propagated to replicas
  as ``X-Edgemesh-Deadline-S`` (serve/rest.py refuses expired work with a
  504) and bounds every per-attempt timeout, backoff sleep, and hedge wait
  — the router can never spend longer on a request than the client asked.
- **Bounded retries.** Transport failures and replica 5xx are retried up
  to ``max_attempts`` times with jittered exponential backoff
  (``backoff_base_s * 2^attempt``, capped, +0..jitter fraction — the
  standard thundering-herd dampener), each retry on a *different* replica
  (failed ones are excluded; exclusions reset only when every replica has
  failed once). 4xx are the client's problem and return immediately.
- **Hedging.** With ``hedge_after_s`` (fixed), ``hedge_percentile``
  (rolling window of observed attempt latencies), or ``hedge_auto`` (the
  zero-config mode: the live p95 of a time-DECAYED latency histogram,
  obs/slo.DecayingQuantile, floored at ``hedge_floor_s``), an attempt
  that outlives the hedge delay gets a second attempt fired at another
  replica; first good answer wins, the loser is abandoned. This converts
  a stalled replica's tail into one extra request of load.
- **Admission control.** A bounded in-flight slot pool fronted by the
  multi-tenant admission controller (fleet/admission.py): per-tenant
  token-bucket rate limits (429 before any slot is spent), weighted-fair
  queueing across tenants and interactive-over-batch priority lanes when
  ``queue_cap`` > 0, and past capacity the router sheds with 503 +
  ``Retry-After`` instead of queueing unboundedly — overload stays
  visible at the edge. Tenant identity (``X-Edgemesh-Tenant``) selects
  the policy, is propagated to replicas, and labels per-tenant counters
  as a BOUNDED value (obs.metrics.bounded_label).
- **Tiered serving.** With ``tiered=True``, long prefills route to
  prefill-tier replicas (membership is dynamic — TierManager scores each
  replica's digest prefill/decode token EWMAs) and the resulting paged KV
  streams to the least-loaded decode-tier replica via ``/kv/export`` →
  ``/kv/import`` (runtime/paged_kv.py wire format). The router keeps a
  bounded LRU of export payloads — the fleet's shared prefix cache: a hot
  prefix prefills once fleet-wide. Transfer endpoints never hedge
  (non-idempotent), and EVERY transfer failure falls back to homogeneous
  routing with no client-visible error.
- **Graceful drain.** ``drain_replica`` takes a replica out of rotation,
  calls its ``/drain`` hook, polls ``/readyz`` until in-flight work hits
  zero, then marks it removed — zero dropped requests by construction.

- **Tracing.** Every request gets a W3C-style trace context
  (``X-Edgemesh-Trace``, obs/trace.py) with one child span per
  retry/hedge attempt, tagged with replica id and outcome and propagated
  to the replica — whose engine spans join the same trace.
  ``span_log=`` appends one ``router_spans`` record per sampled request
  (``trace_sample=`` gates span I/O only, never metrics); ``edgemesh obs
  trace <id> --logs ...`` stitches router + replica logs into one tree.

Obs (per-replica labels throughout): routed/retried/hedged/hedged-won/
shed/exhausted counters, drain events, an in-flight gauge, and the router
latency histogram ``edgemesh_fleet_router_seconds`` alongside the engine
spans (docs/FLEET.md has the catalog).
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from collections import OrderedDict, deque

from edgemesh.fleet.admission import AdmissionController
from edgemesh.fleet.balancer import (
    PrefixAffinityBalancer,
    TierManager,
    make_balancer,
)
from edgemesh.fleet.transport import HttpTransport, TransportError
from edgemesh.obs.metrics import bounded_label
from edgemesh.obs.slo import DecayingQuantile, SloTarget
from edgemesh.obs.trace import ROUTER_RECORD_EVENT, TraceContext, sample
from edgemesh.serve.httputil import (
    ATTEMPTS_HEADER,
    DEADLINE_HEADER,
    KV_EXPORT_PATH,
    KV_IMPORT_PATH,
    REPLICA_HEADER,
    RETRY_AFTER_HEADER,
    SESSION_HEADER,
    TENANT_HEADER,
    TIERED_HEADER,
    TRACE_HEADER,
)

log = logging.getLogger("edgemesh.fleet")

#: Endpoints the router must NEVER hedge: a KV transfer is not idempotent
#: from the fleet's point of view — a hedged export doubles a prefill, a
#: hedged import can double-admit (and double-import pages for) the same
#: request on two replicas, and "first answer wins" would leak the loser's
#: slot until its budget ran out. Transfer tails are handled by the tiered
#: path's FALLBACK (re-route homogeneous), not by racing a second copy.
NON_HEDGEABLE_PATHS = frozenset({KV_EXPORT_PATH, KV_IMPORT_PATH})


class _PinnedBalancer:
    """Single-use balancer that picks exactly one replica id (or nothing):
    how the tiered path checks out a SPECIFIC replica through the same
    atomic ``registry.acquire`` bookkeeping every other attempt uses."""

    name = "pinned"

    def __init__(self, rid: str) -> None:
        self.rid = rid

    def pick(self, candidates, prompt: str | None = None):
        for rep in candidates:
            if rep.rid == self.rid:
                return rep
        return None


class FleetRouter:
    def __init__(
        self,
        registry,
        balancer: str = "least_outstanding",
        transport=None,
        obs_registry=None,
        max_attempts: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        backoff_jitter: float = 0.5,
        default_deadline_s: float = 60.0,
        attempt_timeout_s: float = 30.0,
        hedge_after_s: float = 0.0,
        hedge_percentile: float = 0.0,
        hedge_auto: bool = False,
        hedge_quantile: float = 0.95,
        hedge_floor_s: float = 0.02,
        latency_window: int = 256,
        max_inflight: int = 64,
        admission: AdmissionController | None = None,
        admission_auto: bool = False,
        admission_floor: int = 2,
        admission_ceiling: int = 256,
        tuner=None,
        admission_wait_s: float = 10.0,
        demote_after: int = 2,
        rng: random.Random | None = None,
        span_log=None,
        trace_sample: float = 1.0,
        tiered: bool = False,
        tier_manager: TierManager | None = None,
        prefill_threshold_chars: int = 512,
        prefix_chars: int = 64,
        prefix_hot_after: int = 2,
        kv_cache_entries: int = 32,
    ) -> None:
        from edgemesh.obs import get_registry

        self.registry = registry
        self.balancer = make_balancer(balancer) if isinstance(balancer, str) else balancer
        self.transport = transport or HttpTransport()
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.backoff_jitter = backoff_jitter
        self.default_deadline_s = default_deadline_s
        self.attempt_timeout_s = attempt_timeout_s
        self.hedge_after_s = hedge_after_s
        self.hedge_percentile = hedge_percentile
        # Auto-tuned hedging (the zero-config mode): the delay is the live
        # hedge_quantile (default p95) of a time-DECAYED latency histogram
        # (obs/slo.DecayingQuantile), floored at hedge_floor_s so uniformly
        # fast fleets don't hedge every request into double load. Needs no
        # threshold config and tracks regime changes within one half-life.
        self.hedge_auto = bool(hedge_auto)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_floor_s = float(hedge_floor_s)
        self._hedge_estimator = DecayingQuantile()
        self.max_inflight = max_inflight
        self.demote_after = demote_after
        self._rng = rng or random.Random(0)
        self._sleep = time.sleep  # injectable: tests pin the backoff schedule
        # Distributed tracing (obs/trace.py): one context per request, one
        # child span per retry/hedge attempt, propagated to replicas via
        # X-Edgemesh-Trace. ``trace_sample`` gates span I/O only — every
        # request still counts in every metric. Sampling uses its OWN rng:
        # tests pin self._rng for the backoff schedule, and minting must
        # not perturb it.
        self.trace_sample = float(trace_sample)
        self._trace_rng = random.Random()
        self._trace_log = None
        if span_log is not None:
            from edgemesh.utils.tracing import JsonlLogger

            self._trace_log = JsonlLogger(span_log)
        self._recent_traces: deque[dict] = deque(maxlen=64)
        # Incident propagation (obs/anomaly.py): incident ids observed in
        # replica load digests (HealthProber ``on_incident``) are deduped
        # here, counted, surfaced on /fleetz, and fanned out to every
        # OTHER replica's ``POST /incident`` so the whole fleet's flight
        # rings land in one incident directory (docs/FLEET.md).
        self._incident_lock = threading.Lock()
        # Dedup window: a bounded id ring + set mirror, NOT an ever-growing
        # set — a long-lived router in a churning fleet observes incidents
        # indefinitely. 512 ids comfortably covers every id any replica
        # still advertises in its digest (last_incident is the newest one).
        self._incident_id_ring: deque[str] = deque(maxlen=512)  # guarded by: _incident_lock
        self._incident_ids: set[str] = set()  # guarded by: _incident_lock
        self._incidents: deque[dict] = deque(maxlen=16)  # guarded by: _incident_lock
        # Multi-tenant admission (fleet/admission.py): per-tenant token
        # buckets, weighted-fair queueing and priority lanes in front of
        # the in-flight slot pool. The default controller (no policies,
        # queue_cap=0) reproduces the legacy bounded-semaphore semantics
        # exactly: non-blocking checkout, immediate shed at max_inflight.
        # ``admission_wait_s`` caps how long a queued request may wait for
        # a slot (always further capped by the request deadline).
        self.admission = admission or AdmissionController(
            max_inflight=max_inflight)
        self.max_inflight = self.admission.max_inflight  # controller wins
        self.admission_wait_s = float(admission_wait_s)
        # Router-side per-tenant accounting for /fleetz: answered/good/
        # shed/ratelimited per bounded tenant label. "good" is the
        # router-observed response-latency SLO — status 200 within the
        # SloTarget TTFT budget (for the non-streaming /generate contract
        # the full response IS the first client-visible token).
        self._slo_target = SloTarget.from_env()
        self._tenant_lock = threading.Lock()
        self._tenant_stats: dict[str, dict[str, int]] = {}
        # Tiered serving (prefill/decode disaggregation — docs/FLEET.md
        # "Tiered serving and KV streaming"): prompts at or above
        # ``prefill_threshold_chars`` are prefilled on a prefill-tier
        # replica (rendezvous-chosen by prefix, so a hot prefix keeps
        # hitting the replica whose export cache holds it), the KV payload
        # streams through the router into the least-loaded decode-tier
        # replica, and short prompts route within the decode tier. The
        # router keeps a bounded LRU of export payloads — the fleet-level
        # SHARED PREFIX CACHE: once ``prefix_hot_after`` requests share a
        # prefix key, the prefix is exported once and every later request
        # imports it instead of recomputing. EVERY transfer failure falls
        # back to homogeneous routing — tiering is an optimization, never
        # a correctness gate.
        self.tiered = bool(tiered)
        self.tiers: TierManager | None = None
        if self.tiered:
            self.tiers = tier_manager or TierManager()
        # Model-keyed pools (docs/FLEET.md "Ensemble serving"): replicas
        # registered with a model descriptor route per pool. Tier
        # membership and the auto-hedge estimator are PER POOL — a shared
        # TierManager's cached assignment would leak one pool's split into
        # another's requests, and one slow pool's p95 would arm hedges
        # fleet-wide. Pool None keeps the legacy homogeneous instances.
        self._pool_lock = threading.Lock()
        self._pool_tiers: dict[str, TierManager] = {}  # guarded by: _pool_lock
        self._pool_hedge: dict[str, DecayingQuantile] = {}  # guarded by: _pool_lock
        self.prefill_threshold_chars = int(prefill_threshold_chars)
        self.prefix_chars = int(prefix_chars)
        self.prefix_hot_after = int(prefix_hot_after)
        self.kv_cache_entries = int(kv_cache_entries)
        self._kv_lock = threading.Lock()
        self._kv_cache: OrderedDict[str, dict] = OrderedDict()  # guarded by: _kv_lock
        self._prefix_seen: OrderedDict[str, int] = OrderedDict()  # guarded by: _kv_lock
        # Rolling successful-attempt latencies: an explicit bounded ring
        # (``latency_window``, surfaced in /fleetz) feeding the legacy
        # ``hedge_percentile`` mode; the auto mode reads the decayed
        # estimator instead. Locked: sorting the deque while another
        # handler thread appends raises "deque mutated during iteration".
        self._lat_lock = threading.Lock()
        self._lat_window: deque[float] = deque(maxlen=max(1, int(latency_window)))

        reg = obs_registry or get_registry()
        self.obs = reg
        self._routed = reg.counter(
            "edgemesh_fleet_routed_total",
            "Requests answered, by replica that answered", ("replica",),
        )
        self._retried = reg.counter(
            "edgemesh_fleet_retried_total",
            "Failed attempts that triggered a retry, by replica and reason",
            ("replica", "reason"),
        )
        self._hedged = reg.counter(
            "edgemesh_fleet_hedged_total",
            "Hedge attempts fired, by hedge replica", ("replica",),
        )
        self._hedged_won = reg.counter(
            "edgemesh_fleet_hedged_won_total",
            "Hedge attempts that beat the primary, by replica", ("replica",),
        )
        self._shed = reg.counter(
            "edgemesh_fleet_shed_total",
            "Requests shed without reaching a replica, by reason", ("reason",),
        )
        # Per-tenant twins (tenant values bounded via obs.metrics.
        # bounded_label — EM112). Separate families, not extra labels on
        # the aggregates above: the aggregate families predate tenancy and
        # their labelsets are pinned by existing dashboards and tests.
        self._tenant_requests = reg.counter(
            "edgemesh_fleet_tenant_requests_total",
            "Router requests by tenant and outcome "
            "(ok/retried/hedged_won/shed/exhausted)", ("tenant", "outcome"),
        )
        self._tenant_shed = reg.counter(
            "edgemesh_fleet_tenant_shed_total",
            "Requests shed before reaching a replica, by tenant and reason",
            ("tenant", "reason"),
        )
        self._tenant_ratelimited = reg.counter(
            "edgemesh_fleet_tenant_ratelimited_total",
            "Requests refused by the tenant's token-bucket rate limit",
            ("tenant",),
        )
        self._exhausted = reg.counter(
            "edgemesh_fleet_exhausted_total",
            "Requests that failed every attempt",
        )
        # Tiered-serving accounting: per-request outcome of the transfer
        # path (tiered = answered via export→import, cache_hit = the
        # router's shared prefix cache skipped the export hop, fallback_*
        # = degraded to homogeneous routing — never a client error), and
        # the KV wire bytes the router moved in each direction.
        self._tiered_requests = reg.counter(
            "edgemesh_fleet_tiered_total",
            "Tiered-serving path outcomes", ("outcome",),
        )
        self._kv_bytes = reg.counter(
            "edgemesh_fleet_kv_transfer_bytes_total",
            "KV wire bytes moved by router-orchestrated transfers, "
            "by direction", ("direction",),
        )
        self._incidents_total = reg.counter(
            "edgemesh_fleet_incidents_total",
            "Replica-fired incidents observed (and fanned out), by "
            "trigger kind", ("kind",),
        )
        self._drain_events = reg.counter(
            "edgemesh_fleet_drain_total",
            "Drain lifecycle events", ("replica", "event"),
        )
        self._inflight_gauge = reg.gauge(
            "edgemesh_fleet_inflight", "Requests currently inside the router",
        )
        self._latency = reg.histogram(
            "edgemesh_fleet_router_seconds",
            "End-to-end router request latency (admission to answer)",
        )
        # Outcome-labeled twin of the histogram above: failures and sheds
        # stop being invisible in the latency distribution. The unlabeled
        # family keeps its original successful-requests-only semantics for
        # dashboard compatibility (a family cannot be re-registered with a
        # new labelset); this one observes EVERY request.
        self._latency_outcome = reg.histogram(
            "edgemesh_fleet_router_outcome_seconds",
            "Router request latency by outcome "
            "(ok/retried/hedged_won/shed/exhausted)", ("outcome",),
        )
        # Knee-tracking admission (fleet/autotune.py, ``--admission auto``):
        # the tuner watches every routed request's fate through the same
        # good/answered accounting /fleetz shows, and drives
        # admission.max_inflight (and the per-tenant rate scale) toward the
        # live saturation knee. None = static limits (the legacy mode).
        self.tuner = tuner
        if admission_auto and self.tuner is None:
            from edgemesh.fleet.autotune import KneeTracker

            self.tuner = KneeTracker(
                self.admission, floor=admission_floor,
                ceiling=admission_ceiling, obs_registry=reg,
                log=self._trace_log,
            )
        # Autoscaler seam (fleet/autoscale.py): attached by the fleet CLI
        # after construction (the scaler needs the router for drains). The
        # router only forwards incident signals to it.
        self.autoscaler = None
        # The ensemble coordinator (fleet/ensemble.py): fans POST /ensemble
        # out across the QA pools and drives the refiner pool, all through
        # this router's _route — so branches inherit per-pool hedging,
        # tiering, and the shared trace machinery.
        from edgemesh.fleet.ensemble import EnsembleCoordinator

        self.ensemble = EnsembleCoordinator(self, obs_registry=reg)

    # -- model-keyed pools ---------------------------------------------------

    def _tiers_for(self, pool: str | None) -> TierManager | None:
        """The tier manager scoped to ``pool`` (lazily created; pool None =
        the legacy fleet-wide instance). Per-pool because TierManager
        caches its assignment: alternating calls over different replica
        subsets would serve one pool the other's cached split."""
        if self.tiers is None:
            return None
        if pool is None:
            return self.tiers
        with self._pool_lock:
            tm = self._pool_tiers.get(pool)
            if tm is None:
                tm = TierManager(
                    prefill_fraction=self.tiers.prefill_fraction,
                    refresh_s=self.tiers.refresh_s,
                    hysteresis=self.tiers.hysteresis,
                )
                self._pool_tiers[pool] = tm
            return tm

    def _hedge_estimator_for(self, pool: str | None) -> DecayingQuantile:
        if pool is None:
            return self._hedge_estimator
        with self._pool_lock:
            est = self._pool_hedge.get(pool)
            if est is None:
                est = DecayingQuantile()
                self._pool_hedge[pool] = est
            return est

    # -- request path --------------------------------------------------------

    def handle_generate(self, payload: dict, deadline_s: float | None = None,
                        path: str = "/generate", trace: TraceContext | None = None,
                        tenant: str | None = None,
                        session: str | None = None,
                        pool: str | None = None):
        """Route one request. Returns ``(status, body, headers)`` — the
        HTTP frontend writes them verbatim; in-process callers (tests,
        benchmarks) read them directly. ``trace`` joins an existing trace
        (a client-supplied ``X-Edgemesh-Trace``); otherwise this request
        mints its own. The response always carries the trace header back,
        so clients can fetch ``/debug/traces/<id>`` or grep their logs.

        ``tenant`` is the raw ``X-Edgemesh-Tenant`` value (None for
        untagged traffic, which admits as the ``default`` tenant): it
        selects the admission policy (rate limit / fairness weight /
        priority lane), is propagated to the replica on every attempt, and
        labels the per-tenant counters — as a BOUNDED value
        (obs.metrics.bounded_label), so client-minted ids cannot explode
        metric cardinality."""
        # Normalized once at the door; every .labels(tenant=...) below
        # uses this bounded value (edgelint EM112).
        label = bounded_label(tenant)
        ctx = trace or TraceContext.mint(
            sampled=sample(self.trace_sample, self._trace_rng)
        )
        # spans[0] is the root request span; attempts append behind it.
        # Wall clock throughout (clock: "wall" in the record): these edges
        # are what cross-process assembly anchors replica clocks against.
        spans: list[dict] = [{
            "name": "request", "span_id": ctx.span_id,
            "t0": time.time(), "t1": None,
        }]
        t0 = time.monotonic()
        # One outcome per request for the labeled latency histogram:
        # ok / retried / hedged_won / shed / exhausted. _route/_dispatch
        # refine it in place as the request's fate lands.
        meta = {"outcome": "shed"}
        # Admission: rate limit → fairness queue → slot. Queue wait is
        # capped by the request's own deadline budget — time spent waiting
        # for admission comes out of the same budget _route spends, so the
        # router still never exceeds what the client asked.
        budget = deadline_s if deadline_s is not None else self.default_deadline_s
        verdict = self.admission.acquire(
            label, wait_s=min(self.admission_wait_s, budget)
        )
        if verdict == "ratelimited":
            self._shed.labels(reason="ratelimit").inc()
            self._tenant_shed.labels(tenant=label, reason="ratelimit").inc()
            self._tenant_ratelimited.labels(tenant=label).inc()
            status, body, headers = 429, {
                "error": "tenant rate limit exceeded", "tenant": label,
            }, {RETRY_AFTER_HEADER: "1"}
        elif verdict != "ok":
            reason = "overload" if verdict == "overload" else "queue_timeout"
            self._shed.labels(reason=reason).inc()
            self._tenant_shed.labels(tenant=label, reason=reason).inc()
            status, body, headers = 503, {
                "error": "router at capacity", "reason": reason,
                # Live value: under --admission auto the tuner moves it.
                "max_inflight": self.admission.max_inflight,
            }, {RETRY_AFTER_HEADER: "1"}
        else:
            self._inflight_gauge.inc()
            try:
                status, body, headers = self._route(
                    payload, t0, deadline_s, path, ctx, spans, meta,
                    tenant=tenant, session=session, pool=pool,
                )
            finally:
                self._inflight_gauge.dec()
                self.admission.release()
        latency = time.monotonic() - t0
        self._latency_outcome.labels(outcome=meta["outcome"]).observe(latency)
        self._tenant_requests.labels(tenant=label, outcome=meta["outcome"]).inc()
        self._account_tenant(label, meta["outcome"], status, latency)
        if self.tuner is not None:
            # Same goodness definition as the per-tenant accounting above:
            # answered 200 within the router-side response-latency target.
            self.tuner.observe(
                answered=(meta["outcome"] != "shed" and status == 200),
                good=(status == 200 and latency <= self._slo_target.ttft_s),
                shed=(meta["outcome"] == "shed"),
            )
        headers = dict(headers)
        headers[TRACE_HEADER] = ctx.to_header()
        self._finish_trace(ctx, spans, status, tenant=tenant)
        return status, body, headers

    def _account_tenant(self, label: str, outcome: str, status: int,
                        latency_s: float) -> None:
        """Per-tenant /fleetz accounting: answered/good/shed/ratelimited.
        "good" = answered 200 within the router-side response-latency
        target (SloTarget TTFT — the non-streaming front door delivers the
        whole answer as its first client-visible byte)."""
        with self._tenant_lock:
            cell = self._tenant_stats.setdefault(label, {
                "requests": 0, "answered": 0, "good": 0,
                "shed": 0, "ratelimited": 0,
            })
            cell["requests"] += 1
            if outcome == "shed":
                cell["shed"] += 1
                if status == 429:
                    cell["ratelimited"] += 1
            elif status == 200:
                cell["answered"] += 1
                if latency_s <= self._slo_target.ttft_s:
                    cell["good"] += 1

    def _finish_trace(self, ctx: TraceContext, spans: list[dict],
                      status: int, tenant: str | None = None) -> None:
        """Close the root span; for sampled requests, remember the record
        (``/fleetz`` summaries, ``/debug/traces/<id>``) and append it to the
        router span log. The in-memory record keeps the LIVE span dicts so
        an abandoned hedge attempt that completes late still fills in its
        outcome; the JSONL write is a point-in-time snapshot (a late loser
        may stay "pending" there — the hedged counters still count it)."""
        spans[0]["t1"] = time.time()
        if not ctx.sampled:
            return
        record = {
            "event": ROUTER_RECORD_EVENT, "ts": spans[0]["t1"],
            "trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "process": "router", "status": status, "clock": "wall",
            "tenant": tenant,
            "attempts": len(spans) - 1,
            "latency_s": round(spans[0]["t1"] - spans[0]["t0"], 6),
            "spans": spans,
        }
        self._recent_traces.append(record)
        if self._trace_log is not None:
            fields = {k: v for k, v in record.items()
                      if k not in ("event", "ts")}
            fields["spans"] = [dict(s) for s in spans]
            self._trace_log.log(ROUTER_RECORD_EVENT, **fields)

    def _route(self, payload, t0, deadline_s, path, ctx, spans, meta=None,
               tenant: str | None = None, session: str | None = None,
               pool: str | None = None):
        meta = meta if meta is not None else {"outcome": "shed"}
        deadline = t0 + (deadline_s if deadline_s is not None else self.default_deadline_s)
        prompt = payload.get("question") if isinstance(payload, dict) else None
        excluded: set[str] = set()
        last_error: str = "no attempt made"
        # Tiered serving: long prefills (and hot shared prefixes) go
        # export→import across the tiers; short prompts stay inside the
        # decode tier. Every failure along the tiered path lands back here
        # and routes homogeneously — tier_exclude is a routing HINT that
        # the no-replica branch below clears before it could ever starve
        # a request.
        tier_exclude: frozenset[str] = frozenset()
        if self.tiers is not None and prompt and path == "/generate":
            plan = self._tier_plan(prompt, pool=pool)
            if plan is not None:
                if plan["transfer"]:
                    out = self._tiered_generate(
                        plan, payload, prompt, t0, deadline, ctx, spans,
                        meta, tenant=tenant, session=session, pool=pool,
                    )
                    if out is not None:
                        return out
                    # A failed transfer falls back FULLY homogeneous — no
                    # exclusion. Keeping long prompts off the prefill tier
                    # here would concentrate every long prefill on the
                    # decode tier (the exact interference tiering exists
                    # to prevent) whenever the export path is down.
                else:
                    tier_exclude = frozenset(r.rid for r in plan["prefill"])
        for attempt in range(self.max_attempts):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._shed.labels(reason="deadline").inc()
                meta["outcome"] = "shed"
                return 504, {"error": "deadline exceeded", "attempts": attempt,
                             "last_error": last_error}, {}
            rep = self.registry.acquire(self.balancer, prompt=prompt,
                                        exclude=excluded | tier_exclude,
                                        pool=pool)
            if rep is None and (excluded or tier_exclude):
                # Every routable replica has failed once this request (or
                # the tier hint excluded them all): reset exclusions rather
                # than give up with replicas alive.
                excluded.clear()
                tier_exclude = frozenset()
                rep = self.registry.acquire(self.balancer, prompt=prompt,
                                            exclude=excluded, pool=pool)
            if rep is None:
                self._shed.labels(reason="no_replica").inc()
                meta["outcome"] = "shed"
                return 503, {"error": "no available replica"}, {RETRY_AFTER_HEADER: "1"}
            outcome = self._dispatch(rep, payload, path, deadline, prompt,
                                     excluded, ctx, spans, meta, tenant=tenant,
                                     session=session, pool=pool)
            if outcome[0] == "ok":
                _, rid, status, body, won_span = outcome
                won_span["won"] = True
                self._routed.labels(replica=rid).inc()
                self._latency.observe(time.monotonic() - t0)
                if meta["outcome"] != "hedged_won":
                    meta["outcome"] = "retried" if attempt else "ok"
                return status, body, {
                    REPLICA_HEADER: rid,
                    ATTEMPTS_HEADER: str(attempt + 1),
                }
            failures = outcome[1]  # [(rid, reason, detail), ...]
            for rid, reason, detail in failures:
                excluded.add(rid)
                last_error = f"{rid}: {reason}: {detail}"
                log.warning("attempt %d on %s failed (%s): %s",
                            attempt + 1, rid, reason, detail)
            if attempt + 1 < self.max_attempts:
                for rid, reason, _ in failures:
                    self._retried.labels(replica=rid, reason=reason).inc()
                self._sleep(self._backoff(attempt, deadline))
        self._exhausted.inc()
        meta["outcome"] = "exhausted"
        return 502, {"error": "all attempts failed",
                     "attempts": self.max_attempts,
                     "last_error": last_error}, {}

    # -- tiered serving (prefill/decode disaggregation) ----------------------

    def _tier_plan(self, prompt: str, pool: str | None = None) -> dict | None:
        """Classify one request against the live tier assignment. Returns
        None when the fleet cannot be tiered right now (either tier empty
        → fully homogeneous routing), else ``{"prefill", "decode",
        "transfer", "key", "export_q"}``: long prompts transfer under the
        full-prompt key; short prompts transfer only once their prefix key
        is HOT (``prefix_hot_after`` sightings), exporting just the prefix.
        With a pool, tiering happens WITHIN the pool's members and every
        cache/hotness key is pool-namespaced — a KV payload prefillled by
        one model must never import into another model's cache."""
        reps = self.registry.replicas()
        if pool is not None:
            reps = [r for r in reps if r.pool == pool]
        tiers = self._tiers_for(pool).assign(reps)
        pre, dec = tiers["prefill"], tiers["decode"]
        if not pre or not dec:
            return None
        plan = {"prefill": pre, "decode": dec}
        ns = "" if pool is None else pool + "\x00"
        if len(prompt) >= self.prefill_threshold_chars:
            plan.update(transfer=True, key=ns + prompt, export_q=prompt)
            return plan
        prefix = prompt[: self.prefix_chars]
        hot = self._note_prefix(ns + prefix)
        plan.update(transfer=hot, key=ns + prefix, export_q=prefix)
        return plan

    def _tiered_generate(self, plan, payload, prompt, t0, deadline, ctx,
                         spans, meta, tenant=None, session=None, pool=None):
        """The transfer path: export the prompt (or its hot prefix) from a
        prefill-tier replica — rendezvous-chosen by prefix key, the same
        keying as ``prefix_affinity``, so repeats land on the replica whose
        export cache is warm — then import the payload into the
        least-loaded decode-tier replica, which answers the request with
        no prefill recompute. Returns the final ``(status, body, headers)``
        or None, and None ALWAYS means "route homogeneously": a transfer
        failure is never a client-visible error."""
        key = plan["key"]
        cached = self._kv_cache_get(key)
        from_cache = cached is not None
        if cached is None:
            owner = max(
                plan["prefill"],
                key=lambda r: PrefixAffinityBalancer._score(
                    key[: self.prefix_chars], r.rid),
            )
            rep = self.registry.acquire(_PinnedBalancer(owner.rid),
                                        prompt=prompt, pool=pool)
            if rep is None:
                self._tiered_requests.labels(outcome="fallback_no_replica").inc()
                return None
            out = self._attempt_one(
                rep, {"question": plan["export_q"]}, KV_EXPORT_PATH,
                deadline, ctx.child(), spans, tenant=tenant, session=session,
                record_latency=False,
            )
            if (out[0] != "ok" or out[2] != 200
                    or not isinstance(out[3], dict) or not out[3].get("kv")):
                self._tiered_requests.labels(outcome="fallback_export").inc()
                return None
            body = out[3]
            nbytes = int(body.get("bytes") or 0)
            self._kv_bytes.labels(direction="export").inc(nbytes)
            cached = {"kv": body["kv"], "bytes": nbytes,
                      "tokens": body.get("tokens")}
            self._kv_cache_put(key, cached)
        dest = min(plan["decode"], key=lambda r: (r.outstanding, r.rid))
        rep = self.registry.acquire(_PinnedBalancer(dest.rid), prompt=prompt,
                                    pool=pool)
        if rep is None:
            self._tiered_requests.labels(outcome="fallback_no_replica").inc()
            return None
        body = {"question": prompt, "kv": cached["kv"]}
        if isinstance(payload, dict) and payload.get("max_new") is not None:
            body["max_new"] = payload["max_new"]
        out = self._attempt_one(
            rep, body, KV_IMPORT_PATH, deadline, ctx.child(), spans,
            tenant=tenant, session=session, record_latency=False,
        )
        if out[0] != "ok" or out[2] != 200:
            self._tiered_requests.labels(outcome="fallback_import").inc()
            return None
        _, rid, _status, answer, span = out
        span["won"] = True
        self._routed.labels(replica=rid).inc()
        self._latency.observe(time.monotonic() - t0)
        self._kv_bytes.labels(direction="import").inc(int(cached["bytes"]))
        meta["outcome"] = "ok"
        # ONE outcome per request (the family's fates are disjoint, so
        # fallback ratios computed over it stay honest): "cache_hit" =
        # answered via the shared prefix cache, "tiered" = paid the
        # export hop.
        self._tiered_requests.labels(
            outcome="cache_hit" if from_cache else "tiered").inc()
        attempts = sum(1 for s in spans if s.get("name") == "attempt")
        return 200, answer, {
            REPLICA_HEADER: rid,
            ATTEMPTS_HEADER: str(attempts),
            TIERED_HEADER: "1",
        }

    def _note_prefix(self, key: str) -> bool:
        """Bump the prefix key's sighting count (bounded LRU — an idle key
        eventually evicts, which is the decay) and report hotness."""
        with self._kv_lock:
            n = self._prefix_seen.get(key, 0) + 1
            self._prefix_seen[key] = n
            self._prefix_seen.move_to_end(key)
            while len(self._prefix_seen) > 4096:
                self._prefix_seen.popitem(last=False)
            return n >= self.prefix_hot_after

    def _kv_cache_get(self, key: str) -> dict | None:
        with self._kv_lock:
            hit = self._kv_cache.get(key)
            if hit is not None:
                self._kv_cache.move_to_end(key)
            return hit

    def _kv_cache_put(self, key: str, entry: dict) -> None:
        with self._kv_lock:
            self._kv_cache[key] = entry
            self._kv_cache.move_to_end(key)
            while len(self._kv_cache) > self.kv_cache_entries:
                self._kv_cache.popitem(last=False)

    def note_digest(self, rid: str, load: dict) -> None:
        """Health-prober digest hook (fleet/health.py ``on_digest``): fresh
        phase telemetry invalidates the tier manager's cached assignment so
        membership reacts on the probe cadence, not the cache TTL. The
        digest's ``mem`` block (obs/memory.py) also feeds the admission
        controller's exhaustion-aware deferral, keyed by replica so one
        recovering pool does not mask another's pressure."""
        if self.tiers is not None:
            self.tiers.invalidate()
            with self._pool_lock:
                pool_tiers = list(self._pool_tiers.values())
            for tm in pool_tiers:
                tm.invalidate()
        self.admission.note_mem_forecast(load, replica=rid)

    def _backoff(self, attempt: int, deadline: float) -> float:
        delay = min(self.backoff_cap_s, self.backoff_base_s * (2 ** attempt))
        delay *= 1.0 + self.backoff_jitter * self._rng.random()
        return max(0.0, min(delay, deadline - time.monotonic()))

    # -- attempts ------------------------------------------------------------

    def _attempt_one(self, rep, payload, path, deadline, ctx, spans,
                     hedge: bool = False, tenant: str | None = None,
                     session: str | None = None,
                     record_latency: bool = True,
                     pool: str | None = None):
        """One checked-out attempt → ("ok", rid, status, body) for any
        answered status < 500, else ("fail", rid, reason, detail).

        Each attempt is one child span of the request trace: the span dict
        is appended (with every key it will ever have — concurrent JSON
        dumps must never see a dict growing) BEFORE dispatch, so a replica
        record can parent onto it even when the attempt is later abandoned,
        and mutated in place as the outcome lands."""
        span = {
            "name": "attempt", "span_id": ctx.span_id, "replica": rep.rid,
            "path": path,  # /generate vs the KV transfer hops
            "hedge": hedge, "outcome": "pending", "status": None,
            "won": False,  # set by _route on the attempt whose answer the
            "t0": time.time(), "t1": None,  # client actually received — an
        }  # abandoned hedge loser can ALSO finish "ok" without having won
        spans.append(span)

        def close(outcome: str, status=None):
            span["t1"] = time.time()
            span["outcome"] = outcome
            span["status"] = status

        remaining = deadline - time.monotonic()
        if remaining <= 0:
            self.registry.release(rep.rid, ok=False, demote_after=self.demote_after,
                                  error="deadline exceeded before dispatch")
            close("deadline")
            return ("fail", rep.rid, "deadline", "expired before dispatch")
        timeout_s = min(self.attempt_timeout_s, remaining)
        headers = {DEADLINE_HEADER: f"{remaining:.3f}",
                   TRACE_HEADER: ctx.to_header()}
        if tenant is not None:
            # Tenant identity rides every attempt: the replica's span
            # records and per-tenant SLO metrics attribute the work to the
            # same tenant the router admitted (docs/OBSERVABILITY.md).
            headers[TENANT_HEADER] = tenant
        if session is not None:
            # Session identity rides too (span records only): it is what
            # lets `edgemesh obs replay` rebuild recorded traffic's
            # shared-prefix session grouping from the replica logs.
            headers[SESSION_HEADER] = session
        t0 = time.monotonic()
        try:
            status, body = self.transport.post_json(
                rep.url(path), payload, timeout_s=timeout_s, headers=headers
            )
        except TransportError as e:
            self.registry.release(rep.rid, ok=False, demote_after=self.demote_after,
                                  error=str(e))
            close("connect")
            return ("fail", rep.rid, "connect", str(e))
        if status >= 500:
            self.registry.release(rep.rid, ok=False, demote_after=self.demote_after,
                                  error=f"status {status}")
            close(f"status_{status}", status)
            return ("fail", rep.rid, f"status_{status}", str(body.get("error", body))[:200])
        self.registry.release(rep.rid, ok=True)
        if record_latency:
            # KV transfer hops opt out: an export's prefill wall time is
            # not a /generate latency, and feeding it to the hedge
            # estimator would inflate every auto-tuned hedge delay.
            lat = time.monotonic() - t0
            with self._lat_lock:
                self._lat_window.append(lat)
            # Auto-hedge learns per pool: one pool's latency regime must
            # not arm (or suppress) hedges in another's. (_lat_window —
            # the legacy percentile mode — stays fleet-wide.)
            self._hedge_estimator_for(pool).observe(lat)
        close("ok", status)
        return ("ok", rep.rid, status, body, span)

    def _hedge_delay(self, pool: str | None = None) -> float | None:
        """The current hedge-arming delay: fixed (``hedge_after_s``) beats
        the legacy rolling-window percentile (``hedge_percentile``) beats
        the auto-tuned mode (``hedge_auto``: the live ``hedge_quantile`` of
        the time-decayed latency histogram, floored at ``hedge_floor_s``).
        None = hedging off (or the estimator has not seen enough yet)."""
        if self.hedge_after_s:
            return self.hedge_after_s
        if self.hedge_percentile:
            with self._lat_lock:
                xs = sorted(self._lat_window)
            if len(xs) >= 16:
                return xs[min(len(xs) - 1, int(self.hedge_percentile * len(xs)))]
            return None
        if self.hedge_auto:
            d = self._hedge_estimator_for(pool).quantile(self.hedge_quantile)
            return None if d is None else max(d, self.hedge_floor_s)
        return None

    def _dispatch(self, rep, payload, path, deadline, prompt, excluded,
                  ctx, spans, meta=None, tenant: str | None = None,
                  session: str | None = None, pool: str | None = None):
        """One attempt round, hedged when configured. Returns
        ("ok", rid, status, body) or ("fail", [(rid, reason, detail), ...]).
        Every attempt (primary and hedge) gets its own child trace context
        — distinct span ids are what let the assembled tree show the hedge
        as a sibling of the attempt it raced."""
        meta = meta if meta is not None else {"outcome": "shed"}
        hedge_delay = self._hedge_delay(pool)
        # KV transfers are non-idempotent fleet-side (a hedged import
        # double-admits the request, a hedged export doubles a prefill):
        # they NEVER hedge, regardless of configuration. Their tail story
        # is the tiered path's homogeneous fallback instead.
        if path in NON_HEDGEABLE_PATHS:
            hedge_delay = None
        if hedge_delay is None or hedge_delay >= (deadline - time.monotonic()):
            out = self._attempt_one(rep, payload, path, deadline,
                                    ctx.child(), spans, tenant=tenant,
                                    session=session, pool=pool)
            return out if out[0] == "ok" else ("fail", [out[1:]])

        results: queue.Queue = queue.Queue()

        def run(replica, is_hedge):
            results.put((is_hedge, self._attempt_one(
                replica, payload, path, deadline, ctx.child(), spans,
                hedge=is_hedge, tenant=tenant, session=session, pool=pool,
            )))

        threading.Thread(target=run, args=(rep, False), daemon=True).start()
        try:
            first = results.get(timeout=hedge_delay)
        except queue.Empty:
            first = None
        if first is not None:
            if first[1][0] == "ok":
                return first[1]  # primary answered inside the hedge window
            # A FAST failure is not a tail-latency event: hand it to the
            # normal retry path (backoff + retried counters) instead of
            # firing a zero-backoff failover dressed up as a hedge — the
            # hedged metrics must mean "the primary was slow", nothing else.
            return ("fail", [first[1][1:]])

        hedge_rep = self.registry.acquire(
            self.balancer, prompt=prompt, exclude=excluded | {rep.rid},
            pool=pool,
        )
        if hedge_rep is not None:
            self._hedged.labels(replica=hedge_rep.rid).inc()
            threading.Thread(target=run, args=(hedge_rep, True), daemon=True).start()

        # Drain results until a winner or both attempts have reported. The
        # per-attempt transport timeout bounds the usual stalls, but it is
        # a per-socket-op bound — a replica trickling one byte per read
        # never trips it — so the get() itself is ALSO capped by the
        # request deadline: past it the attempts are abandoned and the
        # router answers within the client's budget.
        pending = 2 if hedge_rep is not None else 1
        failures = []
        while pending > 0:
            try:
                is_hedge, out = results.get(
                    timeout=max(0.05, deadline - time.monotonic())
                )
            except queue.Empty:
                failures.append(
                    (rep.rid, "deadline", "attempt outlived the request deadline")
                )
                break
            pending -= 1
            if out[0] == "ok":
                if is_hedge:
                    self._hedged_won.labels(replica=out[1]).inc()
                    meta["outcome"] = "hedged_won"
                return out
            failures.append(out[1:])
        return ("fail", failures or [(rep.rid, "hedge", "no attempt completed")])

    # -- incidents -----------------------------------------------------------

    def observe_incident(self, source_rid: str, incident: dict) -> bool:
        """A replica's load digest carried an incident {id, kind, ts}
        (fired by its local anomaly triggers — obs/anomaly.py). Dedupe by
        id, count it, remember it for ``/fleetz``, append an ``incident``
        record to the router span log (the postmortem timeline), and fan
        the id out to every OTHER replica's ``POST /incident`` so their
        flight rings dump into the same incident directory. The fan-out
        runs on its own thread: the health prober's probe pass must never
        block on N replicas' dump I/O. Returns True when the incident was
        new."""
        iid = incident.get("id") if isinstance(incident, dict) else None
        if not iid:
            return False
        with self._incident_lock:
            if iid in self._incident_ids:
                return False
            if len(self._incident_id_ring) == self._incident_id_ring.maxlen:
                self._incident_ids.discard(self._incident_id_ring[0])
            self._incident_id_ring.append(iid)
            self._incident_ids.add(iid)
            rec = {
                "id": iid, "kind": incident.get("kind"),
                "ts": incident.get("ts"), "source": source_rid,
            }
            self._incidents.append(rec)
        self._incidents_total.labels(
            kind=str(incident.get("kind") or "unknown")).inc()
        log.warning("incident %s (%s) fired on %s — propagating",
                    iid, rec["kind"], source_rid)
        if self.tuner is not None:
            # Incident windows measure the incident, not the limit: tuning
            # on them would chase the degradation downward (fleet/
            # autotune.py). Observation continues; control pauses.
            self.tuner.freeze(reason=f"incident:{iid}")
        if self.autoscaler is not None:
            # The incident IS a demand/supply signal: a degraded replica
            # means the surviving fleet is about to be short one replica's
            # capacity — scale up ahead of the queue growth
            # (fleet/autoscale.py; ROADMAP "self-driving fleet").
            try:
                self.autoscaler.note_incident(rec)
            except Exception:
                log.exception("autoscaler incident hook failed")
        if self._trace_log is not None:
            self._trace_log.log("incident", **rec)
        targets = [rep for rep in self.registry.replicas()
                   if rep.rid != source_rid]
        threading.Thread(target=self._broadcast_incident,
                         args=(dict(rec), targets), daemon=True).start()
        return True

    def _broadcast_incident(self, rec: dict, targets) -> None:
        for rep in targets:
            try:
                self.transport.post_json(
                    rep.url("/incident"),
                    {"id": rec["id"], "kind": rec.get("kind"),
                     "source": rec.get("source")},
                    timeout_s=self.attempt_timeout_s,
                )
            except TransportError as e:
                # Best-effort: a replica that cannot dump is a smaller
                # postmortem, not a routing failure.
                log.warning("incident fan-out to %s failed: %s", rep.rid, e)

    def recent_incidents(self) -> list[dict]:
        """Newest-first observed incidents — the /fleetz surfacing."""
        with self._incident_lock:
            return [dict(r) for r in reversed(self._incidents)]

    def forget_replica(self, rid: str) -> bool:
        """Deregister ``rid`` AND purge every per-replica trace of it: the
        registry entry (its load digest goes with it), the tier manager's
        hysteresis membership (a re-registered replica must re-earn its
        tier, not inherit a dead incarnation's bonus), and the incident
        bookkeeping sourced from it (a restarted replica re-minting an id
        must propagate fresh, and /fleetz must stop attributing old
        incidents to a replica that no longer exists). The frontend's
        ``/replicas/deregister`` and the autoscaler's drain path both come
        through here — plain ``registry.deregister`` is the seam that left
        stale digests and tier ghosts behind."""
        existed = self.registry.deregister(rid)
        if self.tiers is not None:
            self.tiers.forget(rid)
            with self._pool_lock:
                pool_tiers = list(self._pool_tiers.values())
            for tm in pool_tiers:
                tm.forget(rid)
        # A forgotten replica's pool forecast must not keep deferring
        # batch admissions — passing no digest clears its entry.
        self.admission.note_mem_forecast(None, replica=rid)
        with self._incident_lock:
            stale = [r["id"] for r in self._incidents
                     if r.get("source") == rid]
            if stale:
                self._incidents = deque(
                    (r for r in self._incidents if r.get("source") != rid),
                    maxlen=self._incidents.maxlen,
                )
                for iid in stale:
                    self._incident_ids.discard(iid)
                self._incident_id_ring = deque(
                    (i for i in self._incident_id_ring if i not in stale),
                    maxlen=self._incident_id_ring.maxlen,
                )
        return existed

    # -- drain ---------------------------------------------------------------

    def drain_replica(self, rid: str, timeout_s: float = 60.0,
                      poll_s: float = 0.2) -> dict:
        """Gracefully remove ``rid``: out of rotation immediately, then the
        replica's ``/drain`` hook fires and ``/readyz`` is polled until its
        in-flight count reaches zero (or ``timeout_s``). In-flight requests
        finish; only then is the replica safe to stop."""
        rep = self.registry.get(rid)
        if rep is None:
            return {"replica": rid, "error": "unknown replica"}
        self.registry.set_state(rid, "draining")
        self._drain_events.labels(replica=rid, event="started").inc()
        try:
            self.transport.post_json(rep.url("/drain"), {},
                                     timeout_s=self.attempt_timeout_s)
        except TransportError as e:
            log.warning("drain hook on %s failed: %s", rid, e)
        deadline = time.monotonic() + timeout_s
        inflight: int | None = None
        fail_streak = 0
        while time.monotonic() < deadline:
            # Router-tracked outstanding covers requests we routed; the
            # replica's own /readyz inflight covers direct clients too.
            try:
                _, body = self.transport.get_json(
                    rep.url("/readyz"), timeout_s=self.attempt_timeout_s
                )
                inflight = body.get("inflight")
                fail_streak = 0
            except TransportError:
                # One failed poll is indistinguishable from a GC pause; only
                # a STREAK means the replica is actually gone (nothing left
                # to drain). A transient error must not declare the drain
                # complete while direct-client requests still run.
                fail_streak += 1
                inflight = None
                if fail_streak >= 3:
                    inflight = 0
            if inflight == 0 and rep.outstanding == 0:
                break
            self._sleep(poll_s)
        drained = inflight == 0 and rep.outstanding == 0
        self.registry.set_state(rid, "removed")
        self._drain_events.labels(
            replica=rid, event="completed" if drained else "timeout"
        ).inc()
        return {"replica": rid, "drained": drained, "inflight": inflight}

    # -- introspection -------------------------------------------------------

    def recent_traces(self, limit: int = 20) -> list[dict]:
        """Newest-first compact summaries of recently sampled traces —
        what ``/fleetz`` shows so an operator can pick an id to assemble."""
        out = []
        for rec in reversed(list(self._recent_traces)):
            out.append({
                "trace_id": rec["trace_id"], "status": rec["status"],
                "latency_s": rec.get("latency_s"),
                "attempts": rec.get("attempts"),
                "replicas": sorted({
                    s["replica"] for s in rec["spans"]
                    if s.get("name") == "attempt" and s.get("replica")
                }),
                "ts": rec.get("ts"),
            })
            if len(out) >= limit:
                break
        return out

    def get_trace(self, trace_id: str) -> dict | None:
        """Assemble one recent trace from the router's in-memory record
        (the router-side view: request + attempt spans). Cross-process
        assembly — replica spans stitched in with skew correction — needs
        the span LOGS and lives in ``edgemesh obs trace``. Unique id
        prefixes are accepted."""
        from edgemesh.obs.trace import assemble_trace, critical_path

        exact = [
            rec for rec in self._recent_traces
            if rec["trace_id"] == trace_id
        ]
        if exact:
            # A client fanning out requests under one supplied traceparent
            # produces several records with the same trace id — serve the
            # newest rather than refusing an id that plainly exists.
            match = exact[-1]
        else:
            prefixed = [
                rec for rec in self._recent_traces
                if rec["trace_id"].startswith(trace_id)
            ]
            if len({rec["trace_id"] for rec in prefixed}) != 1:
                return None  # unknown, or ambiguous prefix
            match = prefixed[-1]
        doc = assemble_trace(match["trace_id"], [match])
        doc["critical_path"] = critical_path(doc["tree"])
        return doc

    def status(self) -> dict:
        with self._lat_lock:
            window_len = len(self._lat_window)
            window_size = self._lat_window.maxlen
        delay = self._hedge_delay()
        if self.hedge_after_s:
            hedge_mode = "fixed"
        elif self.hedge_percentile:
            hedge_mode = "percentile"
        elif self.hedge_auto:
            hedge_mode = "auto"
        else:
            hedge_mode = "off"
        with self._tenant_lock:
            tenants = {
                t: {
                    **cell,
                    "goodput_ratio": (
                        round(cell["good"] / cell["answered"], 4)
                        if cell["answered"] else None
                    ),
                }
                for t, cell in sorted(self._tenant_stats.items())
            }
        tiers = None
        if self.tiers is not None:
            t = self.tiers.assign(self.registry.replicas())
            with self._kv_lock:
                cache_len = len(self._kv_cache)
                hot_len = len(self._prefix_seen)
            tiers = {
                # The live, digest-EWMA-driven membership — what an
                # operator watches move as the workload mix shifts.
                "prefill": [r.rid for r in t["prefill"]],
                "decode": [r.rid for r in t["decode"]],
                "prefill_threshold_chars": self.prefill_threshold_chars,
                "prefix_chars": self.prefix_chars,
                "kv_cache": {"entries": cache_len,
                             "capacity": self.kv_cache_entries,
                             "hot_keys": hot_len},
            }
        admission = self.admission.stats()
        if self.tuner is not None:
            admission["tuner"] = self.tuner.status()
        # Fleet capacity rollup (docs/OBSERVABILITY.md "The capacity
        # model"): the routable replicas' digest capacity estimates and
        # observed arrival rates summed into the supply/demand pair the
        # autoscaler balances. Nulls stay null — a cold fleet reports no
        # claim, not zero.
        cap_tok = cap_req = demand = None
        per_replica: dict[str, dict] = {}
        # Fleet compute rollup (docs/OBSERVABILITY.md "The compute
        # observatory"): per-boundary measured launch EWMAs from each
        # replica's digest cost block, aggregated across the routable
        # fleet. Null until some replica's ledger has measured something.
        fleet_costs: dict[str, dict] = {}
        # Fleet memory rollup (docs/OBSERVABILITY.md "The memory
        # observatory"): each routable replica's pool occupancy and
        # exhaustion forecast from the digest ``mem`` block. Null until
        # some replica ships one (dense backends never do).
        mem_replicas: dict[str, dict] = {}
        # Fleet quality rollup (docs/OBSERVABILITY.md "The quality
        # observatory"): each replica's digest quality block beside its
        # latest canary score — what /fleetz shows an operator hunting a
        # replica that answers fast and wrong.
        quality_replicas: dict[str, dict] = {}
        for rep in self.registry.replicas():
            if not rep.routable():
                continue
            load = rep.load if isinstance(rep.load, dict) else {}
            m = load.get("mem")
            if isinstance(m, dict):
                mem_replicas[rep.rid] = {
                    "total_pages": m.get("total_pages"),
                    "free_pages": m.get("free_pages"),
                    "resident_pages": m.get("resident_pages"),
                    "forecast_s": m.get("forecast_s"),
                    "leaked_pages": (m.get("leak") or {}).get("pages"),
                    "conservation_breaks": m.get("conservation_breaks"),
                }
            qcell: dict = {}
            q = load.get("quality")
            if isinstance(q, dict):
                qcell["confidence_ewma"] = q.get("confidence_ewma")
                qcell["low_fraction"] = q.get("low_fraction")
            if isinstance(rep.canary, dict):
                qcell["canary"] = dict(rep.canary)
            if qcell:
                quality_replicas[rep.rid] = qcell
            cap = load.get("capacity")
            if not isinstance(cap, dict):
                continue
            arrival = load.get("ewma_arrival_s")
            cell = {
                "est_tok_s": cap.get("est_tok_s"),
                "est_req_s": cap.get("est_req_s"),
                "measured_tok_s": cap.get("measured_tok_s"),
                "arrival_rps": (
                    round(1.0 / arrival, 3) if arrival else None
                ),
            }
            per_replica[rep.rid] = cell
            if cell["est_tok_s"] is not None:
                cap_tok = (cap_tok or 0.0) + cell["est_tok_s"]
            if cell["est_req_s"] is not None:
                cap_req = (cap_req or 0.0) + cell["est_req_s"]
            if cell["arrival_rps"] is not None:
                demand = (demand or 0.0) + cell["arrival_rps"]
            costs = load.get("costs")
            if isinstance(costs, dict):
                for boundary, c in costs.items():
                    if not isinstance(c, dict):
                        continue
                    agg = fleet_costs.setdefault(
                        str(boundary),
                        {"replicas": 0, "launches": 0,
                         "ewma_launch_s": [], "roofline": []})
                    agg["replicas"] += 1
                    if isinstance(c.get("launches"), int):
                        agg["launches"] += c["launches"]
                    if isinstance(c.get("ewma_launch_s"), (int, float)):
                        agg["ewma_launch_s"].append(float(c["ewma_launch_s"]))
                    if isinstance(c.get("roofline"), (int, float)):
                        agg["roofline"].append(float(c["roofline"]))
        capacity = {
            "fleet_est_tok_s": None if cap_tok is None else round(cap_tok, 3),
            "fleet_est_req_s": None if cap_req is None else round(cap_req, 3),
            "fleet_arrival_rps": None if demand is None else round(demand, 3),
            "replicas": per_replica,
            "costs": {
                b: {
                    "replicas": a["replicas"],
                    "launches": a["launches"],
                    "ewma_launch_s": (
                        round(sum(a["ewma_launch_s"])
                              / len(a["ewma_launch_s"]), 6)
                        if a["ewma_launch_s"] else None),
                    "roofline": (
                        round(sum(a["roofline"]) / len(a["roofline"]), 4)
                        if a["roofline"] else None),
                }
                for b, a in sorted(fleet_costs.items())
            } or None,
        }
        mem = None
        if mem_replicas:
            forecasts = [c["forecast_s"] for c in mem_replicas.values()
                         if isinstance(c["forecast_s"], (int, float))]

            def _tot(key):
                vals = [c[key] for c in mem_replicas.values()
                        if isinstance(c[key], int)]
                return sum(vals) if vals else None

            mem = {
                "fleet_free_pages": _tot("free_pages"),
                "fleet_resident_pages": _tot("resident_pages"),
                "fleet_leaked_pages": _tot("leaked_pages"),
                "fleet_conservation_breaks": _tot("conservation_breaks"),
                # The MINIMUM across replicas, not the mean: exhaustion is
                # per-pool, and the tightest pool is the one admission and
                # the autoscaler act on.
                "min_forecast_s": min(forecasts) if forecasts else None,
                "replicas": mem_replicas,
            }
        quality = None
        if quality_replicas:
            scores = [
                (c["canary"].get("score"), rid)
                for rid, c in quality_replicas.items()
                if isinstance(c.get("canary"), dict)
                and isinstance(c["canary"].get("score"), (int, float))
            ]
            worst = min(scores) if scores else None
            quality = {
                # The MINIMUM canary score and who holds it, mirroring
                # mem's tightest-pool convention: quality collapse is
                # per-replica, and the worst one is the one the balancer
                # penalty and the drift incident act on.
                "min_canary_score": None if worst is None else worst[0],
                "min_canary_replica": None if worst is None else worst[1],
                "replicas": quality_replicas,
            }
        return {
            "balancer": getattr(self.balancer, "name", type(self.balancer).__name__),
            "max_inflight": self.admission.max_inflight,
            "max_attempts": self.max_attempts,
            # The measured capacity model + (when attached) the autoscaler
            # closing the loop on it (docs/FLEET.md "Autoscaling").
            "capacity": capacity,
            # The memory observatory's fleet view: per-replica pool
            # occupancy, leak/conservation counters, and the tightest
            # exhaustion forecast (docs/OBSERVABILITY.md).
            "mem": mem,
            # The quality observatory's fleet view: per-replica digest
            # confidence + latest canary score, with the worst canary
            # called out (docs/OBSERVABILITY.md "The quality observatory").
            "quality": quality,
            "autoscale": (
                None if self.autoscaler is None else self.autoscaler.status()
            ),
            # Tiered serving: null when disabled, else live membership +
            # shared-prefix-cache occupancy (docs/FLEET.md).
            "tiers": tiers,
            # Multi-tenant surfaces: live admission state (queues, policy
            # table, rate-limit hits, and — under --admission auto — the
            # knee tracker's live state) + per-tenant request accounting
            # with the router-observed goodput ratio.
            "admission": admission,
            "tenants": tenants,
            # The successful-attempt latency ring backing the legacy
            # percentile hedge: explicit bound + live fill level.
            "latency_window": {"size": window_size, "len": window_len},
            "hedge": {
                "mode": hedge_mode,
                "delay_s": None if delay is None else round(delay, 6),
                "estimator_weight": round(self._hedge_estimator.weight(), 3),
            },
            # Model-keyed pools: per-pool membership/role/routable counts
            # plus the ensemble coordinator's discovery + outcome view
            # (docs/FLEET.md "Ensemble serving"). Null when the fleet is
            # homogeneous (no replica shipped a model descriptor).
            "pools": self.registry.pools() or None,
            "ensemble": self.ensemble.stats(),
            "replicas": self.registry.snapshot(),
            "metrics": self.obs.summary(prefix="edgemesh_fleet_"),
            "recent_traces": self.recent_traces(),
            # Incident propagation: the newest replica-fired incidents
            # (id/kind/ts/source) — what an operator greps the incident
            # directory by (docs/FLEET.md "Incident propagation").
            "incidents": self.recent_incidents(),
        }
