"""FleetRouter — the request path that makes multiple replicas one service.

TPI-LLM and the profiling-driven edge-inference line both land on the same
conclusion: once more than one serving unit exists, the router layer — not
the kernels — owns tail latency. This router gives the request path real
robustness semantics on top of the replica registry:

- **Deadlines.** Every request carries a deadline (client-supplied or
  ``default_deadline_s``); the remaining budget is propagated to replicas
  as ``X-Edgemesh-Deadline-S`` (serve/rest.py refuses expired work with a
  504) and bounds every per-attempt timeout, backoff sleep, and hedge wait
  — the router can never spend longer on a request than the client asked.
- **Bounded retries.** Transport failures and replica 5xx are retried up
  to ``max_attempts`` times with jittered exponential backoff
  (``backoff_base_s * 2^attempt``, capped, +0..jitter fraction — the
  standard thundering-herd dampener), each retry on a *different* replica
  (failed ones are excluded; exclusions reset only when every replica has
  failed once). 4xx are the client's problem and return immediately.
- **Hedging.** With ``hedge_after_s`` (fixed) or ``hedge_percentile``
  (adaptive over a rolling window of observed attempt latencies), an
  attempt that outlives the hedge delay gets a second attempt fired at
  another replica; first good answer wins, the loser is abandoned. This
  converts a stalled replica's tail into one extra request of load.
- **Admission control.** A bounded in-flight slot pool: past
  ``max_inflight`` the router sheds with 503 + ``Retry-After`` instead of
  queueing unboundedly — overload stays visible at the edge.
- **Graceful drain.** ``drain_replica`` takes a replica out of rotation,
  calls its ``/drain`` hook, polls ``/readyz`` until in-flight work hits
  zero, then marks it removed — zero dropped requests by construction.

Obs (per-replica labels throughout): routed/retried/hedged/hedged-won/
shed/exhausted counters, drain events, an in-flight gauge, and the router
latency histogram ``edgemesh_fleet_router_seconds`` alongside the engine
spans (docs/FLEET.md has the catalog).
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from collections import deque

from edgemesh.fleet.balancer import make_balancer
from edgemesh.fleet.transport import HttpTransport, TransportError
from edgemesh.serve.httputil import DEADLINE_HEADER

log = logging.getLogger("edgemesh.fleet")


class FleetRouter:
    def __init__(
        self,
        registry,
        balancer: str = "least_outstanding",
        transport=None,
        obs_registry=None,
        max_attempts: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        backoff_jitter: float = 0.5,
        default_deadline_s: float = 60.0,
        attempt_timeout_s: float = 30.0,
        hedge_after_s: float = 0.0,
        hedge_percentile: float = 0.0,
        max_inflight: int = 64,
        demote_after: int = 2,
        rng: random.Random | None = None,
    ) -> None:
        from edgemesh.obs import get_registry

        self.registry = registry
        self.balancer = make_balancer(balancer) if isinstance(balancer, str) else balancer
        self.transport = transport or HttpTransport()
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.backoff_jitter = backoff_jitter
        self.default_deadline_s = default_deadline_s
        self.attempt_timeout_s = attempt_timeout_s
        self.hedge_after_s = hedge_after_s
        self.hedge_percentile = hedge_percentile
        self.max_inflight = max_inflight
        self.demote_after = demote_after
        self._rng = rng or random.Random(0)
        self._sleep = time.sleep  # injectable: tests pin the backoff schedule
        self._slots = threading.BoundedSemaphore(max_inflight)
        # Rolling successful-attempt latencies for the adaptive hedge delay.
        # Locked: sorting the deque while another handler thread appends
        # raises "deque mutated during iteration".
        self._lat_lock = threading.Lock()
        self._lat_window: deque[float] = deque(maxlen=256)

        reg = obs_registry or get_registry()
        self.obs = reg
        self._routed = reg.counter(
            "edgemesh_fleet_routed_total",
            "Requests answered, by replica that answered", ("replica",),
        )
        self._retried = reg.counter(
            "edgemesh_fleet_retried_total",
            "Failed attempts that triggered a retry, by replica and reason",
            ("replica", "reason"),
        )
        self._hedged = reg.counter(
            "edgemesh_fleet_hedged_total",
            "Hedge attempts fired, by hedge replica", ("replica",),
        )
        self._hedged_won = reg.counter(
            "edgemesh_fleet_hedged_won_total",
            "Hedge attempts that beat the primary, by replica", ("replica",),
        )
        self._shed = reg.counter(
            "edgemesh_fleet_shed_total",
            "Requests shed without reaching a replica, by reason", ("reason",),
        )
        self._exhausted = reg.counter(
            "edgemesh_fleet_exhausted_total",
            "Requests that failed every attempt",
        )
        self._drain_events = reg.counter(
            "edgemesh_fleet_drain_total",
            "Drain lifecycle events", ("replica", "event"),
        )
        self._inflight_gauge = reg.gauge(
            "edgemesh_fleet_inflight", "Requests currently inside the router",
        )
        self._latency = reg.histogram(
            "edgemesh_fleet_router_seconds",
            "End-to-end router request latency (admission to answer)",
        )

    # -- request path --------------------------------------------------------

    def handle_generate(self, payload: dict, deadline_s: float | None = None,
                        path: str = "/generate"):
        """Route one request. Returns ``(status, body, headers)`` — the
        HTTP frontend writes them verbatim; in-process callers (tests,
        benchmarks) read them directly."""
        t0 = time.monotonic()
        if not self._slots.acquire(blocking=False):
            self._shed.labels(reason="overload").inc()
            return 503, {"error": "router at capacity", "max_inflight": self.max_inflight}, \
                {"Retry-After": "1"}
        self._inflight_gauge.inc()
        try:
            return self._route(payload, t0, deadline_s, path)
        finally:
            self._inflight_gauge.dec()
            self._slots.release()

    def _route(self, payload, t0, deadline_s, path):
        deadline = t0 + (deadline_s if deadline_s is not None else self.default_deadline_s)
        prompt = payload.get("question") if isinstance(payload, dict) else None
        excluded: set[str] = set()
        last_error: str = "no attempt made"
        for attempt in range(self.max_attempts):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._shed.labels(reason="deadline").inc()
                return 504, {"error": "deadline exceeded", "attempts": attempt,
                             "last_error": last_error}, {}
            rep = self.registry.acquire(self.balancer, prompt=prompt, exclude=excluded)
            if rep is None and excluded:
                # Every routable replica has failed once this request:
                # reset exclusions rather than give up with replicas alive.
                excluded.clear()
                rep = self.registry.acquire(self.balancer, prompt=prompt, exclude=excluded)
            if rep is None:
                self._shed.labels(reason="no_replica").inc()
                return 503, {"error": "no available replica"}, {"Retry-After": "1"}
            outcome = self._dispatch(rep, payload, path, deadline, prompt, excluded)
            if outcome[0] == "ok":
                _, rid, status, body = outcome
                self._routed.labels(replica=rid).inc()
                self._latency.observe(time.monotonic() - t0)
                return status, body, {
                    "X-Edgemesh-Replica": rid,
                    "X-Edgemesh-Attempts": str(attempt + 1),
                }
            failures = outcome[1]  # [(rid, reason, detail), ...]
            for rid, reason, detail in failures:
                excluded.add(rid)
                last_error = f"{rid}: {reason}: {detail}"
                log.warning("attempt %d on %s failed (%s): %s",
                            attempt + 1, rid, reason, detail)
            if attempt + 1 < self.max_attempts:
                for rid, reason, _ in failures:
                    self._retried.labels(replica=rid, reason=reason).inc()
                self._sleep(self._backoff(attempt, deadline))
        self._exhausted.inc()
        return 502, {"error": "all attempts failed",
                     "attempts": self.max_attempts,
                     "last_error": last_error}, {}

    def _backoff(self, attempt: int, deadline: float) -> float:
        delay = min(self.backoff_cap_s, self.backoff_base_s * (2 ** attempt))
        delay *= 1.0 + self.backoff_jitter * self._rng.random()
        return max(0.0, min(delay, deadline - time.monotonic()))

    # -- attempts ------------------------------------------------------------

    def _attempt_one(self, rep, payload, path, deadline):
        """One checked-out attempt → ("ok", rid, status, body) for any
        answered status < 500, else ("fail", rid, reason, detail)."""
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            self.registry.release(rep.rid, ok=False, demote_after=self.demote_after,
                                  error="deadline exceeded before dispatch")
            return ("fail", rep.rid, "deadline", "expired before dispatch")
        timeout_s = min(self.attempt_timeout_s, remaining)
        headers = {DEADLINE_HEADER: f"{remaining:.3f}"}
        t0 = time.monotonic()
        try:
            status, body = self.transport.post_json(
                rep.url(path), payload, timeout_s=timeout_s, headers=headers
            )
        except TransportError as e:
            self.registry.release(rep.rid, ok=False, demote_after=self.demote_after,
                                  error=str(e))
            return ("fail", rep.rid, "connect", str(e))
        if status >= 500:
            self.registry.release(rep.rid, ok=False, demote_after=self.demote_after,
                                  error=f"status {status}")
            return ("fail", rep.rid, f"status_{status}", str(body.get("error", body))[:200])
        self.registry.release(rep.rid, ok=True)
        with self._lat_lock:
            self._lat_window.append(time.monotonic() - t0)
        return ("ok", rep.rid, status, body)

    def _hedge_delay(self) -> float | None:
        if self.hedge_after_s:
            return self.hedge_after_s
        if self.hedge_percentile:
            with self._lat_lock:
                xs = sorted(self._lat_window)
            if len(xs) >= 16:
                return xs[min(len(xs) - 1, int(self.hedge_percentile * len(xs)))]
        return None

    def _dispatch(self, rep, payload, path, deadline, prompt, excluded):
        """One attempt round, hedged when configured. Returns
        ("ok", rid, status, body) or ("fail", [(rid, reason, detail), ...])."""
        hedge_delay = self._hedge_delay()
        if hedge_delay is None or hedge_delay >= (deadline - time.monotonic()):
            out = self._attempt_one(rep, payload, path, deadline)
            return out if out[0] == "ok" else ("fail", [out[1:]])

        results: queue.Queue = queue.Queue()

        def run(replica, is_hedge):
            results.put((is_hedge, self._attempt_one(replica, payload, path, deadline)))

        threading.Thread(target=run, args=(rep, False), daemon=True).start()
        try:
            first = results.get(timeout=hedge_delay)
        except queue.Empty:
            first = None
        if first is not None:
            if first[1][0] == "ok":
                return first[1]  # primary answered inside the hedge window
            # A FAST failure is not a tail-latency event: hand it to the
            # normal retry path (backoff + retried counters) instead of
            # firing a zero-backoff failover dressed up as a hedge — the
            # hedged metrics must mean "the primary was slow", nothing else.
            return ("fail", [first[1][1:]])

        hedge_rep = self.registry.acquire(
            self.balancer, prompt=prompt, exclude=excluded | {rep.rid}
        )
        if hedge_rep is not None:
            self._hedged.labels(replica=hedge_rep.rid).inc()
            threading.Thread(target=run, args=(hedge_rep, True), daemon=True).start()

        # Drain results until a winner or both attempts have reported. The
        # per-attempt transport timeout bounds the usual stalls, but it is
        # a per-socket-op bound — a replica trickling one byte per read
        # never trips it — so the get() itself is ALSO capped by the
        # request deadline: past it the attempts are abandoned and the
        # router answers within the client's budget.
        pending = 2 if hedge_rep is not None else 1
        failures = []
        while pending > 0:
            try:
                is_hedge, out = results.get(
                    timeout=max(0.05, deadline - time.monotonic())
                )
            except queue.Empty:
                failures.append(
                    (rep.rid, "deadline", "attempt outlived the request deadline")
                )
                break
            pending -= 1
            if out[0] == "ok":
                if is_hedge:
                    self._hedged_won.labels(replica=out[1]).inc()
                return out
            failures.append(out[1:])
        return ("fail", failures or [(rep.rid, "hedge", "no attempt completed")])

    # -- drain ---------------------------------------------------------------

    def drain_replica(self, rid: str, timeout_s: float = 60.0,
                      poll_s: float = 0.2) -> dict:
        """Gracefully remove ``rid``: out of rotation immediately, then the
        replica's ``/drain`` hook fires and ``/readyz`` is polled until its
        in-flight count reaches zero (or ``timeout_s``). In-flight requests
        finish; only then is the replica safe to stop."""
        rep = self.registry.get(rid)
        if rep is None:
            return {"replica": rid, "error": "unknown replica"}
        self.registry.set_state(rid, "draining")
        self._drain_events.labels(replica=rid, event="started").inc()
        try:
            self.transport.post_json(rep.url("/drain"), {},
                                     timeout_s=self.attempt_timeout_s)
        except TransportError as e:
            log.warning("drain hook on %s failed: %s", rid, e)
        deadline = time.monotonic() + timeout_s
        inflight: int | None = None
        fail_streak = 0
        while time.monotonic() < deadline:
            # Router-tracked outstanding covers requests we routed; the
            # replica's own /readyz inflight covers direct clients too.
            try:
                _, body = self.transport.get_json(
                    rep.url("/readyz"), timeout_s=self.attempt_timeout_s
                )
                inflight = body.get("inflight")
                fail_streak = 0
            except TransportError:
                # One failed poll is indistinguishable from a GC pause; only
                # a STREAK means the replica is actually gone (nothing left
                # to drain). A transient error must not declare the drain
                # complete while direct-client requests still run.
                fail_streak += 1
                inflight = None
                if fail_streak >= 3:
                    inflight = 0
            if inflight == 0 and rep.outstanding == 0:
                break
            self._sleep(poll_s)
        drained = inflight == 0 and rep.outstanding == 0
        self.registry.set_state(rid, "removed")
        self._drain_events.labels(
            replica=rid, event="completed" if drained else "timeout"
        ).inc()
        return {"replica": rid, "drained": drained, "inflight": inflight}

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        return {
            "balancer": getattr(self.balancer, "name", type(self.balancer).__name__),
            "max_inflight": self.max_inflight,
            "max_attempts": self.max_attempts,
            "replicas": self.registry.snapshot(),
            "metrics": self.obs.summary(prefix="edgemesh_fleet_"),
        }
