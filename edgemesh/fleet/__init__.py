"""edgemesh.fleet — multi-replica serving fabric.

The layer that turns N independent ``serve_rest`` processes into one
service (docs/FLEET.md is the operator-facing reference):

- ``registry``: live replica membership + health state machine.
- ``balancer``: round-robin / least-outstanding / prefix-affinity
  (rendezvous-hashed so replica death only remaps its own prefixes) /
  telemetry (weights replicas by the load digests their ``/readyz``
  bodies ship — observed queue+prefill EWMAs, decaying to
  least-outstanding when digests go stale).
- ``health``: periodic ``/readyz`` probes with automatic demote/promote;
  each probe also refreshes the replica's load digest for free.
- ``canary``: golden-set answer-quality probes — per-replica token-F1
  scores the telemetry balancer down-weights on, collapsing scores
  minting fleet-wide ``quality_drift`` incidents (docs/OBSERVABILITY.md
  "The quality observatory").
- ``router``: deadlines, bounded jittered retries, tail-latency hedging
  (fixed, percentile, or auto-tuned from a decayed latency histogram),
  admission control (503 + Retry-After), graceful drain.
- ``autotune``: knee-tracking admission — an AIMD tuner that drives
  ``max_inflight`` (and per-tenant rates) toward the live
  goodput-vs-load knee instead of a static guess.
- ``autoscale``: replica spawn/drain from the digests' arrival-rate vs
  capacity-estimate split, with incidents as a scale-up signal and
  warm starts off a shared persistent compilation cache.
- ``ensemble``: the ``POST /ensemble`` coordinator — parallel QA fan-out
  across model-keyed pools + the refiner pipeline, with graceful
  degradation as a first-class state machine.
- ``frontend``: the HTTP listener (``/generate``, ``/ensemble``,
  ``/fleetz``, ``/metrics``, runtime ``/replicas/*`` membership).
- ``cli``: ``edgemesh fleet serve|status`` — spawn N local replicas and
  front them, or inspect a running fleet.

Importing this package never imports jax (the router runs on hosts with no
accelerator at all — same contract as edgemesh.obs), and every outbound
call carries an explicit timeout (enforced by the wire pass, EM502).
"""

from edgemesh.fleet.balancer import (  # noqa: F401
    BALANCERS,
    LeastOutstandingBalancer,
    PrefixAffinityBalancer,
    RoundRobinBalancer,
    TelemetryBalancer,
    make_balancer,
)
from edgemesh.fleet.autoscale import AutoScaler  # noqa: F401
from edgemesh.fleet.canary import CanaryProber, load_golden_set  # noqa: F401
from edgemesh.fleet.autotune import KneeTracker  # noqa: F401
from edgemesh.fleet.ensemble import EnsembleCoordinator  # noqa: F401
from edgemesh.fleet.frontend import serve_fleet  # noqa: F401
from edgemesh.fleet.health import HealthProber  # noqa: F401
from edgemesh.fleet.registry import Replica, ReplicaRegistry  # noqa: F401
from edgemesh.fleet.router import FleetRouter  # noqa: F401
from edgemesh.fleet.transport import HttpTransport, TransportError  # noqa: F401
