"""Pluggable balancers: how the router picks a replica for one request.

Four policies, selected by name via ``make_balancer``:

- ``round_robin``: cycle registration order. Baseline; ignores load.
- ``least_outstanding``: fewest in-flight requests wins (ties break by
  registration order). The sane default for decode workloads whose service
  times vary by an order of magnitude — queue depth IS the load signal.
- ``prefix_affinity``: requests sharing a prompt prefix land on the same
  replica, so that replica's ``runtime/prefix_cache`` (and on the paged
  engines, its shared template pages) already hold the prefix KV — the
  fleet-level analog of template prefix sharing (docs/SERVING.md).
  Placement is rendezvous (highest-random-weight) hashing of
  ``sha256(prefix, replica-id)``: every (key, replica) pair gets a stable
  pseudo-random score and the max score wins, so when a replica dies ONLY
  its own keys remap — the surviving replicas keep every prefix they have
  already warmed (plain modulo hashing would reshuffle nearly all keys).
- ``telemetry``: weight replicas by their OBSERVED load digests (queue +
  prefill latency EWMAs shipped on ``/readyz``, refreshed by the health
  prober — fleet/health.py) instead of outstanding counts alone — the
  profiling-driven-placement thesis (PAPERS.md: arXiv 2605.25682,
  TPI-LLM). Trust in a digest decays linearly with its receiver-side age
  and hits zero at ``stale_after_s``, where the policy degrades to exactly
  least-outstanding: stale telemetry must never outvote live queue depth,
  and a cold replica (no digest yet) competes on its outstanding count
  rather than starving (docs/FLEET.md "Adaptive routing").

``pick`` is called under the registry lock with a non-empty candidate list
(fleet/registry.py ``acquire``), so reading ``outstanding``/``load`` is
race-free and balancer state needs no extra locking.

No jax imports — the router stack must stay importable on a host with no
accelerator backend at all (same contract as edgemesh.obs).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Sequence


class RoundRobinBalancer:
    name = "round_robin"

    def __init__(self) -> None:
        self._n = 0

    def pick(self, candidates: Sequence, prompt: str | None = None):
        rep = candidates[self._n % len(candidates)]
        self._n += 1
        return rep


class LeastOutstandingBalancer:
    name = "least_outstanding"

    def pick(self, candidates: Sequence, prompt: str | None = None):
        return min(enumerate(candidates), key=lambda t: (t[1].outstanding, t[0]))[1]


class PrefixAffinityBalancer:
    """Rendezvous-hash the prompt prefix onto a replica.

    ``prefix_chars`` bounds the key: requests that share at least the
    template + leading question characters hash identically, which is what
    the replica-side prefix cache keys on. ``spill_margin`` is the overload
    escape hatch: when the affine replica already carries that many more
    outstanding requests than the least-loaded candidate, the request
    spills to least-outstanding instead — affinity is a cache hint, not a
    correctness constraint, and a hot prefix must not melt one replica.
    """

    name = "prefix_affinity"

    def __init__(self, prefix_chars: int = 64, spill_margin: int = 8) -> None:
        self.prefix_chars = prefix_chars
        self.spill_margin = spill_margin
        self._fallback = LeastOutstandingBalancer()

    @staticmethod
    def _score(key: str, rid: str) -> int:
        # sha256, not hash(): str hashing is PYTHONHASHSEED-randomized per
        # process, which would break affinity across router restarts.
        digest = hashlib.sha256(f"{key}\x1f{rid}".encode("utf-8", "replace"))
        return int.from_bytes(digest.digest()[:8], "big")

    def pick(self, candidates: Sequence, prompt: str | None = None):
        if not prompt:
            return self._fallback.pick(candidates, prompt)
        key = prompt[: self.prefix_chars]
        chosen = max(candidates, key=lambda r: self._score(key, r.rid))
        least = min(r.outstanding for r in candidates)
        if chosen.outstanding - least > self.spill_margin:
            return self._fallback.pick(candidates, prompt)
        return chosen


class TelemetryBalancer:
    """Pick the replica with the lowest *observed* expected wait.

    Each candidate is scored by its expected COMPLETION time in seconds —
    the backlog it would queue behind plus the request's own expected
    service there (an idle-but-slow replica must not win picks just
    because it is idle)::

        telem    = ewma_queue_s + ewma_prefill_s
                   + (outstanding + 1) * ewma_service_s
                   [+ compile_penalty_s while recent_compile]
        cost     = freshness * telem
                   + (1 - freshness) * outstanding * neutral_service_s

    ``freshness`` decays linearly from 1 (digest just arrived) to 0 at
    ``stale_after_s`` of receiver-side age, so the two regimes blend:
    fully fresh digests route on observed queue+prefill latency (a slow or
    compiling replica is avoided even when idle), fully stale ones reduce
    the cost to ``outstanding * neutral_service_s`` — exactly
    least-outstanding ordering, ties broken by registration order. A cold
    replica with no digest at all has freshness 0 by definition: it is
    never starved, it simply competes on live queue depth until its first
    probe lands. ``outstanding`` is read live from the registry (not the
    digest), so the loop self-limits between probe refreshes instead of
    herding every request at the currently-fastest replica.
    """

    name = "telemetry"

    def __init__(self, stale_after_s: float = 15.0,
                 neutral_service_s: float = 0.1,
                 compile_penalty_s: float = 0.5,
                 quality_penalty_s: float = 2.0,
                 canary_floor: float = 0.3,
                 canary_stale_after_s: float = 120.0,
                 now=time.monotonic) -> None:
        if stale_after_s <= 0:
            raise ValueError(f"stale_after_s must be > 0, got {stale_after_s}")
        self.stale_after_s = float(stale_after_s)
        self.neutral_service_s = float(neutral_service_s)
        self.compile_penalty_s = float(compile_penalty_s)
        self.quality_penalty_s = float(quality_penalty_s)
        self.canary_floor = float(canary_floor)
        self.canary_stale_after_s = float(canary_stale_after_s)
        self._now = now  # injectable: tests pin digest aging

    def _cost(self, rep) -> float:
        # The quality penalty rides OUTSIDE the digest-freshness blend:
        # the canary score is an independent registry-side signal with its
        # own freshness, and a degraded replica must lose picks even when
        # its load digest is stale or missing.
        quality = self._quality_penalty(rep)
        age = None
        if getattr(rep, "load_ts", None) is not None:
            age = self._now() - rep.load_ts
        neutral = rep.outstanding * self.neutral_service_s
        load = getattr(rep, "load", None)
        if age is None or age >= self.stale_after_s or not isinstance(load, dict):
            return neutral + quality
        freshness = max(0.0, 1.0 - age / self.stale_after_s)
        queue = load.get("ewma_queue_s")
        prefill = load.get("ewma_prefill_s")
        service = load.get("ewma_service_s")
        if service is None:
            # Before the first request completes, the span-level service
            # EWMA is null — but the compute ledger may already have
            # measured decode launches (digest["costs"]). A launch EWMA
            # is a per-segment time, not a per-request one, so it
            # underestimates — still far better directionally than the
            # queue+prefill fallback below. Digests WITHOUT a cost block
            # (older replicas, ledger disabled) score exactly as before.
            service = self._cost_service_s(load)
        if queue is None and prefill is None and service is None:
            # A digest with no latency telemetry yet (non-continuous
            # gateway, or a continuous replica before its first request)
            # must score like NO digest — scoring the nulls as zero cost
            # would herd every pick at the least-instrumented replica.
            return neutral + quality
        queue = queue or 0.0
        prefill = prefill or 0.0
        service = service if service is not None else (queue + prefill)
        telem = queue + prefill + (rep.outstanding + 1) * service
        if load.get("recent_compile"):
            telem += self.compile_penalty_s
        telem += self._mem_penalty(load)
        return freshness * telem + (1.0 - freshness) * neutral + quality

    def _quality_penalty(self, rep) -> float:
        """Seconds of penalty for a replica whose golden-set canary score
        (fleet/canary.py, registry ``update_canary``) sits below the
        floor. Scales with the deficit and decays with canary age — the
        prober's cadence bounds how long a recovered replica stays
        penalized. A replica with no canary result (prober off, replica
        never probed, malformed entry) costs exactly 0.0 — scoring
        unchanged, same contract as ``_mem_penalty``. Down-weighting, not
        exclusion: the drift incident, not the balancer, is what takes a
        degraded replica out of a human's rotation."""
        canary = getattr(rep, "canary", None)
        ts = getattr(rep, "canary_ts", None)
        if not isinstance(canary, dict) or ts is None:
            return 0.0
        age = self._now() - ts
        if age >= self.canary_stale_after_s:
            return 0.0
        score = canary.get("score")
        if not isinstance(score, (int, float)):
            return 0.0
        deficit = self.canary_floor - min(1.0, max(0.0, float(score)))
        if deficit <= 0 or self.canary_floor <= 0:
            return 0.0
        freshness = max(0.0, 1.0 - age / self.canary_stale_after_s)
        return freshness * self.quality_penalty_s * deficit / self.canary_floor

    @staticmethod
    def _mem_penalty(load: dict) -> float:
        """Seconds of penalty for a replica whose page pool is nearly
        exhausted, from the digest's ``mem`` block (obs/memory.py
        ``digest_mem``). Scales inversely with the exhaustion forecast
        below a 10 s horizon — a replica about to wedge its pool should
        lose ties to one with headroom, without ever being hard-excluded
        (under fleet-wide pressure SOMEONE still has to serve). Digests
        without a mem block (dense backends, pre-mem replicas, ledger
        disabled) cost exactly 0.0 — scoring unchanged."""
        mem = load.get("mem")
        if not isinstance(mem, dict):
            return 0.0
        forecast = mem.get("forecast_s")
        if not isinstance(forecast, (int, float)) or forecast < 0:
            return 0.0
        if forecast >= 10.0:
            return 0.0
        return (10.0 - float(forecast)) / 10.0

    @staticmethod
    def _cost_service_s(load: dict) -> float | None:
        """Measured decode-launch EWMA from the digest's per-boundary
        cost block (obs/compute.py ``digest_costs``), or None when the
        digest carries no cost block or no decode boundary measured yet."""
        costs = load.get("costs")
        if not isinstance(costs, dict):
            return None
        for boundary, cell in costs.items():
            if boundary not in ("decode_loop", "spec_rounds"):
                continue
            if not isinstance(cell, dict):
                continue
            v = cell.get("ewma_launch_s")
            if isinstance(v, (int, float)) and v > 0:
                return float(v)
        return None

    def pick(self, candidates: Sequence, prompt: str | None = None):
        return min(
            enumerate(candidates), key=lambda t: (self._cost(t[1]), t[0])
        )[1]


class TierManager:
    """Dynamic prefill/decode tier membership for disaggregated serving.

    Scores every routable replica by its OBSERVED phase mix — the
    ``ewma_prefill_tokens`` / ``ewma_decode_tokens`` split each load digest
    ships (obs/spans.py, refreshed by the health prober) — and assigns the
    most prefill-heavy ``prefill_fraction`` of the fleet to the prefill
    tier, the rest to the decode tier. Membership is therefore DYNAMIC and
    self-reinforcing: the router sends long prefills to the prefill tier,
    which keeps those replicas' prefill share high, which keeps them in the
    tier — while a workload shift (the longs dry up) decays the EWMAs and
    membership follows within a few requests. A replica with no digest yet
    scores the neutral 0.5 and ties break by replica id, so a cold fleet
    still gets a stable, deterministic split.

    Guard rails the router's graceful-degradation contract relies on:

    - fewer than two routable replicas → NO prefill tier (``assign``
      returns every replica as decode) — the router must fall back to
      homogeneous serving rather than starve either phase;
    - the prefill tier never exceeds n-1 replicas and never drops below 1
      (when tiering is possible at all);
    - ``hysteresis`` biases incumbents' scores so membership doesn't flap
      when two replicas' shares cross by noise;
    - assignments are cached for ``refresh_s`` (the router reads tiers on
      every request; scoring is O(n log n)) and ``invalidate()`` — wired
      to the prober's digest refresh — forces a recompute on fresh data.
    """

    name = "tiers"

    def __init__(self, prefill_fraction: float = 1 / 3,
                 refresh_s: float = 1.0, hysteresis: float = 0.1,
                 now=time.monotonic) -> None:
        if not 0.0 < prefill_fraction < 1.0:
            raise ValueError(
                f"prefill_fraction must be in (0, 1), got {prefill_fraction}"
            )
        self.prefill_fraction = float(prefill_fraction)
        self.refresh_s = float(refresh_s)
        self.hysteresis = float(hysteresis)
        self._now = now  # injectable: tests pin the refresh window
        self._lock = threading.Lock()
        self._cached: dict | None = None  # guarded by: _lock
        self._cached_ts: float | None = None  # guarded by: _lock
        self._cached_rids: frozenset | None = None  # guarded by: _lock
        self._prefill_rids: frozenset = frozenset()  # guarded by: _lock

    @staticmethod
    def _prefill_share(rep) -> float:
        load = getattr(rep, "load", None)
        if not isinstance(load, dict):
            return 0.5
        pt = load.get("ewma_prefill_tokens")
        dt = load.get("ewma_decode_tokens")
        if pt is None and dt is None:
            return 0.5
        pt, dt = float(pt or 0.0), float(dt or 0.0)
        return pt / (pt + dt) if pt + dt > 0 else 0.5

    def invalidate(self) -> None:
        """Drop the cached assignment (fresh digests arrived)."""
        with self._lock:
            self._cached_ts = None

    def forget(self, rid: str) -> None:
        """Purge one replica's tier membership (deregister/removal —
        fleet/router.py ``forget_replica``): its hysteresis incumbency
        must not survive into a re-registered incarnation, and the cached
        assignment that may still hold the dead Replica object drops."""
        with self._lock:
            if rid in self._prefill_rids:
                self._prefill_rids = self._prefill_rids - {rid}
            self._cached_ts = None

    def assign(self, replicas: Sequence) -> dict:
        """``{"prefill": [...], "decode": [...]}`` over the routable subset
        of ``replicas``. Never raises; an un-tierable fleet comes back with
        an empty prefill list (the caller's homogeneous-fallback signal)."""
        healthy = [r for r in replicas if r.routable()]
        rids = frozenset(r.rid for r in healthy)
        now = self._now()
        with self._lock:
            if (
                self._cached is not None
                and self._cached_ts is not None
                and now - self._cached_ts < self.refresh_s
                and rids == self._cached_rids
            ):
                return self._cached
            if len(healthy) < 2:
                out = {"prefill": [], "decode": healthy}
                self._cached, self._cached_ts = out, now
                self._cached_rids = rids
                self._prefill_rids = frozenset()
                return out
            prev = self._prefill_rids
            order = sorted(
                healthy,
                key=lambda r: (
                    -(self._prefill_share(r)
                      + (self.hysteresis if r.rid in prev else 0.0)),
                    r.rid,
                ),
            )
            n_pre = max(1, min(len(healthy) - 1,
                               round(self.prefill_fraction * len(healthy))))
            out = {"prefill": order[:n_pre], "decode": order[n_pre:]}
            self._cached, self._cached_ts = out, now
            self._cached_rids = rids
            self._prefill_rids = frozenset(r.rid for r in out["prefill"])
            return out


BALANCERS = {
    "round_robin": RoundRobinBalancer,
    "least_outstanding": LeastOutstandingBalancer,
    "prefix_affinity": PrefixAffinityBalancer,
    "telemetry": TelemetryBalancer,
}


def make_balancer(name: str, **kwargs):
    """Build a balancer by policy name. Unknown names list the choices;
    kwargs a policy does not accept surface as a ValueError naming the
    policy (not a bare TypeError from deep inside a constructor)."""
    try:
        cls = BALANCERS[name]
    except KeyError:
        raise ValueError(
            f"unknown balancer {name!r}; choose from {sorted(BALANCERS)}"
        ) from None
    try:
        return cls(**kwargs)
    except TypeError as e:
        raise ValueError(f"bad arguments for balancer {name!r}: {e}") from e
