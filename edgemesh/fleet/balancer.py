"""Pluggable balancers: how the router picks a replica for one request.

Three policies, selected by name via ``make_balancer``:

- ``round_robin``: cycle registration order. Baseline; ignores load.
- ``least_outstanding``: fewest in-flight requests wins (ties break by
  registration order). The sane default for decode workloads whose service
  times vary by an order of magnitude — queue depth IS the load signal.
- ``prefix_affinity``: requests sharing a prompt prefix land on the same
  replica, so that replica's ``runtime/prefix_cache`` (and on the paged
  engines, its shared template pages) already hold the prefix KV — the
  fleet-level analog of template prefix sharing (docs/SERVING.md).
  Placement is rendezvous (highest-random-weight) hashing of
  ``sha256(prefix, replica-id)``: every (key, replica) pair gets a stable
  pseudo-random score and the max score wins, so when a replica dies ONLY
  its own keys remap — the surviving replicas keep every prefix they have
  already warmed (plain modulo hashing would reshuffle nearly all keys).

``pick`` is called under the registry lock with a non-empty candidate list
(fleet/registry.py ``acquire``), so reading ``outstanding`` is race-free
and balancer state needs no extra locking.

No jax imports — the router stack must stay importable on a host with no
accelerator backend at all (same contract as edgemesh.obs).
"""

from __future__ import annotations

import hashlib
from typing import Sequence


class RoundRobinBalancer:
    name = "round_robin"

    def __init__(self) -> None:
        self._n = 0

    def pick(self, candidates: Sequence, prompt: str | None = None):
        rep = candidates[self._n % len(candidates)]
        self._n += 1
        return rep


class LeastOutstandingBalancer:
    name = "least_outstanding"

    def pick(self, candidates: Sequence, prompt: str | None = None):
        return min(enumerate(candidates), key=lambda t: (t[1].outstanding, t[0]))[1]


class PrefixAffinityBalancer:
    """Rendezvous-hash the prompt prefix onto a replica.

    ``prefix_chars`` bounds the key: requests that share at least the
    template + leading question characters hash identically, which is what
    the replica-side prefix cache keys on. ``spill_margin`` is the overload
    escape hatch: when the affine replica already carries that many more
    outstanding requests than the least-loaded candidate, the request
    spills to least-outstanding instead — affinity is a cache hint, not a
    correctness constraint, and a hot prefix must not melt one replica.
    """

    name = "prefix_affinity"

    def __init__(self, prefix_chars: int = 64, spill_margin: int = 8) -> None:
        self.prefix_chars = prefix_chars
        self.spill_margin = spill_margin
        self._fallback = LeastOutstandingBalancer()

    @staticmethod
    def _score(key: str, rid: str) -> int:
        # sha256, not hash(): str hashing is PYTHONHASHSEED-randomized per
        # process, which would break affinity across router restarts.
        digest = hashlib.sha256(f"{key}\x1f{rid}".encode("utf-8", "replace"))
        return int.from_bytes(digest.digest()[:8], "big")

    def pick(self, candidates: Sequence, prompt: str | None = None):
        if not prompt:
            return self._fallback.pick(candidates, prompt)
        key = prompt[: self.prefix_chars]
        chosen = max(candidates, key=lambda r: self._score(key, r.rid))
        least = min(r.outstanding for r in candidates)
        if chosen.outstanding - least > self.spill_margin:
            return self._fallback.pick(candidates, prompt)
        return chosen


BALANCERS = {
    "round_robin": RoundRobinBalancer,
    "least_outstanding": LeastOutstandingBalancer,
    "prefix_affinity": PrefixAffinityBalancer,
}


def make_balancer(name: str, **kwargs):
    """Build a balancer by policy name; unknown names list the choices."""
    try:
        cls = BALANCERS[name]
    except KeyError:
        raise ValueError(
            f"unknown balancer {name!r}; choose from {sorted(BALANCERS)}"
        ) from None
    return cls(**kwargs)
