"""Knee-tracking admission: ``max_inflight`` tuned by measurement.

PR 9's load observatory made the goodput-vs-offered-load curve and its
saturation knee a measurable object; this module is the first consumer that
CLOSES the loop (ROADMAP "self-driving fleet"). The router's static
``max_inflight`` is an operator guess standing in for a measured quantity:
the concurrency at which goodput peaks. Too high, queueing delay eats the
SLO budget past the knee; too low, the fleet sheds work it could have
served. The :class:`KneeTracker` replaces the guess with an online AIMD
controller fed by the router's own per-window observations:

- Every routed request reports ``(answered, good)`` — "good" is the
  router-observed response-latency SLO the per-tenant accounting already
  computes. Windows of ``window_s`` close into one curve point
  ``{offered_rps, goodput_rps}``, appended to a bounded history that
  :func:`edgemesh.loadgen.curve.find_knee` — the SAME math the offline
  ``load_curve`` bench stage uses — turns into a live knee estimate.
- **Additive increase**: after ``patience`` consecutive windows at or
  above ``goodput_target``, the limit grows by ``increase`` per window
  (up to ``ceiling``) — headroom is probed, never assumed.
- **Multiplicative decrease**: after ``patience`` consecutive BAD windows
  (the ANSWERED requests' SLO-good ratio below the hysteresis band —
  queueing delay eating the budget is the limit-too-high signal; sheds
  stay out of this ratio or sustained open-loop overload would read the
  correct limit as a bad one — or offered load past the live knee with
  window goodput collapsed more than ``collapse_tolerance`` below the
  knee's), the limit cuts to ``decrease`` of itself, floored at
  ``floor`` — the fleet must never be tuned into refusing all work.
- The band between good and bad is a DEAD ZONE: windows there reset both
  streaks, so oscillating arrivals straddling the target hold the limit
  steady instead of flapping it (the hysteresis the tests pin).
- **Incident freeze**: a propagated incident (obs/anomaly.py → the
  router's ``observe_incident``) freezes tuning for ``freeze_s`` —
  degraded-fleet windows are measurements of the incident, not of the
  limit, and acting on them would chase the failure downward.

Per-tenant rate limits scale WITH the limit: ``rate_scale`` =
limit / initial limit, applied through
:meth:`~edgemesh.fleet.admission.AdmissionController.set_rate_scale`, so a
tuned-down fleet tightens every configured tenant bucket proportionally
instead of letting one tenant's static rate override the measured
capacity.

No jax imports (the router-stack contract); the clock is injectable so
tests drive synthetic curves deterministically.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from edgemesh.loadgen.curve import find_knee

TUNE_RECORD_EVENT = "admission_tune"


class KneeTracker:
    """Online AIMD tuner for :class:`~edgemesh.fleet.admission.
    AdmissionController.max_inflight`, tracking the live saturation knee.

    ``admission`` is the controller to drive; ``log`` is an optional
    ``JsonlLogger``-shaped sink (the router passes its span log) that gets
    one ``admission_tune`` record per adjustment — the postmortem/`obs
    summary` trail of what the controller did and why.
    """

    def __init__(self, admission, floor: int = 2, ceiling: int = 256,
                 window_s: float = 2.0, increase: int = 1,
                 decrease: float = 0.7, goodput_target: float = 0.9,
                 bad_band: float = 0.15, collapse_tolerance: float = 0.1,
                 patience: int = 2, history: int = 32,
                 freeze_s: float = 30.0, min_window_requests: int = 4,
                 obs_registry=None, log=None,
                 now=time.monotonic) -> None:
        from edgemesh.obs import get_registry

        if floor < 1:
            raise ValueError(f"floor must be >= 1, got {floor}")
        if ceiling < floor:
            raise ValueError(
                f"ceiling must be >= floor, got {ceiling} < {floor}")
        if not 0.0 < decrease < 1.0:
            raise ValueError(f"decrease must be in (0, 1), got {decrease}")
        self.admission = admission
        self.floor = int(floor)
        self.ceiling = int(ceiling)
        self.window_s = float(window_s)
        self.increase = int(increase)
        self.decrease = float(decrease)
        self.goodput_target = float(goodput_target)
        self.bad_band = float(bad_band)
        self.collapse_tolerance = float(collapse_tolerance)
        self.patience = int(patience)
        self.freeze_s = float(freeze_s)
        self.min_window_requests = int(min_window_requests)
        self._now = now
        self._log = log
        # The configured limit is just the controller's starting point —
        # clamp it into [floor, ceiling] immediately (a default
        # max_inflight above the ceiling would otherwise serve out-of-band
        # until the first decrease).
        start = min(self.ceiling, max(self.floor, int(admission.max_inflight)))
        if start != admission.max_inflight:
            admission.set_max_inflight(start)
        # The initial limit anchors the per-tenant rate scale:
        # scale = limit / initial, so configured tenant rates stretch and
        # shrink with the measured capacity.
        self._initial_limit = start
        self._lock = threading.Lock()
        self._window_start: float | None = None  # guarded by: _lock
        self._requests = 0  # guarded by: _lock
        self._answered = 0  # guarded by: _lock
        self._good = 0  # guarded by: _lock
        self._shed = 0  # guarded by: _lock
        self._good_streak = 0  # guarded by: _lock
        self._bad_streak = 0  # guarded by: _lock
        self._frozen_until: float | None = None  # guarded by: _lock
        self._freezes = 0  # guarded by: _lock
        self._windows = 0  # guarded by: _lock
        self._points: deque[dict] = deque(maxlen=max(4, int(history)))  # guarded by: _lock
        self._knee: dict = find_knee([])  # guarded by: _lock
        self._last_window: dict | None = None  # guarded by: _lock
        reg = obs_registry or get_registry()
        self._limit_gauge = reg.gauge(
            "edgemesh_admission_limit",
            "Live max_inflight the knee tracker has tuned to",
        )
        self._knee_gauge = reg.gauge(
            "edgemesh_admission_knee_rps",
            "Offered load at the tracker's live knee estimate",
        )
        self._actions = reg.counter(
            "edgemesh_admission_tuner_total",
            "Knee-tracker control actions", ("action",),
        )
        self._limit_gauge.set(float(admission.max_inflight))

    # -- feeding -------------------------------------------------------------

    def observe(self, answered: bool, good: bool, shed: bool = False) -> None:
        """One routed request's fate, from the router's accounting seam:
        ``answered`` = a replica answered 200, ``good`` = answered within
        the SLO budget, ``shed`` = refused at admission. Closes the window
        and acts when its span has elapsed."""
        actions = None
        with self._lock:
            now = self._now()
            if self._window_start is None:
                self._window_start = now
            self._requests += 1
            if answered:
                self._answered += 1
            if good:
                self._good += 1
            if shed:
                self._shed += 1
            if now - self._window_start >= self.window_s:
                actions = self._close_window_locked(now)
        if actions:
            self._emit(actions)

    def freeze(self, reason: str = "incident") -> None:
        """Stop tuning for ``freeze_s``: incident windows measure the
        incident, not the limit. Observation continues (the curve history
        stays honest); only control actions pause."""
        with self._lock:
            self._frozen_until = self._now() + self.freeze_s
            self._freezes += 1
            self._good_streak = self._bad_streak = 0
        self._actions.labels(action="freeze").inc()
        self._emit([{"action": "freeze", "reason": reason,
                     "limit": self.admission.max_inflight}])

    # -- the control law -----------------------------------------------------

    def _close_window_locked(self, now: float) -> list[dict]:  # guarded by: _lock
        span = max(1e-9, now - self._window_start)
        offered = self._requests / span
        goodput = self._good / span
        # The control ratio judges ANSWERED requests only: it measures
        # whether the current limit's queueing delay eats the SLO budget
        # (limit too HIGH). Sheds deliberately stay out of it — under
        # sustained open-loop overload the excess arrivals shed no matter
        # where the limit sits, and counting them would read the correct
        # limit as a bad one and slam the controller to the floor. Sheds
        # still cost goodput_rps, so the CURVE (and its knee) stays the
        # honest open-loop measurement.
        ratio = (
            self._good / self._answered if self._answered else None
        )
        window = {
            "offered_rps": round(offered, 4),
            "goodput_rps": round(goodput, 4),
            "goodput_ratio": None if ratio is None else round(ratio, 4),
            "requests": self._requests,
            "answered": self._answered,
            "shed": self._shed,
        }
        thin = self._requests < self.min_window_requests
        self._requests = self._answered = self._good = self._shed = 0
        self._window_start = now
        self._windows += 1
        self._last_window = window
        if not thin:
            self._points.append({"offered_rps": window["offered_rps"],
                                 "goodput_rps": window["goodput_rps"]})
            self._knee = find_knee(list(self._points))
        if self._knee.get("knee_offered_rps") is not None:
            self._knee_gauge.set(self._knee["knee_offered_rps"])
        frozen = (self._frozen_until is not None
                  and now < self._frozen_until)
        if frozen or thin:
            # Frozen: measured, not acted on. Thin: a near-idle window says
            # nothing about the knee — growing the limit on silence would
            # ratchet it to the ceiling overnight for free.
            self._good_streak = self._bad_streak = 0
            return []
        # The collapse signal: offered load past the live knee with window
        # goodput more than collapse_tolerance below the knee's is the
        # overload regime even when the ratio alone looks tolerable.
        knee = self._knee
        collapsed = (
            knee.get("knee_offered_rps") is not None
            and offered > knee["knee_offered_rps"]
            and goodput < (1.0 - self.collapse_tolerance) * (
                knee.get("knee_goodput_rps") or 0.0)
        )
        if ratio is None:
            # No answered requests this window: zero evidence about the
            # limit's service quality — dead zone, like thin windows.
            self._good_streak = self._bad_streak = 0
            return []
        bad = collapsed or ratio < self.goodput_target - self.bad_band
        good_w = (not bad) and ratio >= self.goodput_target
        actions: list[dict] = []
        limit = self.admission.max_inflight
        if good_w:
            self._good_streak += 1
            self._bad_streak = 0
            if self._good_streak >= self.patience and limit < self.ceiling:
                new = min(self.ceiling, limit + self.increase)
                actions.append(self._apply_locked("increase", new, window))
        elif bad:
            self._bad_streak += 1
            self._good_streak = 0
            if self._bad_streak >= self.patience and limit > self.floor:
                new = max(self.floor, int(limit * self.decrease))
                if new < limit:
                    actions.append(
                        self._apply_locked("decrease", new, window))
                self._bad_streak = 0  # wait for post-cut evidence
        else:
            # Dead zone between the target and the bad band: hysteresis.
            # Oscillating arrivals that straddle the target park here and
            # the limit holds instead of flapping.
            self._good_streak = self._bad_streak = 0
        return actions

    def _apply_locked(self, action: str, new_limit: int,
                      window: dict) -> dict:  # guarded by: _lock
        self.admission.set_max_inflight(new_limit)
        scale = new_limit / self._initial_limit
        self.admission.set_rate_scale(scale)
        self._limit_gauge.set(float(new_limit))
        self._actions.labels(action=action).inc()
        return {
            "action": action, "limit": new_limit,
            "rate_scale": round(scale, 4), "window": window,
            "knee_offered_rps": self._knee.get("knee_offered_rps"),
            "knee_goodput_rps": self._knee.get("knee_goodput_rps"),
            "collapsed": self._knee.get("collapsed"),
        }

    def _emit(self, actions: list[dict]) -> None:
        if self._log is None:
            return
        for rec in actions:
            try:
                self._log.log(TUNE_RECORD_EVENT, **rec)
            except Exception:  # telemetry must never break routing
                pass

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        """Live tuner state for ``/fleetz`` (under ``admission.tuner``)."""
        with self._lock:
            now = self._now()
            frozen = (self._frozen_until is not None
                      and now < self._frozen_until)
            return {
                "mode": "auto",
                "limit": self.admission.max_inflight,
                "floor": self.floor,
                "ceiling": self.ceiling,
                "window_s": self.window_s,
                "windows": self._windows,
                "frozen": frozen,
                "freezes": self._freezes,
                "rate_scale": round(
                    self.admission.max_inflight / self._initial_limit, 4),
                "knee": dict(self._knee),
                "last_window": (
                    dict(self._last_window)
                    if self._last_window is not None else None
                ),
            }
