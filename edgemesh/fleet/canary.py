"""Golden-set canary probing: active answer-quality truth for the fleet.

The health prober (fleet/health.py) answers "is the replica up?"; the
telemetry balancer's digests answer "is it fast?". Neither catches the
failure the quality observatory exists for: a replica serving a corrupted
checkpoint passes ``/readyz``, meets its latency SLOs, and answers
garbage. The canary prober closes that gap actively — on a fixed
interval it drives a small **pinned golden set** (question → reference
answer) through every routable replica's ``POST /generate`` and scores
each answer with the eval harness's token-F1 (optionally blended with
embedding cosine), exactly the agreement metric the offline tables use
(obs/quality.py).

The golden set is a JSONL file of ``{"question": ..., "reference": ...}``
pairs, typically pinned from a known-good build's own answers — greedy
decoding is deterministic, so a healthy replica reproduces its reference
exactly (score 1.0) and a degraded one diverges. Without a file a small
built-in fallback set keeps the prober running, but pinned references
are what make the score sharp.

Per replica the prober keeps an EWMA score and publishes it three ways:

- ``registry.update_canary(rid, {...})`` — rides ``/fleetz`` (replica
  rows + the router's fleet ``quality`` rollup) and is what the
  telemetry balancer's ``_quality_penalty`` reads to down-weight a
  degraded replica while it is still technically healthy;
- gauge ``edgemesh_fleet_canary_score{replica}`` (same label convention
  as ``edgemesh_fleet_replica_up``), self-pruned when a replica leaves
  the registry or is removed — the PR 14 leak class;
- a ``canary`` span-log record per scored round (obs JSONL vocabulary),
  which ``edgemesh obs quality`` folds into the offline canary table.

**Collapse → incident.** When a replica's EWMA falls below
``collapse_below`` (after ``min_probes`` rounds), the prober mints a
``quality_drift`` incident and fires it the same way a replica-local
anomaly trigger would: one direct ``POST /incident`` to the degraded
replica (the router's broadcast excludes the source, but that replica's
flight ring is the most interesting one), then
``router.observe_incident`` to fan the id out fleet-wide, freeze the
tuner, and record the source in ``/fleetz``. The collapse fires once per
healthy→collapsed transition and re-arms on recovery, mirroring
:class:`~edgemesh.obs.anomaly.QualityDriftDetector`.

Importing this module never imports jax (the fleet package contract),
and every outbound call carries an explicit timeout (EM502).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from edgemesh.fleet.transport import HttpTransport, TransportError
from edgemesh.obs.quality import CANARY_RECORD_EVENT, token_f1

log = logging.getLogger("edgemesh.fleet")

#: Built-in golden set used when no ``--canary-golden`` file is given:
#: keeps the prober (and its relative healthy-vs-degraded comparison)
#: running with zero config. Pinned per-deployment references are what
#: make the absolute score meaningful.
FALLBACK_GOLDEN: tuple[dict, ...] = (
    {"question": "What is the capital of France?",
     "reference": "The capital of France is Paris."},
    {"question": "How many days are there in a week?",
     "reference": "There are seven days in a week."},
    {"question": "What color is the sky on a clear day?",
     "reference": "On a clear day the sky is blue."},
)


def load_golden_set(path: str) -> list[dict]:
    """Load a golden-set JSONL file: one ``{"question", "reference"}``
    object per line (``"prompt"``/``"answer"`` accepted as aliases).
    Blank lines and comment lines (``#``) are skipped; a line that is
    valid JSON but missing either field is a hard error — a silently
    half-loaded canary set would score replicas against the wrong bar."""
    items: list[dict] = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            obj = json.loads(line)
            question = obj.get("question") or obj.get("prompt")
            reference = obj.get("reference") or obj.get("answer")
            if not isinstance(question, str) or not isinstance(reference, str):
                raise ValueError(
                    f"{path}:{lineno}: golden-set entries need string "
                    "'question' and 'reference' fields"
                )
            items.append({"question": question, "reference": reference})
    if not items:
        raise ValueError(f"{path}: golden set is empty")
    return items


class CanaryProber:
    """Background golden-set prober scoring every routable replica."""

    def __init__(self, registry, transport=None, router=None,
                 golden: list[dict] | None = None,
                 golden_path: str | None = None,
                 interval_s: float = 30.0, timeout_s: float = 15.0,
                 ewma_alpha: float = 0.5, collapse_below: float = 0.2,
                 min_probes: int = 2, embedder=None,
                 obs_registry=None, trace_log=None,
                 on_collapse=None) -> None:
        from edgemesh.obs import get_registry

        self.registry = registry
        self.transport = transport or HttpTransport()
        #: Optional FleetRouter: collapse incidents fan out through its
        #: ``observe_incident`` (dedupe, /fleetz, tuner freeze, broadcast).
        self.router = router
        if golden is not None:
            self.golden = list(golden)
        elif golden_path:
            self.golden = load_golden_set(golden_path)
        else:
            self.golden = list(FALLBACK_GOLDEN)
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.ewma_alpha = ewma_alpha
        self.collapse_below = collapse_below
        self.min_probes = min_probes
        #: Optional text embedder (eval/metrics.py HashingEmbedder): when
        #: set, each probe scores 0.5*token_f1 + 0.5*cosine — cosine
        #: forgives word-order drift token-F1 punishes.
        self.embedder = embedder
        self.trace_log = trace_log
        #: Called ``(rid, incident_dict)`` after a collapse fires —
        #: a test seam beside the router path.
        self.on_collapse = on_collapse
        reg = obs_registry or get_registry()
        self._score_gauge = reg.gauge(
            "edgemesh_fleet_canary_score",
            "Golden-set canary score EWMA per replica (1 = matches "
            "references exactly)", ("replica",),
        )
        self._collapses = reg.counter(
            "edgemesh_fleet_canary_collapses_total",
            "Canary collapses (quality_drift incidents minted) by replica",
            ("replica",),
        )
        # Per-replica prober state: {"score", "probes", "armed"}. "armed"
        # implements fire-once-per-transition, like QualityDriftDetector.
        self._state: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- one pass (directly callable from tests) -----------------------------

    def probe_once(self) -> dict[str, dict]:
        """Score every routable replica against the golden set once;
        returns {rid: canary_record}. Replicas that were unreachable for
        the whole round keep their previous score (the health prober owns
        liveness; a dead replica must not read as "quality collapsed")."""
        self._prune()
        results: dict[str, dict] = {}
        for rep in self.registry.replicas():
            if not rep.routable():
                continue
            rec = self._probe_replica(rep)
            if rec is not None:
                results[rep.rid] = rec
        return results

    def _probe_replica(self, rep) -> dict | None:
        scores: list[float] = []
        failures = 0
        for item in self.golden:
            score = self._probe_one(rep, item)
            if score is None:
                failures += 1
            else:
                scores.append(score)
        if not scores:
            # Whole round unreachable/unanswerable: no quality evidence
            # either way — leave the EWMA (and the balancer's view) alone.
            return None
        round_score = sum(scores) / len(scores)
        st = self._state.get(rep.rid)
        if st is None:
            st = {"score": round_score, "probes": 0, "armed": True}
            self._state[rep.rid] = st
        else:
            st["score"] = (self.ewma_alpha * round_score
                           + (1.0 - self.ewma_alpha) * st["score"])
        st["probes"] += 1
        collapsed = (st["probes"] >= self.min_probes
                     and st["score"] < self.collapse_below)
        rec = {
            "score": round(st["score"], 4),
            "last": round(round_score, 4),
            "probes": st["probes"],
            "set_size": len(self.golden),
            "failures": failures,
            "collapsed": collapsed,
        }
        self.registry.update_canary(rep.rid, rec)
        self._score_gauge.labels(replica=rep.rid).set(rec["score"])
        if self.trace_log is not None:
            self.trace_log.log(CANARY_RECORD_EVENT, replica=rep.rid,
                               pool=rep.pool, **{k: rec[k] for k in
                                                 ("score", "last", "probes",
                                                  "set_size", "failures")})
        if collapsed:
            if st["armed"]:
                st["armed"] = False
                self._fire_collapse(rep, rec)
        elif st["probes"] >= self.min_probes:
            # Recovery (a rolled-back checkpoint, a restarted process)
            # re-arms the trigger for the next collapse.
            st["armed"] = True
        return rec

    def _probe_one(self, rep, item: dict) -> float | None:
        try:
            status, body = self.transport.post_json(
                rep.url("/generate"), {"question": item["question"]},
                timeout_s=self.timeout_s,
            )
        except TransportError as e:
            log.debug("canary probe transport failure for %s: %s", rep.rid, e)
            return None
        if status != 200 or not isinstance(body, dict):
            return None
        answer = body.get("answer")
        if not isinstance(answer, str):
            return None
        score = token_f1(answer, item["reference"])
        if self.embedder is not None:
            from edgemesh.eval.metrics import cosine_similarity

            cos = cosine_similarity(answer, item["reference"],
                                    embedder=self.embedder)
            score = 0.5 * score + 0.5 * max(0.0, cos)
        return score

    # -- collapse → incident -------------------------------------------------

    def _fire_collapse(self, rep, rec: dict) -> None:
        incident = {
            "id": (f"inc-{time.strftime('%Y%m%d-%H%M%S')}-"
                   f"{os.urandom(3).hex()}"),
            "kind": "quality_drift",
            "ts": time.time(),
        }
        log.warning("canary collapse on %s (score %.3f < %.3f): %s",
                    rep.rid, rec["score"], self.collapse_below,
                    incident["id"])
        self._collapses.labels(replica=rep.rid).inc()
        # The router's broadcast excludes the source replica, but the
        # degraded replica's flight ring is the most interesting one —
        # POST to it directly first, then fan out through the router.
        try:
            self.transport.post_json(
                rep.url("/incident"),
                {"id": incident["id"], "kind": incident["kind"],
                 "source": rep.rid},
                timeout_s=self.timeout_s,
            )
        except TransportError as e:
            log.warning("canary incident POST to %s failed: %s", rep.rid, e)
        if self.router is not None:
            try:
                self.router.observe_incident(rep.rid, incident)
            except Exception:  # incident fan-out must never kill the prober
                log.exception("canary incident fan-out failed for %s",
                              rep.rid)
        if self.on_collapse is not None:
            try:
                self.on_collapse(rep.rid, incident)
            except Exception:
                log.exception("canary collapse callback failed for %s",
                              rep.rid)

    # -- registry hygiene ----------------------------------------------------

    def _prune(self) -> None:
        """Drop prober state and the per-replica gauge child for replicas
        that left the registry or were removed — a dead backend's canary
        score must not linger in /metrics (the PR 14 digest leak class;
        the registry purges its own ``rep.canary`` on removal)."""
        live = {rep.rid for rep in self.registry.replicas()
                if rep.state != "removed"}
        for rid in [r for r in self._state if r not in live]:
            del self._state[rid]
            self._score_gauge.remove(replica=rid)

    # -- background loop -----------------------------------------------------

    def start(self) -> "CanaryProber":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-canary", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s + self.timeout_s + 1.0)
            if t.is_alive():
                # Mid-round on a stalled replica: keep the handle so a
                # later start() cannot race two probers (health.py rule).
                return
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # a probe round must never kill the loop
                log.exception("canary probe round failed")
            self._stop.wait(self.interval_s)
