"""Replica registry — the fleet's single source of membership truth.

The reference's "cluster map" is a static IP table in a README
(``Code/gRPC/README.md:9-14``) baked into every client stub; a dead Jetson
stays in the map forever. Here membership is a live, thread-safe registry:
replicas enter via static config or runtime ``/replicas/register``, leave
via deregister or drain, and move through an explicit state machine driven
by the health prober (fleet/health.py) and the router's passive failure
accounting (fleet/router.py):

    healthy ──(probe/route failures ≥ threshold)──► unhealthy
    unhealthy ──(probe successes ≥ threshold)─────► healthy
    any ──drain_replica()──► draining ──(in-flight hits 0)──► removed

Registration is fail-open: a newly registered replica is ``healthy`` and
routable immediately (the prober demotes it within one interval if it
isn't), matching how production balancers admit backends. ``draining`` and
``removed`` are terminal for routing — only an explicit re-``register``
revives a removed replica.

Every mutation happens under one lock; ``acquire``/``release`` make
balancer choice + outstanding-counter bookkeeping atomic so
least-outstanding balancing never reads a torn counter.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterable

STATES = ("healthy", "unhealthy", "draining", "removed")


@dataclass
class Replica:
    """One serving backend (a ``serve_rest`` process) behind the router."""

    rid: str
    base_url: str  # e.g. "http://127.0.0.1:8101", no trailing slash
    state: str = "healthy"
    outstanding: int = 0  # requests currently routed here, not yet finished
    consecutive_failures: int = 0  # probe + route failures since last success
    consecutive_successes: int = 0
    total_routed: int = 0
    total_failures: int = 0
    last_probe_ts: float | None = None
    last_error: str = ""
    meta: dict = field(default_factory=dict)  # operator annotations (pid, ...)
    # Model descriptor shipped at registration ({"pool", "role", "family",
    # "size", ...} — serve/httputil.py WIRE_CONTRACT): which model this
    # backend serves and therefore which pool it routes in. None = the
    # homogeneous fleet (pre-descriptor replicas belong to no named pool).
    model: dict | None = None
    # Latest load digest shipped on the replica's /readyz body (queue depth,
    # latency EWMAs, SLO goodput, recent-compile flag — serve/rest.py), and
    # the RECEIVER-side monotonic stamp the telemetry balancer ages it by
    # (replica wall clocks skew; arrival time is the honest freshness).
    load: dict | None = None
    load_ts: float | None = None
    # Latest golden-set canary result ({"score", "probes", ...} —
    # fleet/canary.py CanaryProber), with its own receiver-side freshness
    # stamp. None until the prober has scored this replica; the telemetry
    # balancer down-weights on a fresh low score only.
    canary: dict | None = None
    canary_ts: float | None = None

    def load_age_s(self) -> float | None:
        return None if self.load_ts is None else time.monotonic() - self.load_ts

    def canary_age_s(self) -> float | None:
        return (None if self.canary_ts is None
                else time.monotonic() - self.canary_ts)

    @property
    def pool(self) -> str | None:
        return (self.model or {}).get("pool")

    def url(self, path: str) -> str:
        return self.base_url.rstrip("/") + path

    def routable(self) -> bool:
        return self.state == "healthy"

    def to_dict(self) -> dict:
        return {
            "id": self.rid,
            "url": self.base_url,
            "state": self.state,
            "outstanding": self.outstanding,
            "consecutive_failures": self.consecutive_failures,
            "total_routed": self.total_routed,
            "total_failures": self.total_failures,
            "last_probe_ts": self.last_probe_ts,
            "last_error": self.last_error,
            **({"meta": self.meta} if self.meta else {}),
            **({"model": self.model, "pool": self.pool}
               if self.model is not None else {}),
            **({
                "load": self.load,
                "load_age_s": round(self.load_age_s(), 3),
            } if self.load is not None else {}),
            **({
                "canary": self.canary,
                "canary_age_s": round(self.canary_age_s(), 3),
            } if self.canary is not None else {}),
        }


class ReplicaRegistry:
    """Thread-safe replica membership + routing bookkeeping."""

    def __init__(self, replicas: Iterable[tuple[str, str]] = ()) -> None:
        self._lock = threading.RLock()
        self._replicas: dict[str, Replica] = {}
        for rid, url in replicas:
            self.register(rid, url)

    # -- membership ----------------------------------------------------------

    def register(self, rid: str, base_url: str,
                 model: dict | None = None, **meta) -> Replica:
        """Add (or revive) a replica. Fail-open: immediately routable.

        Re-registering a LIVE replica at the same URL is idempotent — the
        existing object is revived in place so in-flight ``outstanding``
        accounting survives (a fresh object at outstanding=0 would let a
        drain declare the replica safe while requests still run on it).
        A changed URL is a genuinely new backend and replaces the entry."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None and rep.base_url == base_url:
                if rep.state in ("draining", "removed"):
                    # Reviving a replica that LEFT rotation: its last
                    # digest describes the dead incarnation — the revived
                    # process starts cold and earns a fresh one on the
                    # first probe. (A live re-register keeps its digest:
                    # idempotent heartbeats must not blind the balancer.)
                    rep.load = None
                    rep.load_ts = None
                    # And its canary score: the revived process serves a
                    # possibly-different checkpoint and must re-earn its
                    # quality standing from a fresh probe.
                    rep.canary = None
                    rep.canary_ts = None
                    # Same for the model descriptor: the revived process
                    # declares what it serves NOW; the dead incarnation's
                    # pool membership must not route model-keyed traffic
                    # to a backend that may have come back with a
                    # different checkpoint.
                    rep.model = None
                rep.state = "healthy"
                rep.consecutive_failures = 0
                rep.consecutive_successes = 0
                if isinstance(model, dict):
                    # A live heartbeat without a descriptor keeps the
                    # existing one (idempotence, like meta).
                    rep.model = dict(model)
                if meta:
                    rep.meta.update(meta)
                return rep
            rep = Replica(
                rid=rid, base_url=base_url, meta=dict(meta),
                model=dict(model) if isinstance(model, dict) else None,
            )
            self._replicas[rid] = rep
            return rep

    def deregister(self, rid: str) -> bool:
        with self._lock:
            return self._replicas.pop(rid, None) is not None

    def get(self, rid: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(rid)

    def replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def available(self, pool: str | None = None) -> list[Replica]:
        with self._lock:
            return [
                r for r in self._replicas.values()
                if r.routable() and (pool is None or r.pool == pool)
            ]

    def pools(self) -> dict[str, dict]:
        """Per-pool membership view for /fleetz and the ensemble
        coordinator: rids, the pool's role (first declared wins), and how
        many members are currently routable. Replicas without a model
        descriptor belong to no named pool and do not appear here."""
        with self._lock:
            out: dict[str, dict] = {}
            for r in self._replicas.values():
                name = r.pool
                if name is None:
                    continue
                entry = out.setdefault(
                    name, {"replicas": [], "role": None, "routable": 0}
                )
                entry["replicas"].append(r.rid)
                if entry["role"] is None:
                    entry["role"] = (r.model or {}).get("role")
                if r.routable():
                    entry["routable"] += 1
            return out

    def set_state(self, rid: str, state: str) -> None:
        if state not in STATES:
            raise ValueError(f"unknown replica state {state!r} (one of {STATES})")
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.state = state
                if state == "removed":
                    # A removed replica's digest must not linger in
                    # /fleetz or tier scoring past its death — the stale
                    # snapshot outliving stale_after_s was the bug.
                    rep.load = None
                    rep.load_ts = None
                    # The canary score dies with the backend too — same
                    # leak class as the digest (PR 14): a removed
                    # replica's quality standing must not linger in
                    # /fleetz or balancer scoring.
                    rep.canary = None
                    rep.canary_ts = None
                    # Pool membership dies with the backend for the same
                    # reason: a removed replica must fall out of every
                    # model-keyed pool immediately, not when it is
                    # eventually deregistered.
                    rep.model = None

    # -- routing bookkeeping -------------------------------------------------

    def acquire(self, balancer, prompt: str | None = None,
                exclude: frozenset | set = frozenset(),
                pool: str | None = None) -> Replica | None:
        """Atomically pick a routable replica via ``balancer`` and check out
        one unit of outstanding work on it. Pair with ``release``. With
        ``pool`` set, only members of that model pool are candidates."""
        with self._lock:
            candidates = [
                r for r in self._replicas.values()
                if r.routable() and r.rid not in exclude
                and (pool is None or r.pool == pool)
            ]
            if not candidates:
                return None
            rep = balancer.pick(candidates, prompt)
            if rep is None:
                return None
            rep.outstanding += 1
            return rep

    def release(self, rid: str, ok: bool, demote_after: int = 2,
                error: str = "") -> None:
        """Check one unit of work back in, with passive health accounting:
        ``demote_after`` consecutive failures (route OR probe) demote a
        healthy replica to ``unhealthy`` — the prober re-promotes it."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return  # deregistered mid-flight: nothing to account
            rep.outstanding = max(0, rep.outstanding - 1)
            if ok:
                rep.total_routed += 1
                rep.consecutive_failures = 0
                rep.consecutive_successes += 1
            else:
                rep.total_failures += 1
                rep.consecutive_successes = 0
                rep.consecutive_failures += 1
                if error:
                    rep.last_error = error
                if (
                    rep.state == "healthy"
                    and rep.consecutive_failures >= demote_after
                ):
                    rep.state = "unhealthy"

    def update_load(self, rid: str, digest: dict | None) -> None:
        """Store the replica's latest load digest (shipped on its /readyz
        body — fleet/health.py refreshes it on every probe). The freshness
        stamp is local monotonic time: the telemetry balancer decays its
        trust in the digest by receiver-side age, never replica clocks."""
        if not isinstance(digest, dict):
            return
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.load = digest
                rep.load_ts = time.monotonic()

    def update_canary(self, rid: str, result: dict | None) -> None:
        """Store the replica's latest golden-set canary result
        (fleet/canary.py refreshes it on every probe round). Same
        freshness convention as ``update_load``: receiver-side monotonic
        time, never replica clocks. ``None`` clears the entry (purge)."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            if result is None:
                rep.canary = None
                rep.canary_ts = None
            elif isinstance(result, dict):
                rep.canary = result
                rep.canary_ts = time.monotonic()

    def probe_result(self, rid: str, ok: bool, healthy_after: int = 1,
                     unhealthy_after: int = 2, error: str = "") -> str | None:
        """Record one health-probe outcome; returns the (possibly new) state.
        Draining/removed replicas keep their state — a drain must never be
        un-drained by a passing probe."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return None
            rep.last_probe_ts = time.time()
            if ok:
                rep.consecutive_failures = 0
                rep.consecutive_successes += 1
                if (
                    rep.state == "unhealthy"
                    and rep.consecutive_successes >= healthy_after
                ):
                    rep.state = "healthy"
            else:
                rep.consecutive_successes = 0
                rep.consecutive_failures += 1
                if error:
                    rep.last_error = error
                if (
                    rep.state == "healthy"
                    and rep.consecutive_failures >= unhealthy_after
                ):
                    rep.state = "unhealthy"
            return rep.state

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [r.to_dict() for r in self._replicas.values()]
