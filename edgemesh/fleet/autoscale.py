"""Replica autoscaling driven by the measured capacity model.

The load digests now carry both sides of the scaling question
(docs/OBSERVABILITY.md "The capacity model"): the ARRIVAL rate each
replica is seeing (``ewma_arrival_s`` — offered load, independent of how
service keeps up) and the live CAPACITY estimate (``capacity.est_req_s`` —
sustainable req/s from the service EWMAs). The :class:`AutoScaler` closes
the ROADMAP "self-driving fleet" loop on them: fleet utilization =
observed demand / estimated supply, scaled up past ``high_watermark`` and
down below ``low_watermark``, with streak requirements and cooldowns so a
burst or a single noisy digest never churns processes.

Two design points worth stating:

- **Incidents scale UP.** A propagated incident (obs/anomaly.py → the
  router's ``observe_incident``) means a replica is degrading: the
  surviving fleet is about to be short its capacity, and waiting for the
  utilization math to notice the queue growth wastes exactly the seconds
  a warm start saves. ``note_incident`` requests an immediate spawn
  (bounded by ``max_replicas`` and the incident's own cooldown).
- **Cold start is the binding constraint**, so the scaler is built around
  warm starts: the launcher it drives (fleet/cli.py
  ``SubprocessLauncher``) spawns every replica against one persistent XLA
  compilation cache (``--compile-cache-dir``), measures
  spawn→ready→first-token, and pins the split as
  ``edgemesh_cold_start_seconds{phase}`` — the number PERFORMANCE.md
  budgets and the ``cold_start`` bench stage tracks.

The launcher contract is three methods — ``spawn() -> rid`` (may complete
registration asynchronously), ``stop(rid)``, ``pending() -> int`` (spawns
in flight, counted toward the replica bound so one slow boot cannot
trigger a second) — so tests drive the control law with a fake and the
CLI provides the subprocess reality.

No jax imports (the router-stack contract); the clock is injectable.
"""

from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("edgemesh.fleet")


class AutoScaler:
    """Demand/supply scaling over the registry's live digests.

    ``evaluate()`` is one control pass — the background loop calls it on
    ``interval_s``, tests call it directly. Scale-down drains through the
    router (zero dropped requests) and purges via ``forget_replica``, so
    a scaled-down replica leaves no stale digest or tier ghost behind.
    """

    def __init__(self, registry, launcher, router=None,
                 min_replicas: int = 1, max_replicas: int = 4,
                 high_watermark: float = 0.8, low_watermark: float = 0.3,
                 up_after: int = 2, down_after: int = 5,
                 cooldown_s: float = 20.0, incident_cooldown_s: float = 60.0,
                 interval_s: float = 2.0,
                 neutral_service_s: float = 0.1,
                 mem_pressure_s: float | None = None,
                 obs_registry=None, now=time.monotonic) -> None:
        import os

        from edgemesh.obs import get_registry

        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas must be >= min_replicas, got "
                f"{max_replicas} < {min_replicas}")
        if not 0.0 <= low_watermark < high_watermark:
            raise ValueError(
                f"need 0 <= low_watermark < high_watermark, got "
                f"{low_watermark} / {high_watermark}")
        self.registry = registry
        self.launcher = launcher
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.cooldown_s = float(cooldown_s)
        self.incident_cooldown_s = float(incident_cooldown_s)
        self.interval_s = float(interval_s)
        # A replica whose digest carries no capacity estimate yet (cold,
        # or non-continuous) is credited slots/neutral_service_s — the
        # same neutral assumption the telemetry balancer falls back to,
        # so a cold fleet is never scored as zero supply.
        self.neutral_service_s = float(neutral_service_s)
        # Memory-pressure scale-up (docs/FLEET.md): when any routable
        # replica's pool-exhaustion forecast (the load digest's
        # ``mem.forecast_s``, obs/memory.py) drops below this horizon,
        # the pass votes high-watermark regardless of the demand/supply
        # ratio — a pool about to wedge is a capacity shortage the req/s
        # math cannot see. 0 disables (the default).
        if mem_pressure_s is None:
            mem_pressure_s = float(
                os.environ.get("EDGEMESH_SCALE_MEM_PRESSURE_S", "0") or 0
            )
        self.mem_pressure_s = max(0.0, float(mem_pressure_s))
        self._now = now
        self._lock = threading.Lock()
        self._high_streak = 0  # guarded by: _lock
        self._low_streak = 0  # guarded by: _lock
        self._last_action_ts: float | None = None  # guarded by: _lock
        self._last_incident_ts: float | None = None  # guarded by: _lock
        self._want_incident_up: dict | None = None  # guarded by: _lock
        self._last_eval: dict | None = None  # guarded by: _lock
        self._events: list[dict] = []  # guarded by: _lock
        reg = obs_registry or get_registry()
        self._events_total = reg.counter(
            "edgemesh_autoscale_events_total",
            "Autoscaler actions", ("action",),
        )
        self._replicas_gauge = reg.gauge(
            "edgemesh_autoscale_replicas",
            "Routable replicas + spawns in flight, as the scaler sees them",
        )
        self._util_gauge = reg.gauge(
            "edgemesh_autoscale_utilization_ratio",
            "Observed fleet demand / estimated fleet capacity",
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- signals -------------------------------------------------------------

    def note_incident(self, incident: dict) -> bool:
        """A propagated incident is a scale-up signal (ROADMAP item): flag
        it for the next control pass (never spawn on the caller's thread —
        this is invoked from the router, which is invoked from the
        prober). Bounded by its own cooldown so one incident's fan-out
        cannot spawn a replica per probe tick."""
        with self._lock:
            now = self._now()
            if (self._last_incident_ts is not None
                    and now - self._last_incident_ts < self.incident_cooldown_s):
                return False
            self._last_incident_ts = now
            self._want_incident_up = dict(incident or {})
        return True

    # -- one control pass ----------------------------------------------------

    def _demand_supply(self) -> tuple[float, float, int, float | None]:
        """(demand_rps, supply_rps, routable_count, min_mem_forecast_s)
        from the live digests. The mem forecast is the fleet-wide minimum
        of each digest's ``mem.forecast_s`` (None when no replica reports
        one — pre-mem digests and dense backends stay pressure-neutral)."""
        demand = 0.0
        supply = 0.0
        routable = 0
        mem_min: float | None = None
        for rep in self.registry.replicas():
            if not rep.routable():
                continue
            routable += 1
            load = rep.load if isinstance(rep.load, dict) else {}
            arrival = load.get("ewma_arrival_s")
            if arrival:
                demand += 1.0 / arrival
            cap = load.get("capacity") if isinstance(load.get("capacity"), dict) else {}
            est = cap.get("est_req_s")
            if est:
                supply += est
            else:
                slots = cap.get("slots") or 1
                supply += slots / self.neutral_service_s
            mem = load.get("mem")
            if isinstance(mem, dict):
                f = mem.get("forecast_s")
                if isinstance(f, (int, float)) and f >= 0:
                    mem_min = f if mem_min is None else min(mem_min, float(f))
        return demand, supply, routable, mem_min

    def evaluate(self) -> dict | None:
        """One control pass; returns the action taken (or None). Spawns
        and drains run inline — callers that must not block (the router's
        incident path) go through :meth:`note_incident` instead."""
        demand, supply, routable, mem_min = self._demand_supply()
        util = demand / supply if supply > 0 else 0.0
        mem_pressure = (self.mem_pressure_s > 0 and mem_min is not None
                        and mem_min < self.mem_pressure_s)
        pending = self.launcher.pending()
        live = routable + pending
        self._replicas_gauge.set(float(live))
        self._util_gauge.set(round(util, 4))
        action: dict | None = None
        with self._lock:
            now = self._now()
            incident = self._want_incident_up
            self._want_incident_up = None
            cooling = (self._last_action_ts is not None
                       and now - self._last_action_ts < self.cooldown_s)
            if incident is not None and live < self.max_replicas:
                self._last_action_ts = now
                self._high_streak = self._low_streak = 0
                action = {"action": "incident_up",
                          "incident": incident.get("id"),
                          "kind": incident.get("kind")}
            elif util >= self.high_watermark or mem_pressure:
                # Memory pressure is a high-watermark vote: the same
                # streak/cooldown discipline applies, so a single noisy
                # forecast cannot spawn a replica any faster than a
                # single hot utilization sample can.
                self._low_streak = 0
                self._high_streak += 1
                if (not cooling and self._high_streak >= self.up_after
                        and live < self.max_replicas):
                    self._last_action_ts = now
                    self._high_streak = 0
                    action = {"action": "up"}
                    if mem_pressure and util < self.high_watermark:
                        action["reason"] = "mem_pressure"
            elif util <= self.low_watermark:
                self._high_streak = 0
                self._low_streak += 1
                if (not cooling and self._low_streak >= self.down_after
                        and routable > self.min_replicas and pending == 0):
                    # Confirm a reapable victim BEFORE stamping the
                    # cooldown: a fleet of boot-time replicas the launcher
                    # does not own yields none, and a phantom "down" that
                    # consumed the cooldown would block a genuine
                    # scale-up right after. (Lock order: _lock → the
                    # registry's; nothing takes them reversed.)
                    victim = self._pick_victim()
                    if victim is not None:
                        self._last_action_ts = now
                        self._low_streak = 0
                        action = {"action": "down", "replica": victim}
            else:
                self._high_streak = self._low_streak = 0
            self._last_eval = {
                "demand_rps": round(demand, 3),
                "supply_rps": round(supply, 3),
                "utilization": round(util, 4),
                "routable": routable, "pending": pending,
                "mem_forecast_s": (
                    round(mem_min, 3) if mem_min is not None else None
                ),
                "mem_pressure": mem_pressure,
            }
        if action is None:
            return None
        action.update(self._last_eval or {})
        if action["action"] == "down":
            self._drain_and_stop(action["replica"])
        else:
            try:
                action["replica"] = self.launcher.spawn()
            except Exception as e:
                log.exception("autoscale spawn failed")
                action["error"] = str(e)[:200]
        self._events_total.labels(action=action["action"]).inc()
        with self._lock:
            self._events.append(action)
            del self._events[:-16]
        log.info("autoscale %s (util=%.2f demand=%.2f supply=%.2f)",
                 action["action"], util, demand, supply)
        return action

    def _pick_victim(self) -> str | None:
        """Least-loaded routable replica: fewest outstanding, then lowest
        observed arrival rate — the drain that displaces the least work.
        When the launcher reports ownership (``owns(rid)``), only
        launcher-owned replicas are eligible: draining a boot-time
        replica the launcher cannot actually STOP would leave a drained
        zombie process holding a resident model — the scale-down would
        free nothing."""
        owns = getattr(self.launcher, "owns", None)
        candidates = []
        for rep in self.registry.replicas():
            if not rep.routable():
                continue
            if owns is not None and not owns(rep.rid):
                continue
            load = rep.load if isinstance(rep.load, dict) else {}
            arrival = load.get("ewma_arrival_s")
            rate = (1.0 / arrival) if arrival else 0.0
            candidates.append((rep.outstanding, rate, rep.rid))
        if not candidates:
            return None
        return min(candidates)[2]

    def _drain_and_stop(self, rid: str) -> None:
        if self.router is not None:
            self.router.drain_replica(rid)
            self.router.forget_replica(rid)
        else:
            self.registry.deregister(rid)
        try:
            self.launcher.stop(rid)
        except Exception:
            log.exception("autoscale stop of %s failed", rid)

    # -- background loop (same lifecycle shape as HealthProber) --------------

    def start(self) -> "AutoScaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s + 1.0)
            if not t.is_alive():
                self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.evaluate()
            except Exception:  # a control pass must never kill the loop
                log.exception("autoscale evaluate failed")
            self._stop.wait(self.interval_s)

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        """Live scaler state for ``/fleetz`` (``"autoscale"``)."""
        with self._lock:
            return {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "high_watermark": self.high_watermark,
                "low_watermark": self.low_watermark,
                "cooldown_s": self.cooldown_s,
                "last_eval": (
                    dict(self._last_eval)
                    if self._last_eval is not None else None
                ),
                "recent_events": [dict(e) for e in self._events[-8:]],
            }
