"""edgemesh CLI: one entry point replacing the reference's eight copy-pasted
runner mains (C1-C8 in SURVEY.md §2.1).

Subcommands:
- ``eval``     — golden-dataset evaluation (the combiner/single-model runners)
- ``serve``    — REST front door (rest_api.py parity)
- ``bench``    — decode-throughput microbenchmark (prints one JSON line)
- ``download`` — checkpoint verify/materialize (downloader parity, offline)
- ``train``    — finetuning loop over the QA corpus (beyond reference parity:
                 its roadmap's "After Finetuning" rows were never started)
- ``compare``  — paired bootstrap comparison of two eval runs (the
                 spreadsheet the reference eyeballed, with error bars)
- ``lint``     — static analysis: edgelint AST rules (EM1xx/EM3xx/EM4xx/
                 EM5xx), the abstract eval_shape contract pass (EM2xx),
                 the AbstractMesh sharding dryrun (EM405), and the wire
                 protocol-contract dryrun (EM506); filter with
                 --select/--ignore (python -m edgemesh.analysis)
- ``obs``      — tail/summarize request-span JSONL logs and dump registry
                 snapshots (edgemesh.obs; docs/OBSERVABILITY.md)
- ``fleet``    — multi-replica serving fabric: spawn N local replicas and
                 front them with the fault-tolerant router, or inspect a
                 running fleet (edgemesh.fleet; docs/FLEET.md)
- ``loadgen``  — open-loop load observatory: Poisson/diurnal workload
                 generation against any /generate endpoint, goodput-vs-
                 offered-load sweeps (edgemesh.loadgen; render reports
                 with ``edgemesh obs loadreport``)
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from edgemesh.config import EdgeMeshConfig, build_arg_parser, load_config


def _honor_platform_env() -> None:
    """Make JAX_PLATFORMS work as documented even where a sitecustomize
    force-registers another platform and overrides the env var after import
    (this session's axon remote-TPU plugin does exactly that — without this,
    `JAX_PLATFORMS=cpu edgemesh eval` silently dials the TPU pool)."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def _setup_logging(cfg: EdgeMeshConfig):
    logging.basicConfig(
        level=getattr(logging, cfg.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )


def cmd_eval(cfg: EdgeMeshConfig) -> int:
    from edgemesh.agents import build_ensemble
    from edgemesh.eval.data import load_qa, resolve_dataset_path
    from edgemesh.eval.embedder import build_embedder
    from edgemesh.eval.harness import run_eval

    ensemble = build_ensemble(cfg)
    samples = load_qa(resolve_dataset_path(cfg.eval.dataset_path),
                      split=cfg.eval.dataset_split, limit=cfg.eval.num_samples)
    # Only pay for an embedding model when an embedding metric is requested.
    needs_embedder = bool({"cosine", "bertscore"} & set(cfg.eval.metrics))
    report = run_eval(
        samples,
        ensemble.answer,
        output_jsonl=cfg.eval.output_jsonl,
        resume=cfg.eval.resume,
        metrics=cfg.eval.metrics,
        embedder=build_embedder(cfg.embedder) if needs_embedder else None,
        answer_batch_fn=ensemble.answer_batch,
        batch_size=cfg.eval.batch_size,
    )
    print(json.dumps(report))
    return 0


def cmd_serve(cfg: EdgeMeshConfig, port: int, batch: int = 0, continuous: bool = False,
              kv_backend: str = "dense", kv_page_size: int = 64,
              admission: str = "fifo", span_log: str | None = None,
              trace_sample: float = 1.0,
              profile_dir: str | None = None, tp: int = 0,
              collective_mode: str = "psum",
              collective_dtype: str = "int8",
              flight_capacity: int | None = None,
              flight_dir: str | None = None,
              compile_cache_dir: str | None = None) -> int:
    from edgemesh.agents import build_ensemble
    from edgemesh.serve import serve_rest

    if compile_cache_dir is not None:
        # Before the ensemble builds: model-construction compiles should
        # hit the shared cache too, not just serving-path ones.
        from edgemesh.utils.compat import enable_compilation_cache

        enable_compilation_cache(compile_cache_dir)
    ensemble = build_ensemble(cfg)
    serve_rest(ensemble, port=port, batch=batch, continuous=continuous,
               kv_backend=kv_backend, kv_page_size=kv_page_size,
               admission=admission, span_log=span_log,
               trace_sample=trace_sample, profile_dir=profile_dir,
               tp=tp, collective_mode=collective_mode,
               collective_dtype=collective_dtype,
               flight_capacity=flight_capacity, flight_dir=flight_dir,
               compile_cache_dir=compile_cache_dir)
    return 0


def cmd_bench(cfg: EdgeMeshConfig, preset: str | None, precision: str | None) -> int:
    from edgemesh.benchmarks import decode_benchmark

    quant_mode = "w8a16"
    if precision == "int8_w8a8_auto":
        # Resolved per-build inside decode_benchmark is circular (the bench
        # IS the measurement); bench the XLA w8a8 path, which auto resolves
        # to on every platform measured so far.
        precision, quant_mode = "int8", "w8a8"
    elif precision and precision.startswith("int8_"):
        precision, quant_mode = "int8", precision.removeprefix("int8_")
    print(json.dumps(decode_benchmark(preset=preset, precision=precision, quant_mode=quant_mode)))
    return 0


def _materialize_from_hub_cache(src_root, model_id: str, dest) -> bool:
    """Copy a checkpoint out of a local HF hub cache
    (``models--org--name/snapshots/<rev>/``) into the flat save_pretrained
    layout edgemesh ingests. The offline analog of the reference's
    ``save_transformer_model`` (download.py:20-24): same end state, no
    network. Returns True if a snapshot was found and materialized."""
    import shutil
    from pathlib import Path

    src_root = Path(src_root)
    cache_name = "models--" + model_id.replace("/", "--")
    candidates = [src_root / cache_name, src_root / "hub" / cache_name]
    snap_root = next((c / "snapshots" for c in candidates if (c / "snapshots").is_dir()), None)
    if snap_root is None:
        return False
    snaps = sorted(snap_root.iterdir(), key=lambda p: p.stat().st_mtime)
    if not snaps:
        return False
    snap = snaps[-1]  # most recent revision
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    for f in snap.iterdir():
        # Skip hidden entries and subdirectories (e.g. Llama's original/
        # consolidated-PT folder — not part of the save_pretrained layout).
        if f.name.startswith(".") or not f.resolve().is_file():
            continue
        target = dest / f.name
        if target.exists():
            continue
        # Hub caches store files as symlinks into blobs/ — resolve and copy
        # so the materialized checkpoint is self-contained.
        shutil.copyfile(f.resolve(), target)
    return True


def cmd_download(cfg: EdgeMeshConfig, src: str | None = None) -> int:
    """Offline analog of the reference's downloaders (download.py:20-47):
    verifies each configured checkpoint directory is complete, and with
    ``--src <hub-cache-dir>`` first materializes missing checkpoints from a
    local HF hub cache (model id taken from the agent's ``model.hub_id``, or
    the checkpoint directory's basename)."""
    from pathlib import Path

    ok = True
    for agent in cfg.agents:
        path = agent.model.path
        if not path:
            print(f"{agent.role}: synthetic model (no checkpoint)")
            continue
        p = Path(path)

        def complete(p=p):
            return (p / "config.json").exists() and (
                any(p.glob("*.safetensors")) or (p / "pytorch_model.bin").exists()
            )

        if not complete() and src:
            hub_id = getattr(agent.model, "hub_id", "") or p.name
            if _materialize_from_hub_cache(src, hub_id, p):
                print(f"{agent.role}: materialized {hub_id} from {src}")
        status = "ok" if complete() else "MISSING"
        ok &= status == "ok"
        print(f"{agent.role}: {path} [{status}]")
    if not ok:
        print(
            "note: this environment has no network egress; place HF checkpoints "
            "locally (save_pretrained format, or a hub cache via --src) and "
            "point agents[].model.path at them."
        )
    return 0 if ok else 1


def cmd_train(cfg: EdgeMeshConfig) -> int:
    from edgemesh.training import run_training

    report = run_training(cfg)
    print(json.dumps(report))
    return 0


def main(argv: list[str] | None = None) -> int:
    _honor_platform_env()
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "lint":
        # Own argument shape (paths + lint flags) — delegate to the analysis
        # CLI before the shared parser, like compare below.
        from edgemesh.analysis.__main__ import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "fleet":
        # Own argument shape (subcommands + fleet flags) and no jax at all
        # on the router path — delegate before the shared parser.
        from edgemesh.fleet.cli import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "obs":
        # Offline span-log tooling: no config, no jax, no device — delegate
        # before the shared parser like lint/compare.
        from edgemesh.obs.cli import main as obs_main

        return obs_main(argv[1:])
    if argv and argv[0] == "loadgen":
        # The open-loop load observatory: drives any /generate endpoint
        # over HTTP, no jax/config — delegate before the shared parser.
        from edgemesh.loadgen.cli import main as loadgen_main

        return loadgen_main(argv[1:])
    if argv and argv[0] == "compare":
        # Own argument shape (two positional JSONL paths) — handled before
        # the shared parser, whose config-mirror options don't apply.
        from edgemesh.eval.compare import compare_runs

        if len(argv) != 3:
            raise SystemExit("usage: edgemesh compare <runA.jsonl> <runB.jsonl>")
        print(json.dumps(compare_runs(argv[1], argv[2])))
        return 0
    top = argparse.ArgumentParser(prog="edgemesh")
    top.add_argument("command", choices=["eval", "serve", "bench", "download", "train", "compare"])
    top.add_argument("--port", type=int, default=8000)
    top.add_argument(
        "--batch", type=int, default=0,
        help="serve: coalesce up to N concurrent requests into one decode",
    )
    top.add_argument(
        "--continuous", action="store_true",
        help="serve: chunk-granular continuous batching (single-agent "
        "ensembles; --batch sizes the slot pool)",
    )
    top.add_argument(
        "--kv-backend", default="dense",
        choices=["dense", "dense_int8", "paged", "paged_int8"],
        help="serve --continuous: KV memory model (paged = shared page pool "
        "with zero-copy admission + reclamation; *_int8 halves KV bytes)",
    )
    top.add_argument(
        "--admission", default="fifo", choices=["fifo", "sjf"],
        help="serve --continuous: queue policy (sjf = shortest-job-first by "
        "per-request max_new budget + prompt length; cuts short-job p50 on "
        "mixed workloads, default fifo)",
    )
    top.add_argument(
        "--kv-page-size", type=int, default=64,
        help="serve --continuous --kv-backend paged*: tokens per KV page "
        "(smaller pages = finer reclamation + template prefix sharing kicks "
        "in once the template spans a full page)",
    )
    top.add_argument(
        "--tp", type=int, default=0,
        help="serve --continuous: tensor-parallel degree — serve through "
        "the shard_map engine on a dp=1 x tp mesh (parallel/tp_infer.py); "
        "0/1 keeps the single-program path",
    )
    top.add_argument(
        "--collective-mode", default="psum",
        choices=["psum", "qpsum", "qpsum_overlap"],
        help="serve --continuous --tp N: cross-chip join for the row-"
        "sharded projections — qpsum halves the wire (quantized ring "
        "all-reduce), qpsum_overlap additionally hides the ring behind "
        "the next chunk's matmul (parallel/collectives.py)",
    )
    top.add_argument(
        "--collective-dtype", default="int8", choices=["int8", "fp8", "bf16"],
        help="serve --continuous --tp N: qpsum wire dtype (bf16 = "
        "full-precision passthrough, the ablation baseline)",
    )
    top.add_argument(
        "--span-log", type=str, default=None,
        help="serve --continuous: JSONL path for request-lifecycle span "
        "records (inspect/replay with `edgemesh obs`)",
    )
    top.add_argument(
        "--trace-sample", type=float, default=1.0,
        help="serve --continuous: span-log sampling rate in [0,1] for "
        "locally-originated requests (fleet-routed requests carry the "
        "router's sampling decision); sampled-out requests still count "
        "in /metrics",
    )
    top.add_argument(
        "--flight-capacity", type=int, default=None,
        help="serve --continuous: flight-recorder ring capacity (records); "
        "default keeps the always-on recorder at its standard size, 0 "
        "disables it (docs/OBSERVABILITY.md 'The flight recorder')",
    )
    top.add_argument(
        "--flight-dir", type=str, default=None,
        help="serve --continuous: arm the anomaly triggers (SLO-miss "
        "burst, queue collapse, error spike, compile storm) and dump the "
        "flight ring into <dir>/<incident-id>/ when one fires; also "
        "accepts router-propagated incident ids via POST /incident",
    )
    top.add_argument(
        "--compile-cache-dir", type=str, default=None,
        help="serve: persistent XLA compilation cache directory shared "
        "across replica spawns — a scale-up replica's compiles become "
        "disk-cache hits, so cold-start-to-first-token is load time, not "
        "compile time (docs/FLEET.md 'Autoscaling with warm starts')",
    )
    top.add_argument(
        "--profile-dir", type=str, default=None,
        help="serve: opt in GET /debug/profile?seconds=N jax.profiler "
        "captures under this directory (disabled by default — see the "
        "security note in docs/OBSERVABILITY.md)",
    )
    top.add_argument(
        "--preset", type=str, default=None,
        help="bench: model preset (validated by the bench command)",
    )
    top.add_argument(
        "--precision", type=str, default=None,
        choices=["bf16", "int8", "int8_w8a8", "int8_w8a8_pallas",
                 "int8_w8a8_pallas_pre", "int8_w8a8_auto", "int4"],
        help="bench: numeric precision (w8a8_auto measures every w8a8 "
        "path and benches the winner)",
    )
    top.add_argument(
        "--src", type=str, default=None,
        help="download: local HF hub cache to materialize checkpoints from",
    )
    cmd_args, rest = top.parse_known_args(argv)
    if cmd_args.command == "compare":
        # Normally intercepted before the parser (its args are two plain
        # paths); reaching here means flags preceded the command — reject
        # BEFORE config loading so a bad --config cannot mask the message.
        raise SystemExit(
            "usage: edgemesh compare <runA.jsonl> <runB.jsonl> "
            "(compare must be the first argument)"
        )

    parser = build_arg_parser()
    args, _ = parser.parse_known_args(rest)
    overrides = {k: v for k, v in vars(args).items() if k != "config"}
    cfg = load_config(args.config, overrides)
    _setup_logging(cfg)

    if cmd_args.command in ("eval", "serve", "bench", "train"):
        # Fail fast (with a pin-CPU hint) instead of hanging forever when
        # the device tunnel is wedged — observed >600s silent hangs here.
        from edgemesh.utils.platform import ensure_device_ready

        ensure_device_ready()

    if cmd_args.command == "eval":
        return cmd_eval(cfg)
    if cmd_args.command == "serve":
        return cmd_serve(cfg, cmd_args.port, cmd_args.batch, cmd_args.continuous,
                         cmd_args.kv_backend, cmd_args.kv_page_size,
                         cmd_args.admission, cmd_args.span_log,
                         cmd_args.trace_sample, cmd_args.profile_dir,
                         cmd_args.tp, cmd_args.collective_mode,
                         cmd_args.collective_dtype,
                         cmd_args.flight_capacity, cmd_args.flight_dir,
                         cmd_args.compile_cache_dir)
    if cmd_args.command == "bench":
        return cmd_bench(cfg, cmd_args.preset, cmd_args.precision)
    if cmd_args.command == "train":
        return cmd_train(cfg)
    return cmd_download(cfg, cmd_args.src)


if __name__ == "__main__":
    sys.exit(main())
