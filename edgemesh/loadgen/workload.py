"""WHAT the observatory sends: length mixes, sessions, tenant mixes.

Three layers compose into one merged request timeline:

- :class:`LengthMix` — long-tail (lognormal) prompt/output lengths. Real
  prompt-length distributions are heavy-tailed: a mean-length constant
  would never show the admission queue a 10x-cost straggler parked in
  front of forty cheap requests.
- sessions — multi-turn conversations sharing a stable prefix. Turn ``k``
  of a session carries the session's full synthetic history, so
  ``prefix_affinity`` routing keys identically across turns and the
  replica-side prefix caches (``runtime/prefix_cache.py``, paged template
  pages) actually get exercised by the generated traffic.
- :class:`TenantSpec` / :class:`Workload` — the tenant mix: each tenant
  owns an arrival process, a length mix, a lane (interactive/batch) and a
  session shape; ``Workload.build_schedule`` merges every tenant's
  timeline into one sorted open-loop schedule.

Everything is seeded → a workload spec IS its traffic, replayable
byte-for-byte across arms and runs.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

_WORDS = (
    "mesh edge device tensor shard page cache token decode prefill route "
    "batch stream quant fleet replica probe trace span tenant session"
).split()


@dataclass(frozen=True)
class LengthMix:
    """Long-tail length sampler: ``exp(N(log median, sigma))`` clipped to
    ``[lo, hi]``. ``sigma=0`` degenerates to the constant ``median``."""

    median: int = 48
    sigma: float = 0.6
    lo: int = 8
    hi: int = 2048

    def sample(self, rng: random.Random) -> int:
        if self.sigma <= 0:
            v = float(self.median)
        else:
            v = rng.lognormvariate(_ln(self.median), self.sigma)
        return int(min(self.hi, max(self.lo, round(v))))


def _ln(x: float) -> float:
    import math

    return math.log(max(1.0, float(x)))


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic contract. ``arrival`` is any object with a
    ``schedule(duration_s) -> list[float]`` (edgemesh.loadgen.arrivals).
    ``sessions``/``turns_mean`` shape the multi-turn structure: arrivals
    are dealt round-robin onto ``sessions`` concurrent conversations, and
    a session resets (fresh prefix) after a geometric number of turns
    around ``turns_mean``. ``max_new`` attaches a per-request decode
    budget sampled from ``output_mix`` (only send this at continuous
    non-speculative replicas — the gateway 400s it elsewhere)."""

    name: str
    arrival: object
    lane: str = "interactive"
    prompt_mix: LengthMix = field(default_factory=LengthMix)
    output_mix: LengthMix = field(default_factory=lambda: LengthMix(
        median=32, sigma=0.8, lo=4, hi=512))
    sessions: int = 4
    turns_mean: float = 3.0
    send_max_new: bool = False


@dataclass
class ScheduledRequest:
    """One open-loop launch: fixed time, fixed payload, fixed identity."""

    at_s: float
    tenant: str
    lane: str
    prompt: str
    session: str
    turn: int
    max_new: int | None = None

    def payload(self) -> dict:
        body: dict = {"question": self.prompt}
        if self.max_new is not None:
            body["max_new"] = self.max_new
        return body


class _Session:
    """One rolling conversation: a stable prefix plus appended turns."""

    def __init__(self, sid: str, rng: random.Random, turns_mean: float):
        self.sid = sid
        self._rng = rng
        self._turns_mean = max(1.0, turns_mean)
        self._reset()

    def _reset(self) -> None:
        # The prefix is the affinity/caching key: stable across the
        # session's turns, distinct across sessions.
        seed_words = " ".join(self._rng.choices(_WORDS, k=6))
        self.prefix = f"[session {self.sid}] context: {seed_words}."
        self.turn = 0

    def next_prompt(self, prompt_chars: int) -> tuple[str, int]:
        self.turn += 1
        turn = self.turn
        body = f" turn {turn}:"
        rng = self._rng
        while len(self.prefix) + len(body) < prompt_chars:
            body += " " + rng.choice(_WORDS)
        prompt = self.prefix + body + "?"
        # Geometric session length around turns_mean: each turn ends the
        # session with probability 1/turns_mean.
        if rng.random() < 1.0 / self._turns_mean:
            self._reset()
        return prompt, turn


class Workload:
    """A tenant mix → one merged, sorted open-loop schedule."""

    def __init__(self, tenants: list[TenantSpec], seed: int = 0) -> None:
        if not tenants:
            raise ValueError("a workload needs at least one tenant")
        names = [t.name for t in tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        self.tenants = list(tenants)
        self.seed = int(seed)

    def build_schedule(self, duration_s: float) -> list[ScheduledRequest]:
        import zlib

        out: list[ScheduledRequest] = []
        for spec in self.tenants:
            # crc32, not hash(): str hashing is PYTHONHASHSEED-randomized
            # per process, and a workload spec must replay identically
            # across processes and runs.
            rng = random.Random(zlib.crc32(f"{self.seed}:{spec.name}".encode()))
            sessions = [
                _Session(f"{spec.name}-{i}", rng, spec.turns_mean)
                for i in range(max(1, spec.sessions))
            ]
            for i, at in enumerate(spec.arrival.schedule(duration_s)):
                sess = sessions[i % len(sessions)]
                prompt, turn = sess.next_prompt(spec.prompt_mix.sample(rng))
                out.append(ScheduledRequest(
                    at_s=at, tenant=spec.name, lane=spec.lane,
                    prompt=prompt, session=sess.sid, turn=turn,
                    max_new=(spec.output_mix.sample(rng)
                             if spec.send_max_new else None),
                ))
        out.sort(key=lambda r: r.at_s)
        return out
