"""WHAT the observatory sends: length mixes, sessions, tenant mixes.

Three layers compose into one merged request timeline:

(Plus the inverse: :meth:`Workload.from_spans` reconstructs a replayable
:class:`ReplayWorkload` from recorded span logs — scheduled arrivals from
the records' ``ts_submit`` wall anchors, prompt lengths from
``prompt_chars``, tenant and session identity from the propagated
headers — so a production incident replays through the same
OpenLoopGenerator as a regression workload. ``edgemesh obs replay`` is
the CLI over it.)

- :class:`LengthMix` — long-tail (lognormal) prompt/output lengths. Real
  prompt-length distributions are heavy-tailed: a mean-length constant
  would never show the admission queue a 10x-cost straggler parked in
  front of forty cheap requests.
- sessions — multi-turn conversations sharing a stable prefix. Turn ``k``
  of a session carries the session's full synthetic history, so
  ``prefix_affinity`` routing keys identically across turns and the
  replica-side prefix caches (``runtime/prefix_cache.py``, paged template
  pages) actually get exercised by the generated traffic.
- :class:`TenantSpec` / :class:`Workload` — the tenant mix: each tenant
  owns an arrival process, a length mix, a lane (interactive/batch) and a
  session shape; ``Workload.build_schedule`` merges every tenant's
  timeline into one sorted open-loop schedule.

Everything is seeded → a workload spec IS its traffic, replayable
byte-for-byte across arms and runs.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

_WORDS = (
    "mesh edge device tensor shard page cache token decode prefill route "
    "batch stream quant fleet replica probe trace span tenant session"
).split()


@dataclass(frozen=True)
class LengthMix:
    """Long-tail length sampler: ``exp(N(log median, sigma))`` clipped to
    ``[lo, hi]``. ``sigma=0`` degenerates to the constant ``median``."""

    median: int = 48
    sigma: float = 0.6
    lo: int = 8
    hi: int = 2048

    def sample(self, rng: random.Random) -> int:
        if self.sigma <= 0:
            v = float(self.median)
        else:
            v = rng.lognormvariate(_ln(self.median), self.sigma)
        return int(min(self.hi, max(self.lo, round(v))))


def _ln(x: float) -> float:
    import math

    return math.log(max(1.0, float(x)))


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic contract. ``arrival`` is any object with a
    ``schedule(duration_s) -> list[float]`` (edgemesh.loadgen.arrivals).
    ``sessions``/``turns_mean`` shape the multi-turn structure: arrivals
    are dealt round-robin onto ``sessions`` concurrent conversations, and
    a session resets (fresh prefix) after a geometric number of turns
    around ``turns_mean``. ``max_new`` attaches a per-request decode
    budget sampled from ``output_mix`` (only send this at continuous
    non-speculative replicas — the gateway 400s it elsewhere)."""

    name: str
    arrival: object
    lane: str = "interactive"
    prompt_mix: LengthMix = field(default_factory=LengthMix)
    output_mix: LengthMix = field(default_factory=lambda: LengthMix(
        median=32, sigma=0.8, lo=4, hi=512))
    sessions: int = 4
    turns_mean: float = 3.0
    send_max_new: bool = False


@dataclass
class ScheduledRequest:
    """One open-loop launch: fixed time, fixed payload, fixed identity."""

    at_s: float
    tenant: str
    lane: str
    prompt: str
    session: str
    turn: int
    max_new: int | None = None

    def payload(self) -> dict:
        body: dict = {"question": self.prompt}
        if self.max_new is not None:
            body["max_new"] = self.max_new
        return body


class _Session:
    """One rolling conversation: a stable prefix plus appended turns."""

    def __init__(self, sid: str, rng: random.Random, turns_mean: float):
        self.sid = sid
        self._rng = rng
        self._turns_mean = max(1.0, turns_mean)
        self._generation = 0
        self._reset()

    def _reset(self) -> None:
        import zlib

        # The prefix is the affinity/caching key: stable across the
        # session's turns, distinct across sessions — and a PURE FUNCTION
        # of (session id, generation), independent of the tenant-shared
        # rng. That determinism is what lets `obs replay` rebuild a
        # recorded session's prefix byte-identically from the recorded
        # session id alone, so prefix-affinity routing pins replayed
        # traffic to replicas exactly as the live traffic pinned. Padded
        # past the balancer's 64-char affinity key so the (non-replayable)
        # body words can never leak into the routing decision.
        rng = random.Random(
            zlib.crc32(f"{self.sid}:{self._generation}".encode()))
        seed_words = " ".join(rng.choices(_WORDS, k=8))
        self.prefix = f"[session {self.sid}] context: {seed_words}"
        while len(self.prefix) < 72:
            self.prefix += " " + rng.choice(_WORDS)
        self.prefix += "."
        self.turn = 0
        self._generation += 1

    def next_prompt(self, prompt_chars: int) -> tuple[str, int]:
        self.turn += 1
        turn = self.turn
        body = f" turn {turn}:"
        rng = self._rng
        while len(self.prefix) + len(body) < prompt_chars:
            body += " " + rng.choice(_WORDS)
        prompt = self.prefix + body + "?"
        # Geometric session length around turns_mean: each turn ends the
        # session with probability 1/turns_mean.
        if rng.random() < 1.0 / self._turns_mean:
            self._reset()
        return prompt, turn


#: Span-record event key — mirrored from obs.spans to keep this module
#: import-light (loadgen must not pull the obs stack for a schedule).
_SPAN_RECORD_EVENT = "request_spans"

#: Length fallback chain for pre-``prompt_chars`` records: tokens x this
#: approximates the prompt's character cost closely enough for load shape.
_CHARS_PER_TOKEN = 4


class ReplayWorkload:
    """A recorded request timeline, replayable through the open-loop
    generator. Duck-types :class:`Workload`: ``build_schedule`` returns the
    reconstructed :class:`ScheduledRequest` list (optionally truncated),
    so every existing driver works unchanged."""

    def __init__(self, requests: list[ScheduledRequest],
                 meta: dict | None = None) -> None:
        self.requests = sorted(requests, key=lambda r: r.at_s)
        self.meta = dict(meta or {})

    @property
    def duration_s(self) -> float:
        return max((r.at_s for r in self.requests), default=0.0)

    def build_schedule(self, duration_s: float | None = None
                       ) -> list[ScheduledRequest]:
        if duration_s is None:
            return list(self.requests)
        return [r for r in self.requests if r.at_s <= duration_s]

    def to_doc(self) -> dict:
        """JSON-serializable workload document (``obs replay --out``)."""
        return {
            "kind": "replay_workload",
            **self.meta,
            "requests": [
                {"at_s": round(r.at_s, 6), "tenant": r.tenant,
                 "lane": r.lane, "prompt": r.prompt, "session": r.session,
                 "turn": r.turn, "max_new": r.max_new}
                for r in self.requests
            ],
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "ReplayWorkload":
        if doc.get("kind") != "replay_workload":
            raise ValueError(
                f"not a replay workload document (kind={doc.get('kind')!r})"
            )
        reqs = [
            ScheduledRequest(
                at_s=float(r["at_s"]), tenant=r.get("tenant", "default"),
                lane=r.get("lane", "interactive"), prompt=r["prompt"],
                session=r.get("session", "replay-0"),
                turn=int(r.get("turn", 1)), max_new=r.get("max_new"),
            )
            for r in doc.get("requests", [])
        ]
        meta = {k: v for k, v in doc.items() if k not in ("kind", "requests")}
        return cls(reqs, meta=meta)


class Workload:
    """A tenant mix → one merged, sorted open-loop schedule."""

    def __init__(self, tenants: list[TenantSpec], seed: int = 0) -> None:
        if not tenants:
            raise ValueError("a workload needs at least one tenant")
        names = [t.name for t in tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        self.tenants = list(tenants)
        self.seed = int(seed)

    def build_schedule(self, duration_s: float) -> list[ScheduledRequest]:
        import zlib

        out: list[ScheduledRequest] = []
        for spec in self.tenants:
            # crc32, not hash(): str hashing is PYTHONHASHSEED-randomized
            # per process, and a workload spec must replay identically
            # across processes and runs.
            rng = random.Random(zlib.crc32(f"{self.seed}:{spec.name}".encode()))
            sessions = [
                _Session(f"{spec.name}-{i}", rng, spec.turns_mean)
                for i in range(max(1, spec.sessions))
            ]
            for i, at in enumerate(spec.arrival.schedule(duration_s)):
                sess = sessions[i % len(sessions)]
                prompt, turn = sess.next_prompt(spec.prompt_mix.sample(rng))
                out.append(ScheduledRequest(
                    at_s=at, tenant=spec.name, lane=spec.lane,
                    prompt=prompt, session=sess.sid, turn=turn,
                    max_new=(spec.output_mix.sample(rng)
                             if spec.send_max_new else None),
                ))
        out.sort(key=lambda r: r.at_s)
        return out

    @classmethod
    def from_spans(cls, records, speed: float = 1.0,
                   sessions_per_tenant: int = 4,
                   include_max_new: bool = True) -> ReplayWorkload:
        """Reconstruct a replayable workload from recorded span records.

        ``records`` is an iterable of decoded span-log records (the
        engines' ``request_spans`` vocabulary — a live ``span_log``, a
        flight-recorder dump, or both). Per recorded request:

        - **arrival**: ``ts_submit`` relative to the earliest record,
          time-scaled by ``speed`` (2.0 = replay twice as fast);
        - **tenant**: the recorded tenant (untagged traffic replays as
          ``default``);
        - **session**: the recorded session id when the traffic carried
          ``X-Edgemesh-Session``; otherwise arrivals are dealt round-robin
          onto ``sessions_per_tenant`` synthetic sessions per tenant — the
          shared-prefix structure survives either way;
        - **prompt**: synthesized at the recorded ``prompt_chars`` length
          (``prompt_tokens`` x 4 for older logs) with the session's stable
          prefix, so prefix-affinity routing and replica prefix caches see
          the same key structure the original traffic produced;
        - **max_new**: the recorded ``generated`` count (when
          ``include_max_new`` — send only at continuous non-speculative
          replicas, same rule as ``TenantSpec.send_max_new``).

        Deterministic: prompts are seeded from the session id, so the same
        spans always rebuild byte-identical traffic."""
        import zlib

        if speed <= 0:
            raise ValueError(f"speed must be > 0, got {speed}")
        spans = [
            r for r in records
            if r.get("event", _SPAN_RECORD_EVENT) == _SPAN_RECORD_EVENT
            and r.get("ts_submit") is not None
        ]
        if not spans:
            raise ValueError("no request_spans records with ts_submit — "
                             "nothing to replay")
        spans.sort(key=lambda r: r["ts_submit"])
        t0 = spans[0]["ts_submit"]
        sessions: dict[str, _Session] = {}
        rr_counters: dict[str, int] = {}
        out: list[ScheduledRequest] = []
        for rec in spans:
            tenant = rec.get("tenant") or "default"
            sid = rec.get("session")
            if not sid:
                i = rr_counters.get(tenant, 0)
                rr_counters[tenant] = i + 1
                sid = f"{tenant}-r{i % max(1, int(sessions_per_tenant))}"
            sess = sessions.get(sid)
            if sess is None:
                # turns_mean=inf: replay sessions never reset — the
                # recorded arrival order IS the turn structure.
                rng = random.Random(zlib.crc32(f"replay:{sid}".encode()))
                sess = sessions[sid] = _Session(sid, rng,
                                                turns_mean=float("inf"))
            chars = rec.get("prompt_chars")
            if chars is None:
                toks = rec.get("prompt_tokens")
                chars = (int(toks) * _CHARS_PER_TOKEN
                         if toks is not None else 48)
            prompt, turn = sess.next_prompt(int(chars))
            max_new = None
            if include_max_new:
                gen = rec.get("generated")
                if gen is not None and int(gen) >= 1:
                    max_new = int(gen)
            out.append(ScheduledRequest(
                at_s=(rec["ts_submit"] - t0) / speed, tenant=tenant,
                lane="interactive", prompt=prompt, session=sid, turn=turn,
                max_new=max_new,
            ))
        out.sort(key=lambda r: r.at_s)
        return ReplayWorkload(out, meta={
            "source_records": len(spans), "speed": float(speed),
            "duration_s": round(out[-1].at_s, 6) if out else 0.0,
            "tenants": sorted({r.tenant for r in out}),
        })
