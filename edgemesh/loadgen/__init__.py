"""edgemesh.loadgen — the open-loop load observatory.

Every serving number this repo produced before PR 9 came from a
closed-loop driver: N workers fire a request, WAIT for the answer, fire
the next. Closed loops cannot see queueing collapse — when the system
slows down, the load generator politely slows down with it (coordinated
omission), and the measured tail is a fiction. Production traffic does
not wait. This package drives the fleet the way users do
(docs/OBSERVABILITY.md "The load observatory"):

- ``arrivals``: Poisson and diurnal-burst arrival processes — request
  LAUNCH times are fixed by the schedule before the run starts, and every
  request launches on time regardless of completions, so coordinated
  omission is structurally impossible.
- ``workload``: long-tail prompt/output-length mixes, multi-turn sessions
  with shared prefixes (exercising ``prefix_affinity`` routing and the
  replica prefix caches), and configurable tenant mixes — interactive vs
  batch, compliant vs abusive.
- ``generator``: the open-loop driver. Latency is measured from the
  SCHEDULED arrival (not the actual send), goodput counts good answers
  against every SCHEDULED request, and the report splits per tenant.
- ``curve``: offered-load sweeps → goodput-vs-offered-load points with
  the saturation knee identified (the bench stage ``load_curve`` and
  ``edgemesh obs loadreport`` consume this schema).

No jax anywhere in the package — the observatory drives serving stacks
over HTTP (or any in-process callable) from hosts with no accelerator.
"""

from edgemesh.loadgen.arrivals import (  # noqa: F401
    ConstantProcess,
    DiurnalBurstProcess,
    PoissonProcess,
)
from edgemesh.loadgen.curve import find_knee, run_curve  # noqa: F401
from edgemesh.loadgen.generator import (  # noqa: F401
    OpenLoopGenerator,
    http_target,
    summarize,
)
from edgemesh.loadgen.workload import (  # noqa: F401
    LengthMix,
    ScheduledRequest,
    TenantSpec,
    Workload,
)
