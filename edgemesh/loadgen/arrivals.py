"""Arrival processes: WHEN requests launch, fixed before the run starts.

The whole point of open-loop generation is that the schedule is computed
up front from the arrival process alone — the system under test cannot
slow the generator down, so a saturated fleet accumulates an honest
backlog instead of silently throttling the measurement (the coordinated
omission trap; see docs/OBSERVABILITY.md).

All processes are seeded and deterministic: the same spec replays the
same schedule, which is what lets an A/B (fairness on vs off) drive two
arms with IDENTICAL traffic.
"""

from __future__ import annotations

import math
import random


class ConstantProcess:
    """Fixed inter-arrival gaps — the degenerate baseline (and the
    deterministic choice for schedule-shape unit tests)."""

    name = "constant"

    def __init__(self, rate_rps: float) -> None:
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        self.rate_rps = float(rate_rps)

    def schedule(self, duration_s: float) -> list[float]:
        gap = 1.0 / self.rate_rps
        return [i * gap for i in range(int(duration_s * self.rate_rps))]


class PoissonProcess:
    """Memoryless arrivals: exponential inter-arrival gaps at ``rate_rps``.

    The canonical open-loop model — real request streams from many
    independent users are Poisson to first order, and the exponential
    gaps produce the natural short bursts a constant-gap driver never
    shows the admission queue."""

    name = "poisson"

    def __init__(self, rate_rps: float, seed: int = 0) -> None:
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        self.rate_rps = float(rate_rps)
        self.seed = int(seed)

    def schedule(self, duration_s: float) -> list[float]:
        rng = random.Random(self.seed)
        out: list[float] = []
        t = rng.expovariate(self.rate_rps)
        while t < duration_s:
            out.append(t)
            t += rng.expovariate(self.rate_rps)
        return out


class DiurnalBurstProcess:
    """Non-homogeneous Poisson: a sinusoidal "diurnal" rate swing between
    ``base_rps`` and ``peak_rps`` over ``period_s``, plus optional square
    bursts (``burst_rps`` extra for ``burst_len_s`` every
    ``burst_every_s``) — the compressed model of a day of traffic with
    top-of-the-hour spikes.

    Sampled by thinning (Lewis & Shedler): draw a homogeneous Poisson
    stream at the max rate, keep each arrival with probability
    ``rate(t) / max_rate``. Exact for any bounded rate function, and the
    kept arrivals are still Poisson locally — the burst edges stay sharp.
    """

    name = "diurnal"

    def __init__(self, base_rps: float, peak_rps: float, period_s: float,
                 burst_rps: float = 0.0, burst_every_s: float = 0.0,
                 burst_len_s: float = 1.0, seed: int = 0) -> None:
        if base_rps <= 0 or peak_rps < base_rps:
            raise ValueError(
                f"need 0 < base_rps <= peak_rps, got {base_rps}/{peak_rps}"
            )
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        self.base_rps = float(base_rps)
        self.peak_rps = float(peak_rps)
        self.period_s = float(period_s)
        self.burst_rps = float(burst_rps)
        self.burst_every_s = float(burst_every_s)
        self.burst_len_s = float(burst_len_s)
        self.seed = int(seed)

    def rate(self, t: float) -> float:
        """The instantaneous offered rate at offset ``t`` (rps). Starts at
        the trough (t=0 is the quiet edge of the cycle)."""
        swing = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / self.period_s))
        r = self.base_rps + (self.peak_rps - self.base_rps) * swing
        if (
            self.burst_rps > 0 and self.burst_every_s > 0
            and (t % self.burst_every_s) < self.burst_len_s
        ):
            r += self.burst_rps
        return r

    def schedule(self, duration_s: float) -> list[float]:
        max_rate = self.peak_rps + max(0.0, self.burst_rps)
        rng = random.Random(self.seed)
        out: list[float] = []
        t = rng.expovariate(max_rate)
        while t < duration_s:
            if rng.random() < self.rate(t) / max_rate:
                out.append(t)
            t += rng.expovariate(max_rate)
        return out


ARRIVALS = {
    "constant": ConstantProcess,
    "poisson": PoissonProcess,
    "diurnal": DiurnalBurstProcess,
}
