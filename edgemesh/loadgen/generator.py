"""The open-loop driver: launch on schedule, measure from schedule.

Two rules make this generator immune to coordinated omission:

1. **Launches never wait for completions.** The launcher thread sleeps to
   each scheduled arrival and hands the request to its own worker thread;
   a saturated fleet sees the full offered backlog pile into its
   admission queue, exactly like real traffic.
2. **Latency is measured from the SCHEDULED arrival**, not the actual
   send. If the launcher itself slips (GIL, thread spawn), the slip is
   charged to the measurement — and reported separately as
   ``max_launch_skew_s`` so a broken run is distinguishable from a slow
   fleet.

Goodput is counted against every SCHEDULED request: a shed, errored, or
never-answered request is a goodput miss by construction. That is the
number a closed-loop driver cannot produce.

The target is any callable ``(payload, headers) -> (status, body)`` —
:func:`http_target` adapts a URL via the fleet transport; tests pass
in-process callables and pay zero sockets.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from edgemesh.loadgen.workload import ScheduledRequest
from edgemesh.obs.slo import SloTarget
from edgemesh.serve.httputil import SESSION_HEADER, TENANT_HEADER

#: Synthetic status for transport-level failures (connect refused, socket
#: timeout): the request died below HTTP, which open-loop accounting must
#: still count against goodput.
TRANSPORT_ERROR_STATUS = 599


def http_target(url: str, timeout_s: float = 60.0):
    """Adapt a ``/generate`` URL into a generator target. Transport
    failures become status ``TRANSPORT_ERROR_STATUS`` — never exceptions;
    an open-loop run must account every scheduled request."""
    from edgemesh.fleet.transport import HttpTransport, TransportError

    transport = HttpTransport()

    def call(payload: dict, headers: dict) -> tuple[int, dict]:
        try:
            return transport.post_json(url, payload, timeout_s=timeout_s,
                                       headers=headers)
        except TransportError as e:
            return TRANSPORT_ERROR_STATUS, {"error": str(e)}

    return call


@dataclass
class RequestOutcome:
    """One launched request's fate, timed against its schedule slot."""

    tenant: str
    lane: str
    session: str
    scheduled_s: float        # schedule offset from run start
    launch_skew_s: float      # actual send - scheduled (generator health)
    latency_s: float          # completion - SCHEDULED arrival (the honest one)
    status: int
    ok: bool


class OpenLoopGenerator:
    """Drive one schedule open-loop against one target."""

    def __init__(self, target, schedule: list[ScheduledRequest],
                 slo_latency_s: float | None = None,
                 duration_s: float | None = None,
                 max_threads: int = 512) -> None:
        self.target = target
        self.schedule = sorted(schedule, key=lambda r: r.at_s)
        # The nominal window offered_rps/goodput_rps divide by; falls back
        # to the last scheduled arrival when the caller has no nominal.
        self.duration_s = duration_s
        # The client-side SLO: a request is GOOD iff it answered 200
        # within this many seconds of its scheduled arrival. Defaults to
        # the deployment's TTFT target (for the non-streaming front door
        # the full answer is the first client-visible byte).
        self.slo_latency_s = (
            float(slo_latency_s) if slo_latency_s is not None
            else SloTarget.from_env().ttft_s
        )
        self.max_threads = int(max_threads)

    def run(self) -> dict:
        """Execute the schedule; returns the report dict (see
        :func:`summarize`). Blocks until every launched request resolves
        (each is itself bounded by the target's timeout)."""
        outcomes: list[RequestOutcome | None] = [None] * len(self.schedule)
        threads: list[threading.Thread] = []
        # Backstop against unbounded live-thread growth on a wedged
        # target: the launcher blocks on the gate past ``max_threads``
        # in-flight workers — the stall is visible as launch skew, never
        # silently dropped work. A semaphore, not a liveness scan: the
        # launch loop must stay O(1) per request or the launcher itself
        # slips at exactly the high-rate points the knee is measured at.
        gate = threading.BoundedSemaphore(self.max_threads)
        t0 = time.monotonic()

        def fire(i: int, req: ScheduledRequest) -> None:
            try:
                sent = time.monotonic()
                # Tenant selects admission policy + telemetry; session is
                # span-record identity only — it is what lets `obs replay`
                # rebuild this schedule's session grouping from the logs.
                headers = {TENANT_HEADER: req.tenant,
                           SESSION_HEADER: req.session}
                status, _body = self.target(req.payload(), headers)
                done = time.monotonic()
                sched_abs = t0 + req.at_s
                outcomes[i] = RequestOutcome(
                    tenant=req.tenant, lane=req.lane, session=req.session,
                    scheduled_s=req.at_s,
                    launch_skew_s=sent - sched_abs,
                    latency_s=done - sched_abs,
                    status=status, ok=status == 200,
                )
            finally:
                gate.release()

        for i, req in enumerate(self.schedule):
            # Open-loop: sleep to the SCHEDULE, never to a completion.
            delay = (t0 + req.at_s) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            gate.acquire()
            th = threading.Thread(target=fire, args=(i, req), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        duration_s = self.duration_s or (
            max((r.at_s for r in self.schedule), default=0.0)
            or time.monotonic() - t0
        )
        return summarize([o for o in outcomes if o is not None],
                         duration_s=max(duration_s, 1e-9),
                         slo_latency_s=self.slo_latency_s)


def _pct(xs: list[float], q: float) -> float | None:
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(q * len(xs)))], 6)


def _bucket(outcomes: list[RequestOutcome], duration_s: float,
            slo_latency_s: float) -> dict:
    lat = [o.latency_s for o in outcomes if o.ok]
    good = sum(1 for o in outcomes
               if o.ok and o.latency_s <= slo_latency_s)
    n = len(outcomes)
    return {
        "scheduled": n,
        "offered_rps": round(n / duration_s, 4),
        "ok": sum(1 for o in outcomes if o.ok),
        "shed": sum(1 for o in outcomes if o.status in (429, 503)),
        "ratelimited": sum(1 for o in outcomes if o.status == 429),
        "errors": sum(
            1 for o in outcomes
            if not o.ok and o.status not in (429, 503)
        ),
        "good": good,
        "goodput_rps": round(good / duration_s, 4),
        # Against SCHEDULED, not answered: a shed request is a goodput
        # miss — that asymmetry is the whole observatory.
        "goodput_ratio": round(good / n, 4) if n else None,
        "latency_s_p50": _pct(lat, 0.50),
        "latency_s_p99": _pct(lat, 0.99),
    }


def summarize(outcomes: list[RequestOutcome], duration_s: float,
              slo_latency_s: float) -> dict:
    """Aggregate + per-tenant open-loop report (the ``load_curve`` point
    schema; docs/OBSERVABILITY.md documents every key)."""
    tenants = sorted({o.tenant for o in outcomes})
    report = {
        "duration_s": round(duration_s, 4),
        "slo_latency_s": slo_latency_s,
        "max_launch_skew_s": round(
            max((o.launch_skew_s for o in outcomes), default=0.0), 6
        ),
        **_bucket(outcomes, duration_s, slo_latency_s),
        "tenants": {
            t: _bucket([o for o in outcomes if o.tenant == t],
                       duration_s, slo_latency_s)
            for t in tenants
        },
    }
    return report
