"""Goodput-vs-offered-load curves and the saturation knee.

The production-grade serving report is not one throughput number — it is
the CURVE: sweep offered load across points, plot goodput (requests/s
meeting the SLO) against it, and find where the curve stops following the
diagonal. Below the knee, goodput tracks offered load (the fleet absorbs
everything); past it, queueing delay eats the SLO budget and goodput
flattens — then COLLAPSES as sheds and timeouts take over. Everything
interesting about a serving stack (admission quality, fairness, hedging)
is a statement about the shape of this curve.
"""

from __future__ import annotations


def find_knee(points: list[dict]) -> dict:
    """Identify the saturation knee in a sorted list of curve points
    (each ``{"offered_rps": ..., "goodput_rps": ...}``).

    The knee is the offered load with the highest goodput (ties → lowest
    offered load: pushing harder for nothing is past the knee by
    definition). ``collapsed`` reports whether the curve then came DOWN —
    the highest offered point's goodput fell more than 10% below the knee
    — which distinguishes saturation (flat) from collapse (the overload
    regime open-loop measurement exists to expose)."""
    if not points:
        return {"knee_offered_rps": None, "knee_goodput_rps": None,
                "collapsed": False}
    pts = sorted(points, key=lambda p: p["offered_rps"])
    knee = max(pts, key=lambda p: (p.get("goodput_rps") or 0.0,
                                   -p["offered_rps"]))
    last = pts[-1]
    knee_gp = knee.get("goodput_rps") or 0.0
    collapsed = bool(
        last["offered_rps"] > knee["offered_rps"]
        and (last.get("goodput_rps") or 0.0) < 0.9 * knee_gp
    )
    return {
        "knee_offered_rps": knee["offered_rps"],
        "knee_goodput_rps": knee_gp,
        "collapsed": collapsed,
    }


def run_curve(make_run, rates: list[float]) -> dict:
    """Sweep ``rates`` (aggregate offered rps) through ``make_run(rate) ->
    report`` (an :class:`~edgemesh.loadgen.generator.OpenLoopGenerator`
    run at that rate) and assemble the curve document: one point per
    rate (the generator report + the requested rate) plus the knee.

    ``make_run`` owns workload construction so each point can rebuild the
    tenant mix scaled to its rate — the curve is over IDENTICALLY SHAPED
    traffic at different intensities, not different workloads."""
    points = []
    for rate in rates:
        report = make_run(rate)
        points.append({"requested_rps": rate, **report})
    curve = {
        "points": [
            {
                "requested_rps": p["requested_rps"],
                "offered_rps": p["offered_rps"],
                "goodput_rps": p["goodput_rps"],
                "goodput_ratio": p["goodput_ratio"],
                "shed": p["shed"],
                "errors": p["errors"],
                "latency_s_p50": p["latency_s_p50"],
                "latency_s_p99": p["latency_s_p99"],
                "tenants": p["tenants"],
            }
            for p in points
        ],
        "slo_latency_s": points[0]["slo_latency_s"] if points else None,
    }
    curve.update(find_knee(curve["points"]))
    return curve
