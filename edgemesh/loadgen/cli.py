"""``edgemesh loadgen`` — drive a serving endpoint open-loop.

One shot (``--rate``) prints the open-loop report for a single offered
load; a sweep (``--sweep r1,r2,r3``) prints the goodput-vs-offered-load
curve document with the saturation knee identified; ``--replay
workload.json`` drives a recorded workload rebuilt by ``edgemesh obs
replay`` (incident regression runs). Render reports with ``edgemesh obs
loadreport``. No jax, no device — point it at any ``/generate`` endpoint
(a replica gateway or the fleet frontend).

Tenant mixes: ``--tenant name=share[:lane]`` (repeatable) splits the
aggregate rate by share, e.g. ``--tenant chat=3:interactive --tenant
bulk=1:batch`` sends 75%/25%. Each tenant tags its requests with
``X-Edgemesh-Tenant`` so the router's admission policies and the
per-tenant telemetry see exactly this traffic.
"""

from __future__ import annotations

import argparse
import json
import sys

from edgemesh.loadgen.arrivals import DiurnalBurstProcess, PoissonProcess
from edgemesh.loadgen.curve import run_curve
from edgemesh.loadgen.generator import OpenLoopGenerator, http_target
from edgemesh.loadgen.workload import LengthMix, TenantSpec, Workload


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="edgemesh loadgen",
        description="open-loop load observatory (docs/OBSERVABILITY.md "
        "'The load observatory')",
    )
    p.add_argument("--url", required=True,
                   help="the /generate endpoint to drive (fleet frontend "
                   "or a single replica gateway)")
    p.add_argument("--target", default="generate",
                   choices=["generate", "ensemble"],
                   help="which serving route the traffic drives: "
                   "'ensemble' rewrites the URL's path to the fleet "
                   "frontend's POST /ensemble fan-out (same tenant/"
                   "session/SLO accounting — docs/FLEET.md 'Ensemble "
                   "serving')")
    p.add_argument("--rate", type=float, default=2.0,
                   help="aggregate offered load in requests/s")
    p.add_argument("--sweep", default=None, metavar="R1,R2,...",
                   help="sweep these aggregate rates and emit the "
                   "goodput-vs-offered-load curve (overrides --rate)")
    p.add_argument("--replay", default=None, metavar="WORKLOAD.JSON",
                   help="drive a recorded workload document (written by "
                   "`edgemesh obs replay`) instead of a synthetic mix — "
                   "arrivals, prompts, tenants and sessions come from the "
                   "document; --rate/--sweep/--duration and the mix flags "
                   "are ignored")
    p.add_argument("--duration", type=float, default=10.0,
                   help="seconds of scheduled traffic per point")
    p.add_argument("--arrival", default="poisson",
                   choices=["poisson", "diurnal"],
                   help="arrival process (diurnal = sinusoidal swing + "
                   "bursts; see --period-s/--peak-factor/--burst-rps)")
    p.add_argument("--period-s", type=float, default=60.0)
    p.add_argument("--peak-factor", type=float, default=3.0,
                   help="diurnal: peak rate as a multiple of the trough")
    p.add_argument("--burst-rps", type=float, default=0.0)
    p.add_argument("--burst-every-s", type=float, default=0.0)
    p.add_argument("--tenant", action="append", default=[],
                   metavar="NAME=SHARE[:LANE]",
                   help="tenant mix entry, repeatable (shares normalized; "
                   "lane interactive|batch, default interactive)")
    p.add_argument("--slo-latency-s", type=float, default=None,
                   help="client-side SLO: a request is good iff answered "
                   "200 within this many seconds of its SCHEDULED arrival "
                   "(default: the EDGEMESH_SLO_TTFT_S target)")
    p.add_argument("--timeout-s", type=float, default=60.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prompt-median", type=int, default=48,
                   help="long-tail prompt-length mix: median chars")
    p.add_argument("--prompt-sigma", type=float, default=0.6,
                   help="long-tail prompt-length mix: lognormal sigma")
    p.add_argument("--sessions", type=int, default=4,
                   help="concurrent multi-turn sessions per tenant "
                   "(shared-prefix traffic for prefix_affinity routing)")
    p.add_argument("--turns", type=float, default=3.0,
                   help="mean turns per session before the prefix resets")
    p.add_argument("--max-new", action="store_true",
                   help="attach a sampled per-request max_new budget "
                   "(continuous non-speculative replicas only)")
    p.add_argument("--out", default=None,
                   help="also write the report JSON here")
    return p


def resolve_target_url(url: str, target: str) -> str:
    """Point ``url`` at the requested serving route: a bare base URL gets
    the route appended; a URL already ending in ``/generate`` or
    ``/ensemble`` is rewritten, so existing ``--url .../generate`` command
    lines switch routes with nothing but ``--target ensemble``."""
    base = url.rstrip("/")
    for suffix in ("/generate", "/ensemble"):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
            break
    return base + "/" + target


def _tenant_shares(specs: list[str]) -> list[tuple[str, float, str]]:
    if not specs:
        return [("default", 1.0, "interactive")]
    out = []
    for spec in specs:
        name, _, rest = spec.partition("=")
        if not name or not rest:
            raise SystemExit(f"bad --tenant {spec!r} (want NAME=SHARE[:LANE])")
        share, _, lane = rest.partition(":")
        out.append((name, float(share), lane or "interactive"))
    total = sum(s for _, s, _ in out)
    if total <= 0:
        raise SystemExit("tenant shares must sum > 0")
    return [(n, s / total, lane) for n, s, lane in out]


def _make_workload(args, rate: float) -> Workload:
    shares = _tenant_shares(args.tenant)
    prompt_mix = LengthMix(median=args.prompt_median, sigma=args.prompt_sigma)
    tenants = []
    for i, (name, share, lane) in enumerate(shares):
        t_rate = max(1e-6, rate * share)
        if args.arrival == "diurnal":
            # The requested rate is the MEAN of the sinusoid: trough/peak
            # placed symmetrically around it by --peak-factor.
            trough = 2.0 * t_rate / (1.0 + args.peak_factor)
            arrival = DiurnalBurstProcess(
                base_rps=max(1e-6, trough),
                peak_rps=max(trough, trough * args.peak_factor),
                period_s=args.period_s, burst_rps=args.burst_rps,
                burst_every_s=args.burst_every_s, seed=args.seed + i,
            )
        else:
            arrival = PoissonProcess(t_rate, seed=args.seed + i)
        tenants.append(TenantSpec(
            name=name, arrival=arrival, lane=lane, prompt_mix=prompt_mix,
            sessions=args.sessions, turns_mean=args.turns,
            send_max_new=args.max_new,
        ))
    return Workload(tenants, seed=args.seed)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    target = http_target(resolve_target_url(args.url, args.target),
                         timeout_s=args.timeout_s)

    if args.replay:
        # Incident replay: the recorded schedule IS the traffic — the
        # open-loop driver, SLO accounting, and report schema are the
        # standard ones (zero replay-specific measurement code).
        from edgemesh.loadgen.workload import ReplayWorkload

        try:
            with open(args.replay) as f:
                wl = ReplayWorkload.from_doc(json.load(f))
        except FileNotFoundError:
            print(f"error: no such workload: {args.replay}", file=sys.stderr)
            return 2
        except (ValueError, json.JSONDecodeError) as e:
            print(f"error: bad workload document: {e}", file=sys.stderr)
            return 2
        gen = OpenLoopGenerator(
            target, wl.build_schedule(),
            slo_latency_s=args.slo_latency_s,
            duration_s=wl.meta.get("duration_s") or wl.duration_s,
        )
        doc = gen.run()
        doc["replayed_from"] = args.replay
        text = json.dumps(doc, indent=2)
        print(text)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
        return 0

    def run_at(rate: float) -> dict:
        wl = _make_workload(args, rate)
        gen = OpenLoopGenerator(
            target, wl.build_schedule(args.duration),
            slo_latency_s=args.slo_latency_s, duration_s=args.duration,
        )
        return gen.run()

    if args.sweep:
        rates = [float(r) for r in args.sweep.split(",") if r.strip()]
        if len(rates) < 2:
            raise SystemExit("--sweep needs at least two rates")
        doc = run_curve(run_at, rates)
    else:
        doc = run_at(args.rate)
    text = json.dumps(doc, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
