"""edgelint — AST linter with JAX/TPU-specific rules.

Rules (see docs/ANALYSIS.md for the full rationale and examples):

- EM101 jax-api-drift (error): direct use of a JAX API that moved or was
  removed across the versions this codebase meets (``jax.experimental.
  shard_map``/``maps``/``pjit``/``host_callback``, ``jax.shard_map``,
  ``lax.pcast``, ``lax.axis_size``). Call sites must go through
  ``edgemesh.utils.compat`` — the one allowlisted module.
- EM102 host-sync-in-jit (error): ``.item()``, ``.tolist()``, ``float()``,
  ``np.asarray``/``np.array`` inside traced code — each forces a device→host
  readback per call (or fails at trace time), turning an async dispatch
  pipeline into a round-trip per step.
- EM103 unsynced-timing (warning): two or more wall-clock reads in a
  function that dispatches device work between them with no completion
  fence (``block_until_ready``/``device_sync``/``tree_sync``/readback) —
  the async-dispatch measurement bug: the timed window closes before the
  device finishes.
- EM104 dead-jit-param (warning): a parameter of a jit-decorated function
  never referenced in its body (the ``len_cap`` failure mode: callers pay
  transfer + retrace keying on an argument that cannot change the result).
- EM105 jit-loop-unroll (warning): a Python ``for``/``while`` inside traced
  code whose body does jnp/lax work — unrolls into the XLA graph; compile
  time and program size scale with the trip count (use ``lax.scan``/
  ``fori_loop``, or suppress for small fixed trip counts).
- EM106 print-in-jit (warning): ``print`` (incl. f-string payloads) inside
  traced code — runs at TRACE time only (or leaks ``Traced<...>`` reprs);
  use ``jax.debug.print`` for runtime values.
- EM107 raw-timing-in-serving (warning): a raw wall-clock read
  (``time.time``/``perf_counter``/``monotonic``) inside ``edgemesh/serve/``
  or ``edgemesh/runtime/`` — serving-stack timing belongs to the obs
  substrate (``edgemesh.obs.SpanTracker`` hooks / ``utils.tracing.trace``)
  so it lands in spans, histograms, and ``/metrics`` instead of ad-hoc
  deltas. Result-payload windows use ``utils.tracing.Stopwatch`` or the
  handle ``trace()`` yields; clocks that ARE the obs instrumentation (or
  wait control flow) carry an inline disable.
- EM110 serve-per-row-dispatch (error): a HOST loop in
  ``edgemesh/serve/`` that calls a jitted forward per iteration — a name
  imported from edgemesh.runtime/models matching ``forward_*``/
  ``generate*``/``_decode_loop``/``_spec_rounds``, a local ``jax.jit``
  binding, or a jit-decorated def. Per-row dispatch is exactly the wave
  structure the ragged boundary launch (forward_ragged_paged) deleted:
  one launch serves admission prefill and resident decode together, and
  a Python loop re-introducing per-segment dispatches must not creep
  back. Loops inside traced code are EM105's beat; method-call
  indirection (``self._admit``) is out of scope by design — the retained
  segmented ablation path dispatches through it.

- EM111 metric-naming (warning): a metric registered through the obs
  registry (``.counter/.gauge/.histogram`` with a literal name, anywhere
  under ``edgemesh/``) must carry the ``edgemesh_`` prefix; counters must
  end ``_total`` and gauges/histograms must not — one naming convention
  keeps dashboards, rate() queries, and scrape relabeling honest across
  every subsystem.

- EM112 unbounded-metric-label (error): a ``.labels(...)`` call under
  ``edgemesh/`` binding a request-identity label (``tenant``/``session``/
  ``user`` and their ``_id`` variants) to a value that does not flow
  through ``obs.metrics.bounded_label`` — raw client-controlled strings
  mint one time series per distinct value on EVERY family carrying the
  label, so one abusive client can grow the scrape without bound.
  Accepted values: string constants, direct ``bounded_label(...)`` calls,
  and names whose function-local assignment chain ends in one of those;
  a name with no visible local assignment (a parameter, an outer/module
  binding) is trusted as pre-normalized — normalize at the seam where the
  raw value enters, then pass the bounded value down. Subscripts
  (``rec["tenant"]``) and calls other than the normalizer
  (``payload.get("tenant")``) flag, inline or via a tainted local.

- EM113 span-schema-bypass (error): a ``json.dumps`` + file write, under
  ``edgemesh/``, of a record carrying the span event key (``"event"`` in
  the span vocabulary, or a ``"spans"`` list) outside the sanctioned
  producers (``SpanTracker``/``FlightRecorder``/``JsonlLogger``) —
  replay (`obs replay`), assembly (`obs trace`/`incident`), and the
  offline aggregate rebuild all depend on ONE producer vocabulary, and a
  hand-rolled writer is a second vocabulary waiting to drift.

- EM114 ungated-device-sync (error): a ``.block_until_ready()`` or
  ``jax.device_get`` call inside ``edgemesh/serve/`` or
  ``edgemesh/runtime/``. An ungated sync stalls the pipelined dispatch
  worker for the full program — and on the tunneled TPU platform
  ``block_until_ready`` returns before the program finishes, so it is
  not even a fence (``utils/platform.py``). Measured syncs belong to the
  compute ledger's SAMPLED launch seam (``obs.compute.ComputeLedger`` —
  1-in-N, using the real ``device_sync`` readback); ``device_sync``
  itself stays legal everywhere (it IS the fence primitive), and the
  segment-result fetch of already-complete handles carries an inline
  disable.

The class-level concurrency rules (EM301-EM304: lock discipline,
lock-order cycles, blocking-under-lock, thread hygiene) live in
``edgemesh/analysis/concurrency.py``, and the sharding/collective rules
(EM401-EM404: unbound collective axes, spec mismatches, unreduced sharded
contractions, retrace hazards) in ``edgemesh/analysis/sharding.py`` —
both ride the same entry points: ``lint_source``/``lint_file`` return
every pass's findings.

Suppression: append ``# edgelint: disable=EM105`` (comma-separate for
several rules) to the flagged line, or put the comment on the ``def`` line
to suppress within that whole function.
"""

from __future__ import annotations

import ast
from pathlib import Path

from edgemesh.analysis.findings import DISABLE_RE, Finding, repo_relative

RULES: dict[str, dict] = {
    "EM101": {
        "name": "jax-api-drift",
        "severity": "error",
        "summary": "drifted/removed JAX API used directly (go through edgemesh.utils.compat)",
    },
    "EM102": {
        "name": "host-sync-in-jit",
        "severity": "error",
        "summary": "host readback (.item()/float()/np.asarray) inside traced code",
    },
    "EM103": {
        "name": "unsynced-timing",
        "severity": "warning",
        "summary": "wall-clock window around device work without a completion fence",
    },
    "EM104": {
        "name": "dead-jit-param",
        "severity": "warning",
        "summary": "parameter of a jitted function never used in its body",
    },
    "EM105": {
        "name": "jit-loop-unroll",
        "severity": "warning",
        "summary": "Python loop over jnp/lax work inside traced code (unrolls the graph)",
    },
    "EM106": {
        "name": "print-in-jit",
        "severity": "warning",
        "summary": "print inside traced code runs at trace time (use jax.debug.print)",
    },
    "EM107": {
        "name": "raw-timing-in-serving",
        "severity": "warning",
        "summary": "raw wall-clock read in serve//runtime/ bypasses edgemesh.obs spans",
    },
    "EM110": {
        "name": "serve-per-row-dispatch",
        "severity": "error",
        "summary": "host loop in edgemesh/serve/ dispatches a jitted forward per iteration",
    },
    "EM111": {
        "name": "metric-naming",
        "severity": "warning",
        "summary": "metric name breaks the edgemesh_ prefix / _total suffix convention",
    },
    "EM112": {
        "name": "unbounded-metric-label",
        "severity": "error",
        "summary": "request-derived label value bypasses obs.metrics.bounded_label",
    },
    "EM113": {
        "name": "span-schema-bypass",
        "severity": "error",
        "summary": "span-event JSONL written outside SpanTracker/FlightRecorder/JsonlLogger",
    },
    "EM114": {
        "name": "ungated-device-sync",
        "severity": "error",
        "summary": "block_until_ready/device_get in serve//runtime/ outside the ledger's sampled seam",
    },
    "EM115": {
        "name": "pool-mutation-outside-ledger",
        "severity": "error",
        "summary": "page-pool free list mutated in serve//runtime/ outside the PoolLedger seam",
    },
}

# ---------------------------------------------------------------------------
# EM101 tables
# ---------------------------------------------------------------------------

# Modules whose import (any form) is drift: removed upstream, or absent on
# older jax. Values are the guidance appended to the message.
_DRIFTED_MODULES = {
    "jax.experimental.shard_map": "use edgemesh.utils.compat.shard_map",
    "jax.experimental.maps": "xmap/Mesh moved; use jax.sharding.Mesh",
    "jax.experimental.pjit": "use jax.jit with shardings",
    "jax.experimental.host_callback": "use jax.debug.callback / jax.pure_callback",
}

# Dotted attribute accesses that only exist on one side of the drift.
_DRIFTED_ATTRS = {
    "jax.shard_map": "use edgemesh.utils.compat.shard_map",
    "jax.lax.pcast": "use edgemesh.utils.compat.pcast",
    "jax.lax.axis_size": "use edgemesh.utils.compat.axis_size",
}

# Files allowed to touch either spelling (the shim itself).
_EM101_ALLOWED_SUFFIXES = ("edgemesh/utils/compat.py",)

# EM102: attribute calls that force a device→host readback.
_HOST_SYNC_METHODS = {"item", "tolist"}
_HOST_SYNC_NP_FUNCS = {"asarray", "array"}

# EM103: wall-clock sources and completion fences. Fences come in two
# spellings: method-style (``x.block_until_ready()``) and function-style
# (``device_sync(x)``, edgemesh.utils.platform's readback fence).
_CLOCK_FUNCS = {"time.time", "time.perf_counter", "time.monotonic"}
_FENCE_METHODS = {"block_until_ready", "device_sync", "tree_sync", "result"}
_FENCE_FUNCS = {"block_until_ready", "device_sync", "tree_sync"}

_DISABLE_RE = DISABLE_RE  # shared home: findings.py (concurrency.py uses it too)

# EM107 scope: the serving stack, where every wall-clock read should flow
# through the obs substrate. Path-substring match (like the EM101 allowlist)
# so fixture tests with relative paths resolve the same everywhere.
_EM107_DIRS = ("edgemesh/serve/", "edgemesh/runtime/")

# EM110 scope + dispatch surface: host loops in the serving engine must not
# re-grow per-row jitted dispatches (the pre-ragged wave structure). A name
# counts as a jitted forward when imported from an edgemesh module with one
# of these shapes, locally bound to a jax.jit expression, or defined under a
# jit decorator in the same file.
_EM110_DIRS = ("edgemesh/serve/",)
_EM110_IMPORT_PREFIXES = ("forward_", "generate")
_EM110_IMPORT_EXTRA = {"_decode_loop", "_spec_rounds"}

# EM111 scope + surface: registrations through the obs registry —
# ``<anything>.counter/gauge/histogram("name", ...)`` with a LITERAL name
# (dynamic names are out of scope; the registry call sites in this repo are
# all literal). Shipped-package scope only: tests and docs register
# throwaway families on purpose. The convention (docs/OBSERVABILITY.md):
# every metric carries the ``edgemesh_`` namespace prefix, counters end
# ``_total`` (Prometheus convention for monotone totals), and gauge/
# histogram names must NOT — a ``_total`` gauge reads as a counter on every
# dashboard and breaks rate() queries.
_EM111_DIRS = ("edgemesh/",)
_EM111_METHODS = {"counter", "gauge", "histogram"}
_EM111_PREFIX = "edgemesh_"

# EM112 scope + surface: ``.labels(...)`` keyword values for the
# request-identity label names below. Shipped-package scope only (tests
# register throwaway families with literal values on purpose; the scope
# match also keeps docs snippets out). The one sanctioned normalizer is
# obs.metrics.bounded_label — allowlist + first-N seen-set + the `other`
# overflow bucket (docs/OBSERVABILITY.md "tenant label cardinality").
_EM112_DIRS = ("edgemesh/",)
_EM112_LABELS = {"tenant", "session", "user", "tenant_id", "session_id",
                 "user_id"}
_EM112_NORMALIZER = "bounded_label"

# EM113 scope + surface: span-event JSONL must have ONE producer
# vocabulary — replay (`obs replay`), assembly (`obs trace`/`incident`),
# and the aggregate rebuild (`obs summary`) all key on the record shape
# SpanTracker/FlightRecorder flush through JsonlLogger. A hand-rolled
# ``json.dumps`` + file write of a record carrying the span event key
# (an ``"event"`` in the span vocabulary, or a ``"spans"`` list) is a
# second producer that silently drifts. Allowlisted: the sanctioned
# producers themselves.
_EM113_DIRS = ("edgemesh/",)
_EM113_ALLOWED_SUFFIXES = (
    "edgemesh/utils/tracing.py",   # JsonlLogger — THE serializer
    "edgemesh/obs/spans.py",       # SpanTracker
    "edgemesh/obs/flight.py",      # FlightRecorder
    "edgemesh/obs/compute.py",     # ComputeLedger / SpecRoundLedger
    "edgemesh/obs/memory.py",      # PoolLedger
)
_EM113_EVENTS = {"request_spans", "router_spans", "pool_reset", "compile",
                 "flight_snapshot", "flight_dump", "launch", "spec_rounds",
                 "pool_mem"}
_EM113_EVENT_CONSTS = {"SPAN_RECORD_EVENT", "ROUTER_RECORD_EVENT",
                       "RESET_RECORD_EVENT", "COMPILE_RECORD_EVENT",
                       "ENGINE_RECORD_EVENT", "SNAPSHOT_EVENT",
                       "DUMP_EVENT", "LAUNCH_RECORD_EVENT",
                       "SPEC_ROUND_RECORD_EVENT", "POOL_RECORD_EVENT"}

# EM114 scope + surface: synchronous device readbacks in the serving
# stack. An ungated ``.block_until_ready()`` / ``jax.device_get`` stalls
# the pipelined dispatch worker for the full program (and on the tunneled
# TPU platform block_until_ready returns EARLY — it is not even a fence;
# utils/platform.py). The sanctioned seams: the compute ledger's SAMPLED
# launch fence (obs/compute.py — 1-in-N by design, and it uses the real
# ``device_sync`` readback), ``utils.platform.device_sync`` itself (stays
# legal: it IS the fence primitive), and the segment-result fetch of
# already-complete handles, which carries an inline disable.
_EM114_DIRS = ("edgemesh/serve/", "edgemesh/runtime/")
_EM114_METHOD = "block_until_ready"
_EM114_FUNCS = {"jax.device_get", "jax.block_until_ready"}

# EM115 scope + surface: host-side page-pool mutations in the serving
# stack. The memory observatory's conservation invariant (obs/memory.py:
# ``free + resident + overhead == total`` at every quiesce) only holds if
# EVERY pool transition reports to the PoolLedger — a free list popped or
# extended behind its back is the exact leak-shaped bug the ledger exists
# to catch, planted in the accounting itself. A function is on the seam
# when it references the ledger (``.mem`` / ``.dmem`` / ``PoolLedger``)
# or routes through the engine's ``_pop_pages`` / ``_push_pages``
# helpers; mutations anywhere else are flagged.
_EM115_DIRS = ("edgemesh/serve/", "edgemesh/runtime/")
_EM115_POOLS = ("_free_pages", "_dfree", "_template_pages")
_EM115_MUTATORS = {"pop", "popleft", "append", "extend", "clear",
                   "remove", "insert"}
_EM115_SEAM_ATTRS = ("mem", "dmem")
_EM115_SEAM_CALLS = ("_pop_pages", "_push_pages")
_EM115_SEAM_NAME = "PoolLedger"


# ---------------------------------------------------------------------------
# Import/alias resolution
# ---------------------------------------------------------------------------


class _Aliases:
    """Maps local names to the dotted module/object path they were imported
    as, so ``from jax import lax; lax.pcast`` resolves to ``jax.lax.pcast``."""

    def __init__(self) -> None:
        self.map: dict[str, str] = {}

    def visit_import(self, node: ast.Import) -> None:
        for a in node.names:
            self.map[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def visit_import_from(self, node: ast.ImportFrom) -> None:
        if node.level or not node.module:
            return  # relative imports never reach jax
        for a in node.names:
            self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        base = self.map.get(head, head)
        return f"{base}.{rest}" if rest else base


def _walk_own(fn: ast.AST):
    """Walk fn's body without descending into nested function defs (those
    get their own per-def rule runs)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _dotted_name(node: ast.AST) -> str | None:
    """'jax.experimental.shard_map' for nested Attribute/Name chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# Traced-function discovery
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jax.pmap", "jax.experimental.jax2tf.convert"}
_TRACING_HOFS = {
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.cond",
    "jax.lax.switch", "jax.lax.map", "jax.lax.associative_scan",
    "jax.checkpoint", "jax.remat", "jax.vmap", "jax.grad",
    "jax.value_and_grad", "jax.eval_shape",
}


def _is_jit_expr(node: ast.AST, aliases: _Aliases) -> bool:
    """True for expressions that evaluate to a jit transform: ``jax.jit``,
    ``partial(jax.jit, ...)``, ``jax.jit(...)`` (decorator-factory form)."""
    dotted = _dotted_name(node)
    if dotted and aliases.resolve(dotted) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fd = _dotted_name(node.func)
        if fd:
            rf = aliases.resolve(fd)
            if rf in _JIT_NAMES:
                return True
            if rf in ("functools.partial", "partial") and node.args:
                return _is_jit_expr(node.args[0], aliases)
    return False


class _TracedCollector(ast.NodeVisitor):
    """Finds function defs whose bodies run under tracing: jit-decorated
    defs, defs nested inside them, defs handed to lax control-flow HOFs, and
    ``g = jax.jit(f)`` rebinds."""

    def __init__(self, aliases: _Aliases) -> None:
        self.aliases = aliases
        self.jit_decorated: set[ast.AST] = set()
        self.traced: set[ast.AST] = set()
        self._defs_by_name: dict[str, list[ast.AST]] = {}
        self._hof_callees: set[str] = set()
        self._jit_wrapped: set[str] = set()
        self._stack: list[ast.AST] = []

    def _visit_def(self, node) -> None:
        self._defs_by_name.setdefault(node.name, []).append(node)
        if any(_is_jit_expr(d, self.aliases) for d in node.decorator_list):
            self.jit_decorated.add(node)
            self.traced.add(node)
        elif any(d in self.traced for d in self._stack):
            self.traced.add(node)
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_Call(self, node: ast.Call) -> None:
        fd = _dotted_name(node.func)
        if fd and self.aliases.resolve(fd) in _TRACING_HOFS:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    self._hof_callees.add(arg.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # g = jax.jit(f)  /  g = partial(jax.jit, ...)(f)
        if isinstance(node.value, ast.Call) and _is_jit_expr(node.value.func, self.aliases):
            for arg in node.value.args:
                if isinstance(arg, ast.Name):
                    self._jit_wrapped.add(arg.id)
        self.generic_visit(node)

    def finalize(self) -> None:
        """Propagate tracedness to HOF callees / jit-wrapped names, then to
        defs nested inside anything newly traced (fixpoint)."""
        for name in self._hof_callees | self._jit_wrapped:
            for d in self._defs_by_name.get(name, []):
                self.traced.add(d)
                if name in self._jit_wrapped:
                    self.jit_decorated.add(d)
        changed = True
        while changed:
            changed = False
            for defs in self._defs_by_name.values():
                for d in defs:
                    if d in self.traced:
                        for sub in ast.walk(d):
                            if (
                                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                                and sub not in self.traced
                            ):
                                self.traced.add(sub)
                                changed = True


# ---------------------------------------------------------------------------
# The linter
# ---------------------------------------------------------------------------


class _FileLinter:
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.relpath = repo_relative(path)
        self.source = source
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.aliases = _Aliases()
        # line -> set of disabled rules; a disable on a `def` line covers
        # the whole function (handled in _suppressed).
        self.disabled: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _DISABLE_RE.search(line)
            if m:
                self.disabled[i] = {r.strip() for r in m.group(1).split(",")}
        self._scopes: list[ast.AST] = []

    # -- infrastructure ----------------------------------------------------

    def _suppressed(self, rule: str, line: int) -> bool:
        if rule in self.disabled.get(line, ()):
            return True
        for scope in self._scope_stack_for_line(line):
            if rule in self.disabled.get(scope.lineno, ()):
                return True
        return False

    def _scope_stack_for_line(self, line: int) -> list[ast.AST]:
        return [
            s for s in getattr(self, "_all_defs", [])
            if s.lineno <= line <= getattr(s, "end_lineno", s.lineno)
        ]

    def _context_for_line(self, line: int) -> str:
        best = ""
        for s in getattr(self, "_all_defs", []):
            if s.lineno <= line <= getattr(s, "end_lineno", s.lineno):
                best = s.name if not best else f"{best}.{s.name}"
        return best

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(rule, line):
            return
        self.findings.append(
            Finding(
                rule=rule,
                severity=RULES[rule]["severity"],
                path=self.relpath,
                line=line,
                message=message,
                context=self._context_for_line(line),
                line_text=(self.lines[line - 1].strip() if line <= len(self.lines) else ""),
            )
        )

    # -- driver ------------------------------------------------------------

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError as e:
            self.findings.append(
                Finding("EM000", "error", self.relpath, e.lineno or 1,
                        f"syntax error: {e.msg}")
            )
            return self.findings
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                self.aliases.visit_import(node)
            elif isinstance(node, ast.ImportFrom):
                self.aliases.visit_import_from(node)
        self._all_defs = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        collector = _TracedCollector(self.aliases)
        collector.visit(tree)
        collector.finalize()
        self.traced = collector.traced
        self.jit_decorated = collector.jit_decorated

        self._rule_api_drift(tree)
        self._rule_raw_timing(tree)
        self._rule_serve_row_dispatch(tree)
        self._rule_metric_naming(tree)
        self._rule_unbounded_label(tree)
        self._rule_span_schema_bypass(tree)
        self._rule_ungated_sync(tree)
        self._rule_pool_mutation(tree)
        # Traced ROOTS only: their walkers descend into traced nested defs,
        # so running every traced def would double-report nested call sites.
        traced_roots = [
            fn for fn in self._all_defs
            if fn in self.traced
            and not any(
                fn is not p and fn in set(ast.walk(p))
                for p in self.traced
            )
        ]
        for fn in traced_roots:
            self._rule_host_sync(fn)
            self._rule_loop_unroll(fn)
            self._rule_print(fn)
        for fn in self._all_defs:
            if fn in self.jit_decorated:
                self._rule_dead_param(fn)
            self._rule_unsynced_timing(fn)
        # One finding per (rule, line, message): nested Attribute chains and
        # nested defs can hit the same site through more than one walk.
        # Message stays in the key so two DISTINCT findings anchored to the
        # same line (e.g. two dead params on one def) both survive.
        seen: set[tuple] = set()
        unique: list[Finding] = []
        for f in sorted(self.findings, key=lambda f: (f.line, f.rule)):
            key = (f.rule, f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        return self.findings

    # -- EM101 -------------------------------------------------------------

    def _rule_api_drift(self, tree: ast.Module) -> None:
        if any(self.relpath.endswith(sfx) for sfx in _EM101_ALLOWED_SUFFIXES):
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    hit = self._drifted_module(a.name)
                    if hit:
                        self._emit("EM101", node, f"import of drifted API {a.name!r} — {hit}")
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    hit = self._drifted_module(full) or self._drifted_module(node.module)
                    if hit is None and full in _DRIFTED_ATTRS:
                        hit = _DRIFTED_ATTRS[full]
                    if hit:
                        self._emit(
                            "EM101", node,
                            f"import of drifted API {full!r} — {hit}",
                        )
            elif isinstance(node, ast.Attribute):
                dotted = _dotted_name(node)
                if not dotted:
                    continue
                resolved = self.aliases.resolve(dotted)
                if resolved in _DRIFTED_ATTRS:
                    self._emit(
                        "EM101", node,
                        f"{resolved!r} does not exist across supported jax "
                        f"versions — {_DRIFTED_ATTRS[resolved]}",
                    )
                else:
                    hit = self._drifted_module(resolved)
                    if hit:
                        self._emit("EM101", node, f"use of drifted API {resolved!r} — {hit}")

    @staticmethod
    def _drifted_module(name: str) -> str | None:
        for mod, why in _DRIFTED_MODULES.items():
            if name == mod or name.startswith(mod + "."):
                return why
        return None

    # -- EM107 -------------------------------------------------------------

    def _rule_raw_timing(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM107_DIRS):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_name(node.func)
            if dotted and self.aliases.resolve(dotted) in _CLOCK_FUNCS:
                self._emit(
                    "EM107", node,
                    f"raw {self.aliases.resolve(dotted)}() in the serving "
                    "stack bypasses obs spans — record through "
                    "edgemesh.obs.SpanTracker / utils.tracing.trace() (or "
                    "suppress: control-flow clocks and the obs "
                    "instrumentation itself are legitimate)",
                )

    # -- EM114 -------------------------------------------------------------

    def _rule_ungated_sync(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM114_DIRS):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_name(node.func)
            if not dotted:
                continue
            resolved = self.aliases.resolve(dotted)
            method_style = dotted.endswith("." + _EM114_METHOD)
            if not method_style and resolved not in _EM114_FUNCS:
                continue
            what = (_EM114_METHOD if method_style
                    else resolved.rsplit(".", 1)[-1])
            self._emit(
                "EM114", node,
                f"ungated {what}() in the serving stack stalls the "
                "pipelined dispatch worker (and block_until_ready is not "
                "even a fence on the tunneled TPU platform — "
                "utils/platform.py). Route measured syncs through the "
                "compute ledger's sampled launch seam "
                "(obs.compute.ComputeLedger.launch) or "
                "utils.platform.device_sync at a structured readback "
                "point (suppress: fetching ALREADY-complete segment "
                "handles is legitimate)",
            )

    # -- EM115 -------------------------------------------------------------

    @staticmethod
    def _em115_terminal(node: ast.AST) -> str | None:
        """The rightmost name of an Attribute/Name chain (``self._free_pages``
        → ``_free_pages``), or None for anything else."""
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _em115_on_seam(self, fn: ast.AST) -> bool:
        for node in _walk_own(fn):
            if isinstance(node, ast.Attribute):
                if node.attr in _EM115_SEAM_ATTRS:
                    return True
                if node.attr in _EM115_SEAM_CALLS:
                    return True
            elif isinstance(node, ast.Name) and node.id == _EM115_SEAM_NAME:
                return True
        return False

    def _rule_pool_mutation(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM115_DIRS):
            return
        for fn in self._all_defs:
            if self._em115_on_seam(fn):
                continue
            for node in _walk_own(fn):
                hit = self._em115_mutation(node)
                if hit is None:
                    continue
                pool, how = hit
                self._emit(
                    "EM115", node,
                    f"direct {how} of pool {pool!r} outside the PoolLedger "
                    "seam — every page-pool transition must route through "
                    "the engine's _pop_pages/_push_pages (or report to the "
                    "ledger via engine.mem/.dmem), or the memory "
                    "observatory's conservation invariant silently breaks "
                    "(docs/OBSERVABILITY.md 'The memory observatory'; "
                    "suppress: pool construction before the ledger exists "
                    "is legitimate)",
                )

    def _em115_mutation(self, node: ast.AST) -> tuple[str, str] | None:
        """(pool_name, description) when ``node`` mutates a guarded pool:
        a mutator method call, or a (aug/ann/tuple) assignment targeting
        the pool or one of its elements."""
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _EM115_MUTATORS:
                name = self._em115_terminal(node.func.value)
                if name in _EM115_POOLS:
                    return name, f".{node.func.attr}() call"
            return None
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
                continue
            if isinstance(t, ast.Subscript):
                t = t.value
            name = self._em115_terminal(t)
            if name in _EM115_POOLS:
                return name, "assignment"
        return None

    # -- EM110 -------------------------------------------------------------

    def _rule_serve_row_dispatch(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM110_DIRS):
            return
        jitted: set[str] = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.ImportFrom)
                and node.module
                and node.module.startswith("edgemesh.")
            ):
                for a in node.names:
                    if (
                        a.name.startswith(_EM110_IMPORT_PREFIXES)
                        or a.name in _EM110_IMPORT_EXTRA
                    ):
                        jitted.add(a.asname or a.name)
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                # name = jax.jit(f) / partial(jax.jit, ...)(f)
                if _is_jit_expr(node.value.func, self.aliases):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted.add(t.id)
        for fn in self._all_defs:
            if fn in self.jit_decorated:
                jitted.add(fn.name)
        if not jitted:
            return
        loop_types = (
            ast.For, ast.While, ast.ListComp, ast.SetComp, ast.GeneratorExp,
            ast.DictComp,
        )
        for loop in ast.walk(tree):
            if not isinstance(loop, loop_types):
                continue
            # Loops inside traced code unroll — that is EM105's beat, not a
            # host-side dispatch-per-row problem.
            if any(
                d in self.traced
                and d.lineno <= loop.lineno <= getattr(d, "end_lineno", d.lineno)
                for d in self._all_defs
            ):
                continue
            for sub in ast.walk(loop):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id in jitted
                ):
                    self._emit(
                        "EM110", sub,
                        f"jitted forward {sub.func.id!r} dispatched per loop "
                        "iteration in serve/ — per-row dispatch is the wave "
                        "structure the ragged boundary launch removed; batch "
                        "the rows into ONE forward_ragged_paged launch (or "
                        "suppress for a deliberate ablation path)",
                    )

    # -- EM111 -------------------------------------------------------------

    def _rule_metric_naming(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM111_DIRS):
            return
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EM111_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            kind = node.func.attr
            name = node.args[0].value
            if not name.startswith(_EM111_PREFIX):
                self._emit(
                    "EM111", node,
                    f"{kind} {name!r} registered without the "
                    f"{_EM111_PREFIX!r} namespace prefix — every edgemesh "
                    "metric shares one namespace so dashboards and scrape "
                    "relabeling can select the whole family",
                )
            if kind == "counter" and not name.endswith("_total"):
                self._emit(
                    "EM111", node,
                    f"counter {name!r} must end '_total' (the Prometheus "
                    "convention for monotone totals; rate() tooling keys "
                    "on it)",
                )
            elif kind != "counter" and name.endswith("_total"):
                self._emit(
                    "EM111", node,
                    f"{kind} {name!r} must not end '_total' — that suffix "
                    "is reserved for counters, and a non-monotone series "
                    "named like one breaks every rate() query over it",
                )

    # -- EM112 -------------------------------------------------------------

    def _em112_value_ok(self, value: ast.AST, call_line: int,
                        _seen: frozenset = frozenset()) -> bool:
        """True when a label value visibly flows through bounded_label (or
        is a constant / a trusted pre-normalized name). Mirrors the wire
        pass's (EM502) provenance style: one function-local assignment chain is followed;
        anything the linter cannot see into is trusted, anything it CAN
        see as raw (subscripts, non-normalizer calls) flags."""
        if isinstance(value, ast.Constant):
            return isinstance(value.value, str)
        if isinstance(value, ast.Call):
            fd = _dotted_name(value.func)
            return bool(fd and fd.rsplit(".", 1)[-1] == _EM112_NORMALIZER)
        if isinstance(value, ast.Subscript):
            return False  # rec["tenant"] / headers[...] — visibly raw
        if isinstance(value, ast.Name):
            if value.id in _seen:
                return True  # self-assignment cycle: nothing more to learn
            scopes = self._scope_stack_for_line(call_line)
            fn = scopes[-1] if scopes else None
            if fn is None:
                return True  # module level: out of provenance scope
            rhs, rhs_line = None, -1
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Assign)
                    and rhs_line < sub.lineno < call_line
                    and any(
                        isinstance(t, ast.Name) and t.id == value.id
                        for t in sub.targets
                    )
                ):
                    # Latest SOURCE LINE before the call wins — ast.walk is
                    # breadth-first, so walk order would pick a top-level
                    # assignment over a later nested one.
                    rhs, rhs_line = sub.value, sub.lineno
            if rhs is None:
                # A parameter or outer binding: normalized at the seam
                # where the raw value entered (the pattern the rule
                # pushes callers toward).
                return True
            return self._em112_value_ok(rhs, call_line,
                                        _seen | {value.id})
        # Attributes and anything else opaque: provenance invisible.
        return True

    def _rule_unbounded_label(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM112_DIRS):
            return
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "labels"
            ):
                continue
            for kw in node.keywords:
                if kw.arg not in _EM112_LABELS:
                    continue
                if self._em112_value_ok(kw.value, node.lineno):
                    continue
                self._emit(
                    "EM112", node,
                    f"label {kw.arg!r} bound to a raw request-derived "
                    "value — unbounded label cardinality lets one client "
                    "mint time series without limit; route it through "
                    "obs.metrics.bounded_label(...) (allowlist + 'other' "
                    "overflow bucket)",
                )

    # -- EM113 -------------------------------------------------------------

    def _em113_span_shaped(self, d: ast.Dict) -> bool:
        """A dict literal carrying the span vocabulary: a ``"spans"`` key,
        or an ``"event"`` key whose value is a span-record event — as a
        string constant, or as a name/attribute ending in one of the
        shared event constants (``SPAN_RECORD_EVENT`` etc.)."""
        for key, value in zip(d.keys, d.values):
            if not isinstance(key, ast.Constant):
                continue
            if key.value == "spans":
                return True
            if key.value != "event":
                continue
            if isinstance(value, ast.Constant) and value.value in _EM113_EVENTS:
                return True
            if isinstance(value, (ast.Name, ast.Attribute)):
                dotted = _dotted_name(value)
                if dotted and dotted.rsplit(".", 1)[-1] in _EM113_EVENT_CONSTS:
                    return True
        return False

    def _em113_dict_for_arg(self, arg: ast.AST, call_line: int) -> ast.Dict | None:
        """The dict literal behind a ``json.dumps`` argument, following one
        level of simple local assignment (the wire pass's provenance style)."""
        if isinstance(arg, ast.Dict):
            return arg
        if isinstance(arg, ast.Name):
            scopes = self._scope_stack_for_line(call_line)
            fn = scopes[-1] if scopes else None
            if fn is None:
                return None
            best = None
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Assign)
                    and sub.lineno < call_line
                    and isinstance(sub.value, ast.Dict)
                    and any(isinstance(t, ast.Name) and t.id == arg.id
                            for t in sub.targets)
                ):
                    best = sub.value  # last assignment before the call wins
            return best
        return None

    @staticmethod
    def _em113_fn_writes(fn: ast.AST) -> bool:
        """True when the function also touches a file: an ``open(...)``
        call or a ``.write(...)`` method call — serializing a span-shaped
        record is only a bypass once it heads for disk."""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                return True
            if isinstance(node.func, ast.Attribute) and node.func.attr == "write":
                return True
        return False

    def _rule_span_schema_bypass(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM113_DIRS):
            return
        if any(self.relpath.endswith(sfx) for sfx in _EM113_ALLOWED_SUFFIXES):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            dotted = _dotted_name(node.func)
            if not dotted or self.aliases.resolve(dotted) != "json.dumps":
                continue
            d = self._em113_dict_for_arg(node.args[0], node.lineno)
            if d is None or not self._em113_span_shaped(d):
                continue
            scopes = self._scope_stack_for_line(node.lineno)
            fn = scopes[-1] if scopes else None
            if fn is None or not self._em113_fn_writes(fn):
                continue
            self._emit(
                "EM113", node,
                "span-event record serialized with json.dumps and written "
                "outside the sanctioned producers — replay/assembly "
                "correctness depends on ONE record vocabulary; flush "
                "through SpanTracker, FlightRecorder, or "
                "utils.tracing.JsonlLogger instead",
            )

    # -- EM102 -------------------------------------------------------------

    def _rule_host_sync(self, fn: ast.AST) -> None:
        for node in self._walk_own_and_nested_traced(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _HOST_SYNC_METHODS and not node.args:
                self._emit(
                    "EM102", node,
                    f".{f.attr}() inside traced code forces a device→host "
                    "readback per call (hoist it out of the jitted path)",
                )
                continue
            dotted = _dotted_name(f)
            if dotted:
                resolved = self.aliases.resolve(dotted)
                if resolved in {f"numpy.{n}" for n in _HOST_SYNC_NP_FUNCS}:
                    self._emit(
                        "EM102", node,
                        f"{dotted}(...) inside traced code materializes on "
                        "host (use jnp, or move outside jit)",
                    )
                    continue
            if isinstance(f, ast.Name) and f.id == "float" and node.args:
                arg = node.args[0]
                if not isinstance(arg, ast.Constant):
                    self._emit(
                        "EM102", node,
                        "float(...) on a traced value is a concretization "
                        "error under jit (use .astype / keep it on device)",
                    )

    # -- EM103 -------------------------------------------------------------

    def _rule_unsynced_timing(self, fn: ast.AST) -> None:
        clock_lines: list[int] = []
        has_fence = False
        device_lines: list[int] = []
        # Own statements only: every def gets its own EM103 run, so a window
        # inside a nested helper is attributed to THAT def once, not also to
        # every enclosing def.
        for node in _walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_name(node.func)
            resolved = self.aliases.resolve(dotted) if dotted else None
            if resolved in _CLOCK_FUNCS:
                clock_lines.append(node.lineno)
            elif isinstance(node.func, ast.Attribute) and node.func.attr in _FENCE_METHODS:
                has_fence = True
            elif (
                isinstance(node.func, ast.Name)
                and (dotted or node.func.id).rsplit(".", 1)[-1] in _FENCE_FUNCS
            ):
                has_fence = True
            elif resolved and resolved.split(".")[0] in ("numpy",) and (
                resolved.rsplit(".", 1)[-1] in _HOST_SYNC_NP_FUNCS
            ):
                has_fence = True  # np.asarray IS a readback fence
            elif dotted and (
                resolved.startswith("jax.numpy.") or resolved.startswith("jax.lax.")
                or resolved == "jax.jit" or resolved.startswith("jax.random.")
            ):
                device_lines.append(node.lineno)
        if len(clock_lines) < 2 or has_fence:
            return
        lo, hi = min(clock_lines), max(clock_lines)
        inside = [ln for ln in device_lines if lo <= ln <= hi]
        if inside:
            self._emit(
                "EM103",
                ast.copy_location(ast.Pass(), fn),
                "wall-clock window (lines "
                f"{lo}-{hi}) around device dispatch at line {inside[0]} has no "
                "completion fence (block_until_ready/device_sync) — async "
                "dispatch makes the measured time meaningless",
            )

    # -- EM104 -------------------------------------------------------------

    def _rule_dead_param(self, fn) -> None:
        args = fn.args
        names = [
            a.arg
            for a in (*args.posonlyargs, *args.args, *args.kwonlyargs)
            if a.arg not in ("self", "cls") and not a.arg.startswith("_")
        ]
        if args.vararg and not args.vararg.arg.startswith("_"):
            names.append(args.vararg.arg)
        if args.kwarg and not args.kwarg.arg.startswith("_"):
            names.append(args.kwarg.arg)
        used: set[str] = set()
        for stmt in fn.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name):
                    used.add(node.id)
        for name in names:
            if name not in used:
                self._emit(
                    "EM104", fn,
                    f"parameter {name!r} of jitted function {fn.name!r} is "
                    "never used — callers pay transfer/donation and retraces "
                    "keyed on a value that cannot affect the result "
                    "(implement it or remove it)",
                )

    # -- EM105 -------------------------------------------------------------

    def _rule_loop_unroll(self, fn: ast.AST) -> None:
        for node in self._walk_own_and_nested_traced(fn):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            # Small constant-range unrolls are idiomatic (head groups etc.).
            if isinstance(node, ast.For) and self._small_constant_range(node.iter):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted_name(sub.func)
                resolved = self.aliases.resolve(dotted) if dotted else ""
                if resolved.startswith("jax.numpy.") or resolved.startswith("jax.lax."):
                    self._emit(
                        "EM105", node,
                        "Python loop over jnp/lax work inside traced code "
                        "unrolls into the XLA graph (compile time scales "
                        "with trip count) — use lax.scan/fori_loop, or "
                        "suppress for a small fixed unroll",
                    )
                    break

    @staticmethod
    def _small_constant_range(it: ast.AST, limit: int = 8) -> bool:
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "range"
            and len(it.args) == 1
            and isinstance(it.args[0], ast.Constant)
            and isinstance(it.args[0].value, int)
        ):
            return it.args[0].value <= limit
        return False

    # -- EM106 -------------------------------------------------------------

    def _rule_print(self, fn: ast.AST) -> None:
        for node in self._walk_own_and_nested_traced(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                self._emit(
                    "EM106", node,
                    "print() inside traced code runs at trace time only "
                    "(f-string payloads render Traced<...> reprs) — use "
                    "jax.debug.print for runtime values",
                )

    # -- helpers -----------------------------------------------------------

    def _walk_own_and_nested_traced(self, fn: ast.AST):
        """Walk fn's body, descending into nested defs only when they are
        themselves traced (a non-traced local helper is host code)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node not in self.traced:
                    continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


def lint_file(path: str | Path) -> list[Finding]:
    src = Path(path).read_text(encoding="utf-8", errors="replace")
    return lint_source(src, str(path))


def lint_source(source: str, path: str = "<memory>") -> list[Finding]:
    """Lint a source string (the fixture-test entry point): the per-function
    AST rules (EM1xx), the class-level concurrency pass (EM3xx), and the
    sharding/collective pass (EM401-EM404), and the wire protocol-contract
    pass (EM501-EM505)."""
    # Lazy imports: the sibling passes are not dependencies of the EM1xx
    # machinery, and importing them at module top would be a cycle (both
    # reuse linter internals).
    from edgemesh.analysis.concurrency import analyze_source
    from edgemesh.analysis.sharding import analyze_source as analyze_sharding
    from edgemesh.analysis.wire import analyze_source as analyze_wire

    findings = _FileLinter(path, source).run()
    findings.extend(analyze_source(source, path))
    findings.extend(analyze_sharding(source, path))
    findings.extend(analyze_wire(source, path))
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def iter_python_files(paths) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(
                f for f in sorted(p.rglob("*.py")) if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return out


def lint_paths(paths) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(lint_file(f))
    return findings
