"""CLI for the static-analysis pass: ``python -m edgemesh.analysis [paths]``.

Also reachable as ``edgemesh lint [paths]`` (edgemesh/cli.py). Exit status is
the CI contract: 0 when every finding is baselined (or none exist), 1 when
any non-baselined finding remains, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from edgemesh.analysis.edgelint import iter_python_files, lint_paths
from edgemesh.analysis.findings import (
    Baseline,
    Finding,
    default_baseline_path,
    repo_relative,
)


def _default_target() -> list[str]:
    # The package directory itself: works from any cwd.
    return [str(Path(__file__).resolve().parent.parent)]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m edgemesh.analysis",
        description="edgelint (AST rules) + abstract eval_shape contracts + "
        "AbstractMesh sharding dryrun + wire protocol-contract dryrun",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to lint (default: the edgemesh package)",
    )
    p.add_argument(
        "--format", choices=["pretty", "json", "github"], default="pretty",
        help="pretty = one line per finding; json = machine-readable report; "
        "github = GitHub Actions ::error/::warning annotations",
    )
    p.add_argument(
        "--no-contracts", action="store_true",
        help="skip the semantic passes that import jax (the EM2xx eval_shape "
        "contracts AND the EM405 AbstractMesh sharding dryrun); pure AST lint. "
        "The stdlib-only wire dryrun (EM506) still runs",
    )
    p.add_argument(
        "--severity", choices=["error", "warning"], default="warning",
        help="minimum severity to report (default: warning = everything)",
    )
    p.add_argument(
        "--select", default=None, metavar="RULES",
        help="only report these rules — comma-separated, prefix-aware: "
        "'EM4xx' selects every EM4 rule, 'EM301' exactly one "
        "(e.g. --select EM4xx,EM301)",
    )
    p.add_argument(
        "--ignore", default=None, metavar="RULES",
        help="drop these rules from the report (same syntax as --select; "
        "applied after it)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {default_baseline_path().name} next to "
        "the analysis package)",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather all current findings into the baseline and exit 0 "
        "(review the diff before committing!)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding (audit mode)",
    )
    p.add_argument(
        "--prune-baseline", action="store_true",
        help="drop stale baseline entries (file or finding no longer exists) "
        "and rewrite the baseline file",
    )
    return p


#: Retired rule ids kept as spellable aliases: scripts that pinned the old
#: ad-hoc fleet HTTP rules keep working, with a nudge toward the successor.
_RETIRED_ALIASES = {"EM108": "EM502", "EM109": "EM502"}


def _parse_rule_patterns(arg: str | None) -> list[str] | None:
    """Comma-separated rule patterns: exact IDs ('EM301') and prefix
    wildcards spelled with trailing x's ('EM4xx' → every EM4 rule).
    Retired ids (EM108/EM109) translate to their successor with a
    deprecation note on stderr."""
    if arg is None:
        return None
    patterns = []
    for p in arg.split(","):
        p = p.strip().upper()
        if not p:
            continue
        if p in _RETIRED_ALIASES:
            successor = _RETIRED_ALIASES[p]
            print(
                f"note: {p} was retired into the wire contract pass; "
                f"selecting {successor} (see docs/ANALYSIS.md)",
                file=sys.stderr,
            )
            p = successor
        patterns.append(p)
    return patterns or None


def _rule_matches(rule: str, patterns: list[str]) -> bool:
    r = rule.upper()
    for p in patterns:
        if p.endswith("X"):
            if r.startswith(p.rstrip("X")):
                return True
        elif r == p:
            return True
    return False


def _rule_selected(rule: str, select: list[str] | None,
                   ignore: list[str] | None) -> bool:
    if select is not None and not _rule_matches(rule, select):
        return False
    if ignore is not None and _rule_matches(rule, ignore):
        return False
    return True


def _stale_entries(baseline: Baseline, findings: list[Finding],
                   paths: list[str],
                   skipped_rule_prefixes: tuple[str, ...] = (),
                   select: list[str] | None = None,
                   ignore: list[str] | None = None) -> list[dict]:
    """Baseline entries that no longer match anything.

    An entry is stale when (a) its file no longer exists at all, or (b) its
    file WAS linted in this run and no current finding carries its
    fingerprint. Entries for files outside the linted path set (and still
    on disk) are left alone — a single-file lint must not condemn the rest
    of the baseline — and so are entries from a pass that did not run this
    invocation (``--no-contracts`` skips EM2xx/EM405, so an absent
    fingerprint from those proves nothing) or a rule filtered out by
    ``--select``/``--ignore`` (a filtered run cannot judge the rules it
    never reported). Staleness matters beyond hygiene: a dead entry would
    silently mask a FUTURE finding that lands on the same fingerprint
    (same rule, scope, and line text — e.g. the regressed code pasted back
    in).
    """
    current = {f.fingerprint() for f in findings}
    linted = {repo_relative(p) for p in iter_python_files(paths)}
    repo_root = Path(__file__).resolve().parent.parent.parent
    stale = []
    for entry in baseline.entries:
        path = entry.get("path", "")
        exists = (repo_root / path).exists() or Path(path).exists()
        if not exists:
            stale.append({**entry, "reason": "file no longer exists"})
            continue
        rule = entry.get("rule", "")
        if any(rule.startswith(p) for p in skipped_rule_prefixes):
            continue  # that pass didn't run; its findings can't be judged
        if not _rule_selected(rule, select, ignore):
            continue  # rule filtered out this run; can't be judged either
        if path in linted and entry["fingerprint"] not in current:
            stale.append({**entry, "reason": "finding no longer present"})
    return stale


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.prune_baseline and args.no_baseline:
        # --no-baseline empties the in-memory baseline; pruning "against" it
        # would rewrite the file to nothing and destroy every entry.
        print(
            "error: --prune-baseline operates on the baseline; drop "
            "--no-baseline", file=sys.stderr,
        )
        return 2
    paths = args.paths or _default_target()
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        # A typo'd path must NOT report "clean"/exit 0 — that is a lint gate
        # that permanently checks zero files.
        print(
            f"error: no such path: {', '.join(missing)}", file=sys.stderr
        )
        return 2

    select = _parse_rule_patterns(args.select)
    ignore = _parse_rule_patterns(args.ignore)

    findings: list[Finding] = lint_paths(paths)
    # The wire dryrun (EM506) is stdlib-only — no jax import to skip — so
    # it runs unconditionally: the route tables must never drift out from
    # under a --no-contracts gate.
    from edgemesh.analysis.wire import run_wire_contracts

    findings.extend(run_wire_contracts())
    if not args.no_contracts:
        from edgemesh.analysis.contracts import run_contracts
        from edgemesh.analysis.sharding import run_sharding_contracts

        findings.extend(run_contracts())
        findings.extend(run_sharding_contracts())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    findings = [f for f in findings if _rule_selected(f.rule, select, ignore)]
    # Staleness is judged against EVERY finding (before the severity filter
    # drops warnings): a baselined warning is not stale just because the
    # operator asked to see errors only.
    all_findings = list(findings)
    if args.severity == "error":
        findings = [f for f in findings if f.severity == "error"]

    baseline_path = Path(args.baseline) if args.baseline else default_baseline_path()
    if args.write_baseline:
        new_baseline = Baseline.from_findings(findings)
        if select is not None or ignore is not None:
            # A filtered run only saw the selected rules: rewrite THEIR
            # entries and keep everything else — a full overwrite here
            # would silently destroy every other rule's grandfathered debt.
            kept = [
                e for e in Baseline.load(baseline_path).entries
                if not _rule_selected(e.get("rule", ""), select, ignore)
            ]
            seen: set[str] = set()
            entries = []
            for e in sorted(
                kept + new_baseline.entries,
                key=lambda e: (e.get("path", ""), e.get("rule", ""),
                               e["fingerprint"]),
            ):
                if e["fingerprint"] not in seen:
                    seen.add(e["fingerprint"])
                    entries.append(e)
            new_baseline = Baseline({e["fingerprint"] for e in entries}, entries)
        new_baseline.save(baseline_path)
        print(
            f"wrote {len(new_baseline.entries)} grandfathered finding(s) to "
            f"{baseline_path}"
        )
        return 0

    baseline = Baseline() if args.no_baseline else Baseline.load(baseline_path)
    stale = [] if args.no_baseline else _stale_entries(
        baseline, all_findings, paths,
        skipped_rule_prefixes=("EM2", "EM405") if args.no_contracts else (),
        select=select, ignore=ignore,
    )
    if args.prune_baseline:
        stale_fps = {e["fingerprint"] for e in stale}
        keep = [e for e in baseline.entries if e["fingerprint"] not in stale_fps]
        Baseline({e["fingerprint"] for e in keep}, keep).save(baseline_path)
        print(
            f"pruned {len(stale)} stale entr{'y' if len(stale) == 1 else 'ies'} "
            f"from {baseline_path} ({len(keep)} kept)"
        )
        return 0
    for entry in stale:
        print(
            f"warning: stale baseline entry {entry['fingerprint']} "
            f"({entry.get('rule')} {entry.get('path')}): {entry['reason']} — "
            "it would mask a future finding at this fingerprint; run "
            "--prune-baseline",
            file=sys.stderr,
        )
    fresh = baseline.filter(findings)
    suppressed = len(findings) - len(fresh)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in fresh],
            "baselined": suppressed,
            "stale_baseline": stale,
            "checked_paths": [str(p) for p in paths],
        }, indent=2))
    elif args.format == "github":
        # GitHub Actions workflow-command annotations: findings land
        # inline on the PR diff. Newlines must be %0A-escaped per the
        # workflow-command spec.
        for f in fresh:
            kind = "error" if f.severity == "error" else "warning"
            title = f"{f.rule} {f.severity}"
            msg = f.message.replace("%", "%25").replace("\n", "%0A")
            print(
                f"::{kind} file={f.path},line={f.line},title={title}::{msg}"
            )
    else:
        for f in fresh:
            print(f.render())
        counts: dict[str, int] = {}
        for f in fresh:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        tail = ", ".join(f"{n} {sev}(s)" for sev, n in sorted(counts.items())) or "clean"
        extra = f" ({suppressed} baselined)" if suppressed else ""
        print(f"edgemesh.analysis: {tail}{extra}")

    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
