"""CLI for the static-analysis pass: ``python -m edgemesh.analysis [paths]``.

Also reachable as ``edgemesh lint [paths]`` (edgemesh/cli.py). Exit status is
the CI contract: 0 when every finding is baselined (or none exist), 1 when
any non-baselined finding remains, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from edgemesh.analysis.edgelint import lint_paths
from edgemesh.analysis.findings import Baseline, Finding, default_baseline_path


def _default_target() -> list[str]:
    # The package directory itself: works from any cwd.
    return [str(Path(__file__).resolve().parent.parent)]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m edgemesh.analysis",
        description="edgelint (AST rules) + abstract eval_shape contract pass",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to lint (default: the edgemesh package)",
    )
    p.add_argument(
        "--format", choices=["pretty", "json"], default="pretty",
        help="pretty = one line per finding; json = machine-readable report",
    )
    p.add_argument(
        "--no-contracts", action="store_true",
        help="skip the eval_shape contract pass (pure AST lint; no jax import)",
    )
    p.add_argument(
        "--severity", choices=["error", "warning"], default="warning",
        help="minimum severity to report (default: warning = everything)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {default_baseline_path().name} next to "
        "the analysis package)",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather all current findings into the baseline and exit 0 "
        "(review the diff before committing!)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding (audit mode)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    paths = args.paths or _default_target()
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        # A typo'd path must NOT report "clean"/exit 0 — that is a lint gate
        # that permanently checks zero files.
        print(
            f"error: no such path: {', '.join(missing)}", file=sys.stderr
        )
        return 2

    findings: list[Finding] = lint_paths(paths)
    if not args.no_contracts:
        from edgemesh.analysis.contracts import run_contracts

        findings.extend(run_contracts())
    if args.severity == "error":
        findings = [f for f in findings if f.severity == "error"]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    baseline_path = Path(args.baseline) if args.baseline else default_baseline_path()
    if args.write_baseline:
        Baseline.from_findings(findings).save(baseline_path)
        print(f"wrote {len(findings)} grandfathered finding(s) to {baseline_path}")
        return 0

    baseline = Baseline() if args.no_baseline else Baseline.load(baseline_path)
    fresh = baseline.filter(findings)
    suppressed = len(findings) - len(fresh)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in fresh],
            "baselined": suppressed,
            "checked_paths": [str(p) for p in paths],
        }, indent=2))
    else:
        for f in fresh:
            print(f.render())
        counts: dict[str, int] = {}
        for f in fresh:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        tail = ", ".join(f"{n} {sev}(s)" for sev, n in sorted(counts.items())) or "clean"
        extra = f" ({suppressed} baselined)" if suppressed else ""
        print(f"edgemesh.analysis: {tail}{extra}")

    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
