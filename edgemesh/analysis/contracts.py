"""Abstract contract pass: drive public entry points through ``jax.eval_shape``.

edgelint (the AST pass) catches what it can see in source; this pass catches
what only tracing reveals, WITHOUT executing anything on a device:

- **EM201 contract-trace-failure** (error): a registered entry point no longer
  traces on its documented abstract signature — the static analog of the
  seed's seven ring-attention failures (an API drift or shape contract break
  shows up here before any test runs a device program).
- **EM202 cache-instability** (error): a decode-step entry returns a KV cache
  whose avals (shape/dtype tree) differ from its input cache. A decode loop
  carries the cache; any aval drift either fails ``lax.while_loop`` outright
  or — worse — silently retraces and recompiles the multi-second decode
  program every step.
- **EM203 dtype-promotion** (error): an entry point's outputs contain float64
  / weakly-typed leaves. 64-bit leaves mean an accidental x64 promotion
  (2x memory + a recompile when the flag flips); weak types make output
  avals depend on how callers combine them — the classic cache-key
  instability hazard.
- **EM204 unwired-check-contract** (error): a kernel exposing ``check=True``
  whose body does not call its registered ``ops/checks.py`` contract (the
  contract exists but the kernel silently skips it — checks rot).
- **EM205 contract-not-firing** (error): a registered checkify contract that
  does NOT raise on its known-bad input (or raises on its known-good one) —
  proves every contract is actually exercised, not just imported.

Everything here runs abstractly (``jax.eval_shape``) except EM205, which
executes the tiny checkify predicates (a handful of reductions over <1 KB
arrays) — the whole pass is sub-second on CPU.
"""

from __future__ import annotations

import inspect
from functools import partial

from edgemesh.analysis.findings import Finding

CONTRACT_RULES: dict[str, dict] = {
    "EM201": {
        "name": "contract-trace-failure",
        "severity": "error",
        "summary": "public entry point fails to trace on its abstract signature",
    },
    "EM202": {
        "name": "cache-instability",
        "severity": "error",
        "summary": "decode entry returns cache avals != input cache avals (recompile hazard)",
    },
    "EM203": {
        "name": "dtype-promotion",
        "severity": "error",
        "summary": "float64 / weak-type leaves in entry-point outputs",
    },
    "EM204": {
        "name": "unwired-check-contract",
        "severity": "error",
        "summary": "kernel exposes check=True but never calls its ops/checks.py contract",
    },
    "EM205": {
        "name": "contract-not-firing",
        "severity": "error",
        "summary": "registered checkify contract does not fire on known-bad inputs",
    },
}


def _avals(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: (tuple(a.shape), str(a.dtype)), tree
    )


def _promotion_problems(tree) -> list[str]:
    import jax

    problems: list[str] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        dt = str(leaf.dtype)
        if dt in ("float64", "int64", "complex128"):
            problems.append(f"leaf {jax.tree_util.keystr(path)} is {dt}")
        if getattr(leaf, "weak_type", False):
            problems.append(f"leaf {jax.tree_util.keystr(path)} is weakly typed")
    return problems


# ---------------------------------------------------------------------------
# Entry-point registry
# ---------------------------------------------------------------------------
#
# Each entry is (name, source-path, runner). The runner builds tiny abstract
# arguments, eval_shapes the entry point, and returns a list of
# (rule, message) problems; raising is reported as EM201.


def _tiny():
    from edgemesh.models.families import tiny_config

    return tiny_config("llama")


def _abstract_model(cfg, batch=2, max_seq=32):
    import jax

    from edgemesh.models.transformer import init_kv_cache, init_params

    params = jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))
    cache = jax.eval_shape(lambda: init_kv_cache(cfg, batch, max_seq))
    return params, cache


def _check_prefill():
    import jax
    import jax.numpy as jnp

    from edgemesh.models.transformer import forward_prefill

    cfg = _tiny()
    params, cache = _abstract_model(cfg)
    tokens = jax.ShapeDtypeStruct((2, 8), jnp.int32)
    lengths = jax.ShapeDtypeStruct((2,), jnp.int32)
    logits, out_cache = jax.eval_shape(
        partial(forward_prefill, cfg), params, tokens, lengths, cache
    )
    problems = [("EM203", p) for p in _promotion_problems((logits, out_cache))]
    if logits.shape != (2, cfg.vocab_size):
        problems.append(
            ("EM201", f"prefill logits shape {logits.shape} != (batch, vocab)")
        )
    if _avals(out_cache) != _avals(cache):
        problems.append(
            ("EM202", "prefill returned cache avals differ from the input cache")
        )
    return problems


def _check_decode():
    import jax
    import jax.numpy as jnp

    from edgemesh.models.transformer import forward_decode

    cfg = _tiny()
    params, cache = _abstract_model(cfg)
    tokens = jax.ShapeDtypeStruct((2,), jnp.int32)
    logits, out_cache = jax.eval_shape(
        partial(forward_decode, cfg), params, tokens, cache
    )
    problems = [("EM203", p) for p in _promotion_problems((logits, out_cache))]
    if _avals(out_cache) != _avals(cache):
        problems.append(
            ("EM202",
             "decode returned cache avals differ from the input cache — a "
             "decode while_loop would retrace/recompile per step")
        )
    return problems


def _check_verify():
    import jax
    import jax.numpy as jnp

    from edgemesh.models.transformer import forward_verify

    cfg = _tiny()
    params, cache = _abstract_model(cfg)
    tokens = jax.ShapeDtypeStruct((2, 4), jnp.int32)
    logits, out_cache = jax.eval_shape(
        partial(forward_verify, cfg), params, tokens, cache
    )
    problems = [("EM203", p) for p in _promotion_problems((logits, out_cache))]
    if _avals(out_cache) != _avals(cache):
        problems.append(("EM202", "verify returned cache avals differ from input"))
    return problems


def _check_decode_loop():
    import jax
    import jax.numpy as jnp

    from edgemesh.config import SamplingParams
    from edgemesh.runtime.generate import _decode_loop

    cfg = _tiny()
    params, cache = _abstract_model(cfg)
    first_logits = jax.ShapeDtypeStruct((2, cfg.vocab_size), jnp.float32)
    token_mask = jax.ShapeDtypeStruct((2, cfg.vocab_size), jnp.bool_)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    out = jax.eval_shape(
        partial(_decode_loop, cfg, sampling=SamplingParams(), max_new=4, eos_id=1),
        params, first_logits=first_logits, cache=cache,
        token_mask=token_mask, rng=rng,
    )
    tokens, num_generated, out_cache = out[0], out[1], out[2]
    problems = [("EM203", p) for p in _promotion_problems(out)]
    if tokens.shape != (2, 4) or str(tokens.dtype) != "int32":
        problems.append(
            ("EM201", f"decode loop token buffer {tokens.shape}/{tokens.dtype} "
             "!= ([b, max_new], int32)")
        )
    if str(num_generated.dtype) != "int32":
        problems.append(("EM201", "num_generated must stay int32"))
    if _avals(out_cache) != _avals(cache):
        problems.append(
            ("EM202", "decode loop returned cache avals differ from input — "
             "generate_stream resubmits this cache next segment")
        )
    return problems


def _check_sample_token():
    import jax
    import jax.numpy as jnp

    from edgemesh.config import SamplingParams
    from edgemesh.ops.sampling import sample_token

    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    logits = jax.ShapeDtypeStruct((2, 64), jnp.float32)
    mask = jax.ShapeDtypeStruct((2, 64), jnp.bool_)
    tok = jax.eval_shape(
        partial(sample_token, params=SamplingParams()), rng, logits, token_mask=mask
    )
    problems = [("EM203", p) for p in _promotion_problems(tok)]
    if tok.shape != (2,):
        problems.append(("EM201", f"sample_token shape {tok.shape} != (batch,)"))
    return problems


def _check_attend():
    import jax
    import jax.numpy as jnp

    from edgemesh.ops.attention import LayerKV, attend

    q = jax.ShapeDtypeStruct((1, 4, 4, 8), jnp.float32)
    k = jax.ShapeDtypeStruct((1, 8, 2, 8), jnp.float32)
    q_pos = jax.ShapeDtypeStruct((1, 4), jnp.int32)
    kv_valid = jax.ShapeDtypeStruct((1, 8), jnp.bool_)
    out = jax.eval_shape(attend, q, LayerKV(k, k), q_pos, kv_valid)
    problems = [("EM203", p) for p in _promotion_problems(out)]
    if out.shape != (1, 4, 4, 8):
        problems.append(("EM201", f"attend output shape {out.shape} != q shape"))
    return problems


def _check_flash_attention():
    import jax
    import jax.numpy as jnp

    from edgemesh.ops.flash_attention import HAVE_PALLAS, flash_attention

    if not HAVE_PALLAS:
        return []
    q = jax.ShapeDtypeStruct((1, 4, 2, 8), jnp.float32)
    k = jax.ShapeDtypeStruct((1, 8, 1, 8), jnp.float32)
    kv_lens = jax.ShapeDtypeStruct((1,), jnp.int32)
    out = jax.eval_shape(partial(flash_attention, causal=True), q, k, k, kv_lens)
    problems = [("EM203", p) for p in _promotion_problems(out)]
    if out.shape != (1, 4, 2, 8) or str(out.dtype) != "float32":
        problems.append(
            ("EM201", "flash_attention output must match q's shape/dtype "
             f"(got {out.shape}/{out.dtype})")
        )
    return problems


def _check_paged_attention():
    import jax
    import jax.numpy as jnp

    from edgemesh.ops.paged_attention import paged_decode_attention

    try:
        from edgemesh.ops.paged_attention import HAVE_PALLAS
    except ImportError:  # pragma: no cover
        HAVE_PALLAS = True
    if not HAVE_PALLAS:
        return []
    q = jax.ShapeDtypeStruct((2, 2, 8), jnp.float32)
    pages = jax.ShapeDtypeStruct((4, 1, 8, 8), jnp.float32)
    table = jax.ShapeDtypeStruct((2, 2), jnp.int32)
    kv_lens = jax.ShapeDtypeStruct((2,), jnp.int32)
    out = jax.eval_shape(paged_decode_attention, q, pages, pages, table, kv_lens)
    problems = [("EM203", p) for p in _promotion_problems(out)]
    if out.shape != (2, 2, 8):
        problems.append(
            ("EM201", f"paged_decode_attention output {out.shape} != q shape")
        )
    return problems


def _check_int8_matmul():
    import jax
    import jax.numpy as jnp

    from edgemesh.ops.int8 import int8_matmul_fused

    x = jax.ShapeDtypeStruct((2, 8), jnp.float32)
    w_q = jax.ShapeDtypeStruct((8, 4), jnp.int8)
    scales = jax.ShapeDtypeStruct((4,), jnp.float32)
    out = jax.eval_shape(int8_matmul_fused, x, w_q, scales)
    problems = [("EM203", p) for p in _promotion_problems(out)]
    if out.shape != (2, 4):
        problems.append(("EM201", f"int8_matmul_fused output {out.shape} != [M, N]"))
    return problems


def _check_ring_attention():
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from edgemesh.parallel.ring_attention import ring_attention

    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    q = jax.ShapeDtypeStruct((1, 8, 2, 8), jnp.float32)
    k = jax.ShapeDtypeStruct((1, 8, 1, 8), jnp.float32)
    pos = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    valid = jax.ShapeDtypeStruct((1, 8), jnp.bool_)
    out = jax.eval_shape(partial(ring_attention, mesh=mesh), q, k, k, pos, valid)
    problems = [("EM203", p) for p in _promotion_problems(out)]
    if out.shape != (1, 8, 2, 8):
        problems.append(("EM201", f"ring_attention output {out.shape} != q shape"))
    return problems


def _check_ulysses_attention():
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from edgemesh.parallel.ulysses import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    q = jax.ShapeDtypeStruct((1, 8, 2, 8), jnp.float32)
    k = jax.ShapeDtypeStruct((1, 8, 2, 8), jnp.float32)
    pos = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    valid = jax.ShapeDtypeStruct((1, 8), jnp.bool_)
    out = jax.eval_shape(partial(ulysses_attention, mesh=mesh), q, k, k, pos, valid)
    problems = [("EM203", p) for p in _promotion_problems(out)]
    if out.shape != (1, 8, 2, 8):
        problems.append(("EM201", f"ulysses_attention output {out.shape} != q shape"))
    return problems


ENTRY_POINTS: list[tuple[str, str, callable]] = [
    ("transformer.forward_prefill", "edgemesh/models/transformer.py", _check_prefill),
    ("transformer.forward_decode", "edgemesh/models/transformer.py", _check_decode),
    ("transformer.forward_verify", "edgemesh/models/transformer.py", _check_verify),
    ("generate._decode_loop", "edgemesh/runtime/generate.py", _check_decode_loop),
    ("sampling.sample_token", "edgemesh/ops/sampling.py", _check_sample_token),
    ("attention.attend", "edgemesh/ops/attention.py", _check_attend),
    ("flash_attention", "edgemesh/ops/flash_attention.py", _check_flash_attention),
    ("paged_decode_attention", "edgemesh/ops/paged_attention.py", _check_paged_attention),
    ("int8_matmul_fused", "edgemesh/ops/int8.py", _check_int8_matmul),
    ("ring_attention", "edgemesh/parallel/ring_attention.py", _check_ring_attention),
    ("ulysses_attention", "edgemesh/parallel/ulysses.py", _check_ulysses_attention),
]


# ---------------------------------------------------------------------------
# check=True kernel ↔ ops/checks.py contract registry (EM204/EM205)
# ---------------------------------------------------------------------------
#
# Every kernel exposing a ``check`` kwarg must appear here with the
# ops/checks.py predicate it wires in, plus a known-good and a known-bad
# argument builder so the pass can PROVE the contract fires. Adding a new
# ``check=True`` kernel without registering it here is itself a finding.


def _flash_args(good: bool):
    import jax.numpy as jnp

    q = jnp.ones((1, 4, 2, 8), jnp.float32)
    k = jnp.ones((1, 8, 1, 8), jnp.float32)
    kv_lens = jnp.array([4 if good else 99], jnp.int32)  # bad: beyond kv extent
    return (q, k, kv_lens, jnp.array([0], jnp.int32))


def _paged_args(good: bool):
    import jax.numpy as jnp

    q = jnp.ones((2, 1, 8), jnp.float32)
    pages = jnp.ones((4, 1, 8, 8), jnp.float32)
    table = jnp.array([[0, 1], [2, 3 if good else 99]], jnp.int32)  # bad: OOB page
    kv_lens = jnp.array([3, 3], jnp.int32)
    return (q, pages, table, kv_lens)


def _ragged_args(good: bool):
    import jax.numpy as jnp

    q = jnp.ones((4, 1, 8), jnp.float32)
    pages = jnp.ones((4, 1, 8, 8), jnp.float32)
    table = jnp.array([[0, 1], [2, 3]], jnp.int32)
    kv_lens = jnp.array([5, 6], jnp.int32)
    # bad: segments claim more packed rows than q carries
    cu = jnp.array([0, 1, 4 if good else 9], jnp.int32)
    return (q, pages, table, kv_lens, cu)


def _int8_args(good: bool):
    import jax.numpy as jnp

    x = jnp.ones((2, 8), jnp.float32)
    w_q = jnp.zeros((8, 4), jnp.int8)
    scales = (
        jnp.ones((4,), jnp.float32)
        if good
        else jnp.array([1.0, 0.0, 1.0, 1.0], jnp.float32)  # bad: zero scale
    )
    return (x, w_q, scales)


CHECK_CONTRACTS: list[dict] = [
    {
        "kernel": ("edgemesh.ops.flash_attention", "flash_attention"),
        "checker": "check_flash_inputs",
        "args": _flash_args,
    },
    {
        "kernel": ("edgemesh.ops.paged_attention", "paged_decode_attention"),
        "checker": "check_paged_inputs",
        "args": _paged_args,
    },
    {
        "kernel": ("edgemesh.ops.paged_attention", "ragged_paged_attention"),
        "checker": "check_ragged_inputs",
        "args": _ragged_args,
    },
    {
        "kernel": ("edgemesh.ops.int8", "int8_matmul_fused"),
        "checker": "check_int8_inputs",
        "args": _int8_args,
    },
]


def _unwrap(fn):
    while hasattr(fn, "__wrapped__"):
        fn = fn.__wrapped__
    return fn


def _iter_check_kwarg_kernels():
    """Every public callable under edgemesh.ops exposing a ``check`` kwarg —
    the set EM204 requires to be covered by CHECK_CONTRACTS."""
    import importlib
    import pkgutil

    import edgemesh.ops as ops_pkg

    seen = set()
    for info in pkgutil.iter_modules(ops_pkg.__path__):
        if info.name == "checks":
            continue
        mod = importlib.import_module(f"edgemesh.ops.{info.name}")
        for name, obj in vars(mod).items():
            if name.startswith("_") or not callable(obj):
                continue
            raw = _unwrap(obj)
            if getattr(raw, "__module__", "") != mod.__name__:
                continue
            try:
                sig = inspect.signature(raw)
            except (TypeError, ValueError):
                continue
            if "check" in sig.parameters and (mod.__name__, name) not in seen:
                seen.add((mod.__name__, name))
                yield mod.__name__, name, raw
    return


def _run_check_contracts() -> list[Finding]:
    import importlib

    findings: list[Finding] = []
    registered = {c["kernel"] for c in CHECK_CONTRACTS}

    for mod_name, fn_name, raw in _iter_check_kwarg_kernels():
        rel = mod_name.replace(".", "/") + ".py"
        if (mod_name, fn_name) not in registered:
            findings.append(Finding(
                "EM204", "error", rel, 1,
                f"{fn_name} exposes check=True but has no entry in "
                "analysis/contracts.CHECK_CONTRACTS — register its "
                "ops/checks.py predicate plus good/bad exercise inputs",
                context=fn_name,
            ))

    from edgemesh.ops import checks as checks_mod

    for contract in CHECK_CONTRACTS:
        mod_name, fn_name = contract["kernel"]
        checker_name = contract["checker"]
        rel = mod_name.replace(".", "/") + ".py"
        try:
            mod = importlib.import_module(mod_name)
            raw = _unwrap(getattr(mod, fn_name))
        except (ImportError, AttributeError) as e:
            findings.append(Finding(
                "EM204", "error", rel, 1,
                f"registered kernel {mod_name}.{fn_name} does not import: {e}",
                context=fn_name,
            ))
            continue
        checker = getattr(checks_mod, checker_name, None)
        if checker is None:
            findings.append(Finding(
                "EM204", "error", "edgemesh/ops/checks.py", 1,
                f"contract {checker_name} for {fn_name} is not defined in "
                "ops/checks.py", context=fn_name,
            ))
            continue
        # Wired: the kernel body must actually call the checker when
        # check=True (a contract that exists but is never invoked rots).
        try:
            src = inspect.getsource(raw)
        except OSError:
            src = ""
        if checker_name not in src:
            findings.append(Finding(
                "EM204", "error", rel,
                getattr(raw, "__code__", None).co_firstlineno if hasattr(raw, "__code__") else 1,
                f"{fn_name} never calls its registered contract {checker_name} "
                "— check=True would silently validate nothing",
                context=fn_name,
            ))
            continue
        # Exercised: good inputs pass, bad inputs raise.
        line = raw.__code__.co_firstlineno if hasattr(raw, "__code__") else 1
        try:
            checks_mod.checked(checker)(*contract["args"](good=True))
        except Exception as e:  # noqa: BLE001 — any raise on GOOD inputs is the finding
            findings.append(Finding(
                "EM205", "error", "edgemesh/ops/checks.py", line,
                f"{checker_name} raised on its known-GOOD inputs: {e}",
                context=checker_name,
            ))
            continue
        fired = False
        try:
            checks_mod.checked(checker)(*contract["args"](good=False))
        except Exception:  # noqa: BLE001 — firing is the success condition
            fired = True
        if not fired:
            findings.append(Finding(
                "EM205", "error", "edgemesh/ops/checks.py", line,
                f"{checker_name} did NOT raise on its known-bad inputs — the "
                f"contract protecting {fn_name} is dead",
                context=checker_name,
            ))
    return findings


def run_contracts() -> list[Finding]:
    """Run the full abstract contract pass; returns findings (empty = green)."""
    findings: list[Finding] = []
    for name, rel, runner in ENTRY_POINTS:
        try:
            problems = runner()
        except Exception as e:  # noqa: BLE001 — a trace failure IS the finding
            findings.append(Finding(
                "EM201", "error", rel, 1,
                f"{name} failed to trace under eval_shape on its documented "
                f"abstract signature: {type(e).__name__}: {e}",
                context=name,
            ))
            continue
        for rule, message in problems:
            findings.append(Finding(
                rule, CONTRACT_RULES[rule]["severity"], rel, 1, message,
                context=name,
            ))
    findings.extend(_run_check_contracts())
    return findings
