"""edgemesh.analysis — static analysis (edgelint) + abstract contract checks.

Two passes over the codebase, designed to catch the silent-wrong-numbers and
API-drift bug classes BEFORE anything executes on a device:

- **edgelint** (``edgelint.py``): an AST linter with JAX/TPU-specific rules —
  drifted/removed JAX APIs (the ``jax.shard_map`` vs
  ``jax.experimental.shard_map`` split that broke 7 seed tests), host syncs
  inside jitted code, wall-clock timing without a completion fence, dead
  parameters in public jitted signatures (the ``len_cap`` failure mode),
  Python-loop unrolls and prints inside traced code.
- **contracts** (``contracts.py``): drives registered public entry points
  (ops kernels, transformer forwards, decode step) through ``jax.eval_shape``
  on tiny abstract configs, asserting shape/dtype stability (decode's output
  cache avals must equal its input cache avals — the recompile hazard), no
  float64/weak-type promotion, and that every kernel exposing ``check=True``
  wires an ``ops/checks.py`` contract.

CLI: ``python -m edgemesh.analysis [paths]`` or ``edgemesh lint [paths]``.
Grandfathered findings live in ``baseline.json`` next to this module; the
run exits non-zero on any non-baselined finding. See docs/ANALYSIS.md.
"""

from edgemesh.analysis.findings import (  # noqa: F401
    Baseline,
    Finding,
    default_baseline_path,
)
from edgemesh.analysis.edgelint import RULES, lint_paths  # noqa: F401


def run_analysis(paths, *, contracts: bool = True):
    """Lint ``paths`` and (optionally) run the abstract contract pass.

    Returns a list of Findings. Import of the contract pass is deferred so
    pure-lint callers never pay the jax import.
    """
    findings = lint_paths(paths)
    if contracts:
        from edgemesh.analysis.contracts import run_contracts

        findings.extend(run_contracts())
    return findings
