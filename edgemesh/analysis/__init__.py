"""edgemesh.analysis — static analysis (edgelint) + abstract contract checks.

Passes over the codebase designed to catch the silent-wrong-numbers and
API-drift bug classes BEFORE anything executes on a device:

- **edgelint** (``edgelint.py``): an AST linter with JAX/TPU-specific rules —
  drifted/removed JAX APIs (the ``jax.shard_map`` vs
  ``jax.experimental.shard_map`` split that broke 7 seed tests), host syncs
  inside jitted code, wall-clock timing without a completion fence, dead
  parameters in public jitted signatures (the ``len_cap`` failure mode),
  Python-loop unrolls and prints inside traced code.
- **contracts** (``contracts.py``): drives registered public entry points
  (ops kernels, transformer forwards, decode step) through ``jax.eval_shape``
  on tiny abstract configs, asserting shape/dtype stability (decode's output
  cache avals must equal its input cache avals — the recompile hazard), no
  float64/weak-type promotion, and that every kernel exposing ``check=True``
  wires an ``ops/checks.py`` contract.
- **sharding** (``sharding.py``): the parallel-stack pass — AST rules
  EM401-EM404 (unbound collective axes, shard_map spec mismatches,
  unreduced sharded contractions, host→jit retrace hazards) riding the
  lint entry points, plus the ``SHARDING_CONTRACTS`` AbstractMesh dryrun
  (EM405): every public shard_map wrapper traced under tp2/tp8/dp2×tp4/
  pp2-style layouts on CPU, no devices required.
- **wire** (``wire.py``): the protocol-contract pass over the fleet
  fabric's hand-rolled HTTP/JSON surface — AST rules EM501-EM505
  (unknown routes, header contracts, payload-key drift, schema
  producer/consumer drift, response discipline) checked against the one
  ``httputil.WIRE_CONTRACT`` table, plus the ``WIRE_CONTRACTS`` dryrun
  (EM506): each server's SERVED_ROUTES dispatch table cross-checked
  against the declared contract, stdlib-only, no sockets.

CLI: ``python -m edgemesh.analysis [paths]`` or ``edgemesh lint [paths]``.
Grandfathered findings live in ``baseline.json`` next to this module; the
run exits non-zero on any non-baselined finding. Filter rules with
``--select``/``--ignore`` (prefix-aware: ``--select EM4xx``). See
docs/ANALYSIS.md.
"""

from edgemesh.analysis.findings import (  # noqa: F401
    Baseline,
    Finding,
    default_baseline_path,
)
from edgemesh.analysis.edgelint import RULES, lint_paths  # noqa: F401


def run_analysis(paths, *, contracts: bool = True):
    """Lint ``paths`` and (optionally) run the jax-importing semantic
    passes (eval_shape contracts + the AbstractMesh sharding dryrun).

    The wire dryrun (EM506) imports nothing beyond the stdlib, so it runs
    even when ``contracts=False`` — the route tables must never drift out
    from under a pure-lint gate. Returns a list of Findings. Imports of
    the jax-importing passes are deferred so pure-lint callers never pay
    the jax import.
    """
    findings = lint_paths(paths)
    from edgemesh.analysis.wire import run_wire_contracts

    findings.extend(run_wire_contracts())
    if contracts:
        from edgemesh.analysis.contracts import run_contracts
        from edgemesh.analysis.sharding import run_sharding_contracts

        findings.extend(run_contracts())
        findings.extend(run_sharding_contracts())
    return findings
