"""Whole-class concurrency analysis for the threaded serving stack (EM3xx).

The EM1xx rules are per-function pattern checks; they cannot see a data
race, because a race is a property of a CLASS — which fields its methods
share, which lock each field belongs to, and what runs while that lock is
held. This pass does class-level abstract interpretation over the AST:

- **Lock discovery.** Every ``self._x = threading.Lock()`` / ``RLock()`` /
  ``Condition()`` assignment (including the dataclass
  ``field(default_factory=threading.Lock)`` spelling) makes ``_x`` a lock
  field of the class. Semaphores are deliberately NOT locks: a semaphore is
  an admission token (the router's in-flight slot pool), and holding one
  while sleeping or dialing out is the design, not a bug. Class bodies are
  merged down single-module inheritance chains, so a subclass's methods are
  judged against the base's locks and guard map (the speculative engine
  rides the base engine's ``_cond``).

- **EM301 unguarded-shared-state (error).** The guarded-field set is
  INFERRED: any ``self._x`` read or written inside a ``with self._lock:``
  block (in any method of the class or its same-module bases) is taken to
  be guarded by that lock. A *mutation* of an inferred-guarded field
  outside any held-lock region — assignment, augmented assignment,
  subscript store/delete, or a mutating method call (``append``/``pop``/
  ``update``/...) — is a race: the locked readers the inference found can
  observe torn or stale state. ``__init__`` (and ``__post_init__``/
  ``__new__``) are exempt — construction happens-before publication.
  Two annotations tune the inference (docs/ANALYSIS.md):

  - ``# guarded by: <lock>`` — on a field assignment: declares the guard
    explicitly (adds the field to the lock's guard set even when inference
    would miss it). On a ``def`` line: asserts every caller holds
    ``<lock>``, so the whole method body is analyzed as under it (the
    helper-called-with-lock-held pattern).
  - ``# not shared`` — on a field assignment: the field is owned by one
    thread (an engine worker's slot table, a donated device cache) and is
    exempt from EM301 even when a lock block happens to touch it.

- **EM302 lock-order-inversion (error).** A may-hold graph: an edge
  ``A -> B`` whenever a method can acquire ``B`` while holding ``A``,
  including through self-calls (``with self._a: self.helper()`` where the
  helper takes ``self._b``). A cycle means two threads can deadlock by
  acquiring the same locks in opposite orders. Per class (merged with
  same-module bases); cross-object cycles (registry<->router style) are
  out of static reach — docs/FLEET.md documents the ordering discipline.

- **EM303 blocking-under-lock (warning).** A known-blocking call while a
  lock is held: outbound HTTP (``post_json``/``get_json``/``urlopen``),
  ``time.sleep``, ``subprocess.*``, a zero-arg ``.get()`` / no-timeout
  ``.result()`` (queue/Future waits), ``.join()`` without timeout,
  ``block_until_ready``/``device_sync`` device fences. One stalled callee
  under a lock turns every other thread that needs the lock into a convoy
  — the exact shape that turns one stalled replica into a wedged router.
  ``Condition.wait``/``wait_for`` are NOT blocking-under-lock (they
  release the lock). Self-calls are descended; held regions also track
  ``lock.acquire()``/``release()`` pairs and, beyond class-constructed
  locks, any ``with``/acquire target whose terminal name looks like a lock
  (``*lock*``/``*cond*``/``*cv*``/``*mutex*``) so module-level locks and
  borrowed locks (``self.server.profile_lock``) are covered too.

- **EM304 thread-hygiene (warning).** ``threading.Thread(...)`` with no
  ``daemon=`` and no ``.join()`` on the stored handle anywhere in the file
  (an orphan thread with no shutdown path), and worker loops whose
  ``try``'s handler is a bare ``except:``/``except Exception:`` with a
  body of only ``pass``/``continue`` — a silently-swallowing worker loop
  keeps "running" after its state machine died.

Suppression and baselining are the standard edgelint mechanics: inline
``# edgelint: disable=EM301`` (line, ``def`` line, or ``class`` line), and
the fingerprint baseline (findings.py).
"""

from __future__ import annotations

import ast
import re

from edgemesh.analysis.edgelint import _Aliases as _EdgelintAliases
from edgemesh.analysis.edgelint import _dotted_name as _dotted
from edgemesh.analysis.findings import DISABLE_RE, Finding, repo_relative

RULES: dict[str, dict] = {
    "EM301": {
        "name": "unguarded-shared-state",
        "severity": "error",
        "summary": "mutation of an inferred lock-guarded field outside the lock",
    },
    "EM302": {
        "name": "lock-order-inversion",
        "severity": "error",
        "summary": "two locks acquired in opposite orders on different paths",
    },
    "EM303": {
        "name": "blocking-under-lock",
        "severity": "warning",
        "summary": "known-blocking call while a lock is held",
    },
    "EM304": {
        "name": "thread-hygiene",
        "severity": "warning",
        "summary": "thread without a shutdown path, or except-swallowing worker loop",
    },
}

# Lock constructors (threading.*). Semaphores are admission tokens, not
# mutual exclusion — holding one across blocking work is usually the point.
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}

# Annotation vocabulary (EM301). Matched against the raw source line of a
# field assignment or a ``def`` line.
_GUARDED_BY_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_NOT_SHARED_RE = re.compile(r"#\s*not shared\b")

# Heuristic: a with/acquire target whose terminal name matches this is
# treated as a lock even when this pass never saw it constructed (module
# globals, locks borrowed from another object).
_LOCKISH_NAME_RE = re.compile(r"(?:^|_)(?:lock|cond|cv|mutex)", re.IGNORECASE)

# Methods that mutate their receiver (list/dict/set/deque surface).
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "pop", "popleft", "popitem", "remove", "discard", "clear", "update",
    "setdefault", "sort", "reverse", "rotate",
}

# EM303: resolved dotted calls that block.
_BLOCKING_FUNCS = {"time.sleep", "urllib.request.urlopen", "jax.device_get"}
_BLOCKING_PREFIXES = ("subprocess.",)
# Attribute calls that block regardless of receiver.
_BLOCKING_ATTRS = {"post_json", "get_json", "block_until_ready", "device_sync"}
# Function-name spellings of the repo's device fences.
_BLOCKING_NAME_FUNCS = {"device_sync", "tree_sync"}
# Condition methods that RELEASE the lock while waiting — never EM303.
_WAIT_METHODS = {"wait", "wait_for", "notify", "notify_all"}

_INIT_METHODS = {"__init__", "__post_init__", "__new__"}


def _build_aliases(tree: ast.Module) -> _EdgelintAliases:
    """edgelint's import-alias resolver, fed the whole module — ONE
    resolution contract across every pass (``from jax import lax;
    lax.pcast`` and ``import time as t; t.sleep`` resolve identically)."""
    aliases = _EdgelintAliases()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            aliases.visit_import(node)
        elif isinstance(node, ast.ImportFrom):
            aliases.visit_import_from(node)
    return aliases


def _is_lock_ctor(node: ast.AST, aliases: _EdgelintAliases) -> bool:
    """``threading.Lock()`` / aliased, or
    ``field(default_factory=threading.Lock)`` (dataclass spelling)."""
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d and aliases.resolve(d) in _LOCK_CTORS:
        return True
    if d and aliases.resolve(d).rsplit(".", 1)[-1] == "field":
        for kw in node.keywords:
            if kw.arg == "default_factory":
                fd = _dotted(kw.value)
                if fd and aliases.resolve(fd) in _LOCK_CTORS:
                    return True
    return False


def _flatten_targets(targets) -> list[ast.AST]:
    """Unpack tuple/list assignment targets: ``self.a, self.b = ...``."""
    out: list[ast.AST] = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            out.extend(_flatten_targets(t.elts))
        else:
            out.append(t)
    return out


def _self_attr(node: ast.AST) -> str | None:
    """'x' for ``self.x``; None otherwise."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_key(node: ast.AST) -> str | None:
    """Identity of a lock expression for held-set/graph purposes.

    ``self._lock`` -> "self._lock"; a bare lockish Name -> its id; a
    lockish attribute chain (``self.server.profile_lock``) -> the dotted
    path. None when the expression does not look like a lock at all."""
    attr = _self_attr(node)
    if attr is not None:
        return f"self.{attr}"
    d = _dotted(node)
    if d is not None:
        tail = d.rsplit(".", 1)[-1]
        if _LOCKISH_NAME_RE.search(tail):
            return d
    return None


class _ClassInfo:
    """Per-class facts collected in pass one."""

    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
        self.locks: set[str] = set()  # field names constructed as locks
        self.methods: dict[str, ast.AST] = {}
        # field -> set of lock keys it was touched under (inference)
        self.guarded: dict[str, set[str]] = {}
        self.not_shared: set[str] = set()
        # field -> declared guard (from "# guarded by:" on an assignment)
        self.declared: dict[str, str] = {}


class _FileConcurrency:
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.relpath = repo_relative(path)
        self.source = source
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.disabled: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = DISABLE_RE.search(line)
            if m:
                self.disabled[i] = {r.strip() for r in m.group(1).split(",")}

    # -- shared emit machinery ----------------------------------------------

    def _scopes_for_line(self, line: int) -> list[ast.AST]:
        return [
            s for s in self._all_scopes
            if s.lineno <= line <= getattr(s, "end_lineno", s.lineno)
        ]

    def _suppressed(self, rule: str, line: int) -> bool:
        if rule in self.disabled.get(line, ()):
            return True
        for scope in self._scopes_for_line(line):
            if rule in self.disabled.get(scope.lineno, ()):
                return True
        return False

    def _context_for_line(self, line: int) -> str:
        best = ""
        for s in self._scopes_for_line(line):
            best = s.name if not best else f"{best}.{s.name}"
        return best

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(rule, line):
            return
        self.findings.append(
            Finding(
                rule=rule,
                severity=RULES[rule]["severity"],
                path=self.relpath,
                line=line,
                message=message,
                context=self._context_for_line(line),
                line_text=(self.lines[line - 1].strip() if line <= len(self.lines) else ""),
            )
        )

    def _line_text(self, node: ast.AST) -> str:
        line = getattr(node, "lineno", 0)
        return self.lines[line - 1] if 0 < line <= len(self.lines) else ""

    # -- driver --------------------------------------------------------------

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError:
            return []  # edgelint already reports EM000 for this file
        self.aliases = _build_aliases(tree)
        self._all_scopes = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
        ]

        # Pass one: per-class collection, then merge same-module bases.
        infos: dict[str, _ClassInfo] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                infos[node.name] = self._collect_class(node)
        for info in infos.values():
            self._merge_bases(info, infos, set())

        # Pass two: judge each class.
        for info in infos.values():
            self._rule_unguarded(info)
            self._rule_lock_order(info)
        # EM303 runs over every function (methods get self-call descent via
        # their class info); EM304 over the whole module.
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                info = infos[node.name]
                for m in info.own_methods:
                    self._scan_blocking(
                        info, info.methods_merged, m,
                        self._entry_locks(m), set(),
                    )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not any(
                    isinstance(s, ast.ClassDef)
                    and s.lineno <= node.lineno <= getattr(s, "end_lineno", s.lineno)
                    for s in self._all_scopes
                ):
                    self._scan_blocking(None, {}, node, self._entry_locks(node), set())
        self._rule_thread_hygiene(tree)

        seen: set[tuple] = set()
        unique: list[Finding] = []
        for f in sorted(self.findings, key=lambda f: (f.line, f.rule)):
            key = (f.rule, f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        return self.findings

    # -- collection ----------------------------------------------------------

    def _collect_class(self, node: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(node)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[stmt.name] = stmt
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                # Dataclass field: ``_lock: Any = field(default_factory=...)``
                if isinstance(stmt.target, ast.Name) and _is_lock_ctor(
                    stmt.value, self.aliases
                ):
                    info.locks.add(stmt.target.id)
        # Lock constructions + annotations on self-field assignments.
        for sub in ast.walk(node):
            targets: list[ast.AST] = []
            value = None
            if isinstance(sub, ast.Assign):
                targets, value = _flatten_targets(sub.targets), sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                targets, value = [sub.target], sub.value
            for t in targets:
                f = _self_attr(t)
                if f is None:
                    continue
                if value is not None and _is_lock_ctor(value, self.aliases):
                    info.locks.add(f)
                text = self._line_text(sub)
                if _NOT_SHARED_RE.search(text):
                    info.not_shared.add(f)
                m = _GUARDED_BY_RE.search(text)
                if m:
                    info.declared[f] = m.group(1)
        # Guarded-field inference: self-attr accesses inside held regions.
        for m in info.methods.values():
            self._infer_method(info, m)
        return info

    def _infer_method(self, info: _ClassInfo, fn: ast.AST) -> None:
        def visit(node: ast.AST, held: frozenset[str]) -> frozenset[str]:
            """Returns the held set AFTER this node — locked regions come
            from with-blocks AND linear acquire()/release() pairs (the
            try/finally idiom), same tracking as every other sub-rule."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                return held  # nested defs run on their own schedule
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    key = _lock_key(item.context_expr)
                    if key is not None and self._is_known_lock(info, key):
                        inner = inner | {key}
                for child in node.body:
                    inner = visit(child, inner)
                return held
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                key = _lock_key(node.func.value)
                known = key is not None and self._is_known_lock(info, key)
                if known and node.func.attr == "acquire":
                    return held | {key}
                if known and node.func.attr == "release":
                    return frozenset(k for k in held if k != key)
            if held and isinstance(node, ast.Attribute):
                f = _self_attr(node)
                if f is not None and f not in info.locks:
                    for lock in held:
                        info.guarded.setdefault(f, set()).add(lock)
            for child in ast.iter_child_nodes(node):
                held = visit(child, held)
            return held

        held = self._entry_locks(fn)
        for stmt in fn.body:
            held = visit(stmt, held)

    def _entry_locks(self, fn: ast.AST) -> frozenset[str]:
        """Locks asserted held at method entry via ``# guarded by:`` on the
        def line."""
        m = _GUARDED_BY_RE.search(self._line_text(fn))
        if m:
            return frozenset({f"self.{m.group(1)}", m.group(1)})
        return frozenset()

    def _is_known_lock(self, info: _ClassInfo | None, key: str) -> bool:
        if info is not None and key.startswith("self."):
            if key[len("self."):] in info.locks:
                return True
        return bool(_LOCKISH_NAME_RE.search(key.rsplit(".", 1)[-1]))

    def _merge_bases(self, info: _ClassInfo, infos: dict[str, _ClassInfo],
                     seen: set[str]) -> None:
        """Fold same-module base classes into the subclass view (locks,
        guard inference, annotations, and the method table used for
        self-call resolution — subclass overrides win)."""
        if getattr(info, "_merged", False):
            return
        info._merged = True
        info.own_methods = list(info.methods.values())
        merged = dict(info.methods)
        for base_name in info.bases:
            base = infos.get(base_name)
            if base is None or base_name in seen:
                continue
            self._merge_bases(base, infos, seen | {info.node.name})
            info.locks |= base.locks
            info.not_shared |= base.not_shared
            for f, g in base.declared.items():
                info.declared.setdefault(f, g)
            for f, locks in base.guarded.items():
                info.guarded.setdefault(f, set()).update(locks)
            for name, fn in base.methods_merged.items():
                merged.setdefault(name, fn)
        info.methods_merged = merged
        # Re-run inference for own methods now that base locks are known
        # (a subclass method using an inherited lock field).
        for m in info.own_methods:
            self._infer_method(info, m)

    # -- EM301 ---------------------------------------------------------------

    def _rule_unguarded(self, info: _ClassInfo) -> None:
        guard_of: dict[str, set[str]] = {}
        for f, locks in info.guarded.items():
            guard_of[f] = set(locks)
        for f, lock in info.declared.items():
            guard_of.setdefault(f, set()).update({f"self.{lock}", lock})
        for f in info.not_shared:
            guard_of.pop(f, None)
        if not guard_of:
            return

        for fn in info.own_methods:
            if fn.name in _INIT_METHODS:
                continue
            self._scan_mutations(info, fn, guard_of)

    def _scan_mutations(self, info: _ClassInfo, fn: ast.AST,
                        guard_of: dict[str, set[str]]) -> None:
        def report(node: ast.AST, f: str, held: frozenset[str]) -> None:
            locks = guard_of.get(f)
            if not locks or locks & held:
                return
            if _NOT_SHARED_RE.search(self._line_text(node)) or _GUARDED_BY_RE.search(
                self._line_text(node)
            ):
                # Site-level annotation: reviewed single-thread ownership or
                # an externally-held guard this pass cannot see.
                return
            lock_names = ", ".join(sorted(k.removeprefix("self.") for k in locks))
            self._emit(
                "EM301", node,
                f"'{info.node.name}.{f}' is read/written under '{lock_names}' "
                f"elsewhere but mutated here without it — locked readers can "
                "see torn/stale state (take the lock, or annotate the field "
                "'# guarded by: <lock>' / '# not shared')",
            )

        def visit(node: ast.AST, held: frozenset[str]) -> frozenset[str]:
            """Returns the held set AFTER this node — linear
            acquire()/release() pairs extend it statement-to-statement, the
            same tracking _scan_blocking uses (a with-block is not the only
            correct way to hold a lock)."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                return held
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    key = _lock_key(item.context_expr)
                    if key is not None and self._is_known_lock(info, key):
                        inner = inner | {key}
                for child in node.body:
                    inner = visit(child, inner)
                return held
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                key = _lock_key(node.func.value)
                known = key is not None and self._is_known_lock(info, key)
                if known and node.func.attr == "acquire":
                    return held | {key}
                if known and node.func.attr == "release":
                    return frozenset(k for k in held if k != key)
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in _flatten_targets(targets):
                    f = _self_attr(t)
                    if f is not None:
                        report(node, f, held)
                    elif isinstance(t, ast.Subscript):
                        f = _self_attr(t.value)
                        if f is not None:
                            report(node, f, held)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    f = _self_attr(base)
                    if f is not None:
                        report(node, f, held)
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _MUTATOR_METHODS:
                    f = _self_attr(node.func.value)
                    if f is not None:
                        report(node, f, held)
            for child in ast.iter_child_nodes(node):
                held = visit(child, held)
            return held

        held = self._entry_locks(fn)
        for stmt in fn.body:
            held = visit(stmt, held)

    # -- EM302 ---------------------------------------------------------------

    def _rule_lock_order(self, info: _ClassInfo) -> None:
        # edges[(A, B)] = (method name, line) sample where B is taken under A
        edges: dict[tuple[str, str], tuple[str, int]] = {}

        def walk(fn: ast.AST, entry_held: frozenset[str],
                 stack: frozenset[str], origin: str) -> None:
            def visit(node: ast.AST, held: frozenset[str]) -> frozenset[str]:
                """Returns the held set AFTER this node, so a linear
                ``a.acquire(); with b: ...`` sequence contributes its
                a->b edge like the with-block form does."""
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                    return held
                if isinstance(node, ast.With):
                    inner = held
                    for item in node.items:
                        key = _lock_key(item.context_expr)
                        if key is not None and self._is_known_lock(info, key):
                            for h in inner:
                                if h != key:
                                    edges.setdefault((h, key), (origin, node.lineno))
                            inner = inner | {key}
                    for child in node.body:
                        inner = visit(child, inner)
                    return held
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                    key = _lock_key(node.func.value)
                    known = key is not None and self._is_known_lock(info, key)
                    if known and node.func.attr == "acquire":
                        for h in held:
                            if h != key:
                                edges.setdefault((h, key), (origin, node.lineno))
                        return held | {key}
                    if known and node.func.attr == "release":
                        return frozenset(k for k in held if k != key)
                    if (
                        isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and held
                    ):
                        callee = info.methods_merged.get(node.func.attr)
                        if callee is not None and node.func.attr not in stack:
                            walk(callee, held, stack | {node.func.attr},
                                 f"{origin}->{node.func.attr}")
                for child in ast.iter_child_nodes(node):
                    held = visit(child, held)
                return held

            held = entry_held
            for stmt in fn.body:
                held = visit(stmt, held)

        for fn in info.own_methods:
            walk(fn, self._entry_locks(fn), frozenset({fn.name}), fn.name)

        # Cycle detection over the acquisition digraph.
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        reported: set[frozenset[str]] = set()
        for start in sorted(graph):
            path: list[str] = []

            def dfs(nodekey: str) -> list[str] | None:
                if nodekey in path:
                    return path[path.index(nodekey):] + [nodekey]
                path.append(nodekey)
                for nxt in sorted(graph.get(nodekey, ())):
                    cyc = dfs(nxt)
                    if cyc is not None:
                        return cyc
                path.pop()
                return None

            cycle = dfs(start)
            if cycle is None:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            # Anchor on one edge of the cycle, describe the whole loop.
            origin, line = edges[(cycle[0], cycle[1])]
            route = " -> ".join(k.removeprefix("self.") for k in cycle)
            anchor = ast.copy_location(ast.Pass(), info.node)
            anchor.lineno = line
            self._emit(
                "EM302", anchor,
                f"lock-order inversion in '{info.node.name}': {route} "
                f"(one edge via {origin}) — two threads taking these locks "
                "in opposite orders deadlock; pick one global order and "
                "release before crossing it",
            )

    # -- EM303 ---------------------------------------------------------------

    def _blocking_reason(self, node: ast.Call) -> str | None:
        d = _dotted(node.func)
        resolved = self.aliases.resolve(d) if d else None
        if resolved:
            if resolved in _BLOCKING_FUNCS:
                return f"{resolved}()"
            if any(resolved.startswith(p) for p in _BLOCKING_PREFIXES):
                return f"{resolved}()"
        if isinstance(node.func, ast.Name) and node.func.id in _BLOCKING_NAME_FUNCS:
            return f"{node.func.id}()"
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            has_timeout = any(
                kw.arg in ("timeout", "timeout_s") for kw in node.keywords
            )
            if attr in _BLOCKING_ATTRS:
                # Transport calls block for their (bounded) timeout — still
                # a convoy while a lock is held, so a timeout kwarg does not
                # exempt them.
                return f".{attr}()"
            if attr == "get" and not node.args and not node.keywords:
                return ".get() with no timeout"
            if attr in ("result", "join") and not has_timeout and not node.args:
                return f".{attr}() with no timeout"
        return None

    def _scan_blocking(self, info: _ClassInfo | None,
                       methods: dict[str, ast.AST], fn: ast.AST,
                       entry_held: frozenset[str], stack: frozenset[str],
                       report_node: ast.AST | None = None) -> None:
        """Walk ``fn`` tracking held locks (with-blocks AND linear
        acquire()/release() pairs); report blocking calls executed while
        any lock is held. Only KNOWN locks count — class-constructed
        Lock/RLock/Condition fields plus lockish-named targets — so a
        semaphore slot held across dispatch is not a finding.
        ``report_node`` anchors findings at an outer self-call site when
        descending."""

        def report(node: ast.Call, what: str, held: frozenset[str]) -> None:
            anchor = report_node or node
            locks = ", ".join(sorted(k.removeprefix("self.") for k in held))
            via = "" if report_node is None else f" (via self.{fn.name}())"
            self._emit(
                "EM303", anchor,
                f"blocking {what}{via} while holding '{locks}' — every "
                "thread needing the lock convoys behind this call; move the "
                "blocking work outside the held region or switch to a "
                "flag-under-lock",
            )

        def visit(node: ast.AST, held: frozenset[str]) -> frozenset[str]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                return held
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    key = _lock_key(item.context_expr)
                    if key is not None and self._is_known_lock(info, key):
                        inner = inner | {key}
                for child in node.body:
                    inner = visit(child, inner)
                return held
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                key = _lock_key(node.func.value)
                known = key is not None and self._is_known_lock(info, key)
                if known and attr == "acquire":
                    # Linear tracking: held from this statement until a
                    # release() on the same chain in this function.
                    return held | {key}
                if known and attr == "release":
                    return frozenset(k for k in held if k != key)
                if known and attr in _WAIT_METHODS:
                    # Condition.wait releases the lock while blocked.
                    for child in ast.iter_child_nodes(node):
                        visit(child, held)
                    return held
            if isinstance(node, ast.Call):
                if held:
                    what = self._blocking_reason(node)
                    if what is not None:
                        report(node, what, held)
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods
                        and node.func.attr not in stack
                    ):
                        self._scan_blocking(
                            info, methods, methods[node.func.attr], held,
                            stack | {node.func.attr},
                            report_node=report_node or node,
                        )
            for child in ast.iter_child_nodes(node):
                held = visit(child, held)
            return held

        held = entry_held
        for stmt in fn.body:
            held = visit(stmt, held)

    # -- EM304 ---------------------------------------------------------------

    def _rule_thread_hygiene(self, tree: ast.Module) -> None:
        # Names/attrs .join()ed anywhere in the file.
        joined: set[str] = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                d = _dotted(node.func.value)
                if d:
                    joined.add(d)
        # Map def name -> node for target resolution (module + class level).
        defs: dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if not d or self.aliases.resolve(d) != "threading.Thread":
                continue
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            if "daemon" not in kwargs:
                # Find where the handle lands: x = Thread(...) / self._t = ...
                # (annotated form included: self._t: Thread = Thread(...)).
                handle: str | None = None
                parent_targets = self._assign_targets(tree, node)
                for t in parent_targets:
                    handle = _dotted(t)
                    break
                if handle is None or handle not in joined:
                    self._emit(
                        "EM304", node,
                        "thread has no shutdown path: neither daemon= nor a "
                        ".join() on its handle anywhere in this file — it "
                        "outlives close()/shutdown and leaks across restarts",
                    )
            target = kwargs.get("target")
            tname = None
            if isinstance(target, ast.Name):
                tname = target.id
            elif isinstance(target, ast.Attribute):
                tname = target.attr
            worker = defs.get(tname) if tname else None
            if worker is not None:
                self._check_swallowing_loop(worker)

    @staticmethod
    def _assign_targets(tree: ast.Module, call: ast.Call) -> list[ast.AST]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and node.value is call:
                return _flatten_targets(node.targets)
            if isinstance(node, ast.AnnAssign) and node.value is call:
                return [node.target]
        return []

    def _check_swallowing_loop(self, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Try):
                    continue
                for handler in sub.handlers:
                    broad = handler.type is None or (
                        isinstance(handler.type, ast.Name)
                        and handler.type.id in ("Exception", "BaseException")
                    )
                    silent = all(
                        isinstance(s, (ast.Pass, ast.Continue)) for s in handler.body
                    )
                    if broad and silent:
                        self._emit(
                            "EM304", handler,
                            "worker loop swallows every exception silently "
                            "(bare except + pass/continue) — the thread keeps "
                            "'running' after its state machine died; log it "
                            "(log.exception) or let it crash loudly",
                        )


def analyze_source(source: str, path: str = "<memory>") -> list[Finding]:
    """Concurrency-pass entry point (mirrors edgelint.lint_source)."""
    return _FileConcurrency(path, source).run()
