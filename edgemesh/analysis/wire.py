"""Static HTTP/JSON protocol-contract analysis for the fleet fabric (EM5xx).

The fleet fabric is a hand-rolled HTTP/JSON surface: ~15 routes
string-dispatched in serve/rest.py and fleet/frontend.py, a few dozen
client call sites across fleet/, loadgen/, and benchmarks.py, and schema'd
dicts (load digests, /fleetz, span records) produced in one process and
consumed in another. Every historical bug class here — a typo'd digest key
silently ``.get()``-defaulting in the balancer, a header dropped on one of
five propagation paths — ships past the fast tier and only dies in
slow-tier e2e. This pass is the wire's equivalent of the sharding pass
(analysis/sharding.py): the protocol is declared ONCE, in
``httputil.WIRE_CONTRACT``, and everything else is checked against it.

**Layer 1 — AST rules** (standard ``lint_source``/baseline/disable/
``--select`` machinery; same suppression comments):

- **EM501 unknown-route (error).** A client call — ``post_json`` /
  ``get_json`` / ``urlopen`` / connection ``.request`` — whose URL path
  the pass can resolve (a literal, the trailing constant of an f-string,
  a ``base + "/path"`` concatenation, a ``rep.url("/path")`` argument, or
  a ``httputil`` path constant, through one level of local assignment)
  that matches no declared route, or a route served under a different
  method. Opaque URLs (a parameter, a config value) are out of scope —
  same visibility contract as the old header rule.

- **EM502 header-contract (error).** The per-route required/forwarded
  header sets live in WIRE_CONTRACT — this rule SUBSUMES the retired
  ad-hoc EM108 (fleet-dial-timeout) and EM109 (fleet-trace-header), whose
  hardcoded requirements became contract rows. Client side (fleet/ only,
  like its ancestors): a call that builds a headers mapping for a route
  must include each required header (the literal, any name ending in the
  ``httputil`` constant's name, or a ``**`` expansion); a route marked
  ``strict_headers`` (the KV transfer hops) flags even with no headers
  mapping at all; raw dials (``urlopen``/``HTTPConnection``/...) without
  a timeout keep the EM108 check under this id. Handler side (the two
  server files): the dispatch scope serving the route — the functions
  containing its path literal plus their self-call closure — must read
  each required/forwarded header via the matching ``httputil.read_*``
  helper.

- **EM503 payload-key-drift (error).** Client side: keys of a dict
  literal POSTed to a resolved route must be declared in the route's
  ``request_keys``. Handler side (server files): every ``payload.get()``/
  subscript read of a request body must be a key some declared route for
  that server carries — the classic typo'd-key bug, caught from both
  ends. Handler reads are checked against the union of the server's
  declared keys because dispatch helpers are shared across routes.

- **EM504 schema-drift (error).** For the registered cross-process dict
  schemas (``WIRE_SCHEMAS``: load digest + capacity model, the /readyz
  body, /fleetz, router trace records): every consumer-side key read must
  appear in some producer-side write (dict literal, subscript store,
  ``setdefault``, ``dict(k=...)``). Consumers are named functions with
  seed receiver names; derivation follows ``.get()`` chains, subscripts,
  ``or {}`` guards, local rebinding, and loop targets — the same
  descend-through-helpers pragmatics the concurrency pass uses.

- **EM505 response-discipline (warning).** A handler answering 5xx with a
  dict literal that lacks the structured ``"kind"`` vocabulary (a bare
  500 tells the fleet router nothing), and a client function that makes
  transport calls and branches on 503 without ever mentioning
  ``Retry-After`` (the shed contract: 503 always carries it).

**Layer 2 — the wire dryrun** (EM506, like the sharding pass's EM405):
``WIRE_CONTRACTS`` registers each server's live dispatch table
(``SERVED_ROUTES`` in serve/rest.py and fleet/frontend.py — the table the
404 branch actually consults, so it cannot go stale), and
``run_wire_contracts()`` imports it and cross-checks against the static
contract: a route registered but undeclared, declared but unserved, or
served under a different method fails in seconds with no sockets. Both
server modules are stdlib-only at import time, so the dryrun runs even
under ``--no-contracts``.

``edgemesh obs routes`` renders the contract table; docs/ANALYSIS.md
documents the rules and docs/FLEET.md the protocol they guard.
"""

from __future__ import annotations

import ast
from pathlib import Path

from edgemesh.analysis.edgelint import _Aliases as _EdgelintAliases
from edgemesh.analysis.edgelint import _dotted_name as _dotted
from edgemesh.analysis.findings import DISABLE_RE, Finding, repo_relative
from edgemesh.serve import httputil

WIRE_RULES: dict[str, dict] = {
    "EM501": {
        "name": "unknown-route",
        "severity": "error",
        "summary": "client call targets a path or method no WIRE_CONTRACT route declares",
    },
    "EM502": {
        "name": "header-contract",
        "severity": "error",
        "summary": "required wire header missing at a client site or never read by the handler",
    },
    "EM503": {
        "name": "payload-key-drift",
        "severity": "error",
        "summary": "POSTed payload key or handler body read outside the route's declared keys",
    },
    "EM504": {
        "name": "schema-drift",
        "severity": "error",
        "summary": "consumer reads a schema key no registered producer writes",
    },
    "EM505": {
        "name": "response-discipline",
        "severity": "warning",
        "summary": "bare 5xx without the structured error vocabulary, or 503 handled without Retry-After",
    },
}

#: The Layer-2 dryrun rule — separate table, like SHARDING_CONTRACT_RULES,
#: because its findings come from ``run_wire_contracts()``, not from
#: ``analyze_source``.
WIRE_CONTRACT_RULES: dict[str, dict] = {
    "EM506": {
        "name": "wire-dryrun-failure",
        "severity": "error",
        "summary": "a server's live dispatch table disagrees with WIRE_CONTRACT",
    },
}

# -- contract plumbing shared by the rules -----------------------------------

#: Which repo file implements each server named in WIRE_CONTRACT rows.
#: Path-substring matched (like the EM107 dirs) so fixture tests with
#: relative paths resolve the same everywhere.
WIRE_SERVERS: dict[str, str] = {
    "gateway": "edgemesh/serve/rest.py",
    "frontend": "edgemesh/fleet/frontend.py",
}

#: Client-side header/timeout obligations apply here (the fleet's outbound
#: seams — the scope the retired EM108/EM109 judged). EM501/EM503/EM505
#: client checks run package-wide.
WIRE_CLIENT_DIRS = ("edgemesh/fleet/",)

#: header value -> the httputil read helper a handler must call for it.
READ_HELPERS: dict[str, str] = {
    httputil.DEADLINE_HEADER: "read_deadline_header",
    httputil.TRACE_HEADER: "read_trace_header",
    httputil.TENANT_HEADER: "read_tenant_header",
    httputil.SESSION_HEADER: "read_session_header",
}

#: header value -> the exported constant name (a headers-dict key written
#: as ``httputil.TRACE_HEADER`` or a local ``TRACE_HEADER`` import counts).
HEADER_CONSTS: dict[str, str] = {
    httputil.DEADLINE_HEADER: "DEADLINE_HEADER",
    httputil.TRACE_HEADER: "TRACE_HEADER",
    httputil.TENANT_HEADER: "TENANT_HEADER",
    httputil.SESSION_HEADER: "SESSION_HEADER",
}

#: httputil path-constant names, so ``rep.url(KV_EXPORT_PATH)`` resolves.
PATH_CONSTS: dict[str, str] = {
    "KV_EXPORT_PATH": httputil.KV_EXPORT_PATH,
    "KV_IMPORT_PATH": httputil.KV_IMPORT_PATH,
    "ENSEMBLE_PATH": httputil.ENSEMBLE_PATH,
}

# The EM108 dial table, now a contract policy under EM502: outbound calls
# that accept a timeout, mapped to the 0-based positional index where the
# timeout can ride (None = kwarg only).
_DIAL_CALLS = {
    "urllib.request.urlopen": 2,        # urlopen(url, data, timeout)
    "socket.create_connection": 1,      # create_connection(address, timeout)
    "http.client.HTTPConnection": 2,    # HTTPConnection(host, port, timeout)
    "http.client.HTTPSConnection": 2,
    "requests.get": None,               # kwarg-only (defensive: not a dep)
    "requests.post": None,
    "requests.request": None,
}

_TRANSPORT_CALLS = {"post_json": "POST", "get_json": "GET"}
_URLOPEN = "urllib.request.urlopen"

# -- EM504 schema registry ----------------------------------------------------
#
# Each schema names the functions that PRODUCE its dict shape (keys are
# collected from dict literals, subscript stores, ``setdefault``, and
# ``dict(k=...)`` anywhere in those functions) and the functions that
# CONSUME it (with the local names the schema document is bound to — reads
# derived from those names are checked against the produced key set).
# Producer files are parsed lazily from the repo root and cached.

WIRE_SCHEMAS: dict[str, dict] = {
    "load_digest": {
        "doc": "per-replica load digest (+ capacity model) — GET /loadz, "
               "piggybacked on /readyz; what the telemetry balancer and "
               "autoscaler weigh replicas by",
        "producers": (
            ("edgemesh/serve/rest.py", "_load_digest"),
            ("edgemesh/serve/continuous.py", "load_digest"),
            ("edgemesh/serve/continuous.py", "estimate_capacity"),
            # per-boundary cost block (digest["costs"]) — measured launch
            # EWMAs from the compute ledger (obs/compute.py)
            ("edgemesh/obs/compute.py", "digest_costs"),
            # pool-memory block (digest["mem"]) — occupancy, fragmentation,
            # leak counters, and the exhaustion forecast from the pool
            # ledger (obs/memory.py)
            ("edgemesh/obs/memory.py", "digest_mem"),
        ),
        "consumers": (
            ("edgemesh/fleet/balancer.py", "_cost", ("load",)),
            ("edgemesh/fleet/balancer.py", "_cost_service_s", ("load",)),
            ("edgemesh/fleet/balancer.py", "_mem_penalty", ("load",)),
            ("edgemesh/fleet/balancer.py", "_prefill_share", ("load",)),
            ("edgemesh/fleet/autoscale.py", "_demand_supply", ("load",)),
            ("edgemesh/fleet/autoscale.py", "evaluate", ("load",)),
            ("edgemesh/fleet/admission.py", "note_mem_forecast", ("load",)),
            ("edgemesh/fleet/health.py", "probe_once", ("load",)),
        ),
    },
    "readyz_body": {
        "doc": "GET /readyz response — readiness + live inflight count "
               "(the drain poll) + the piggybacked digest",
        "producers": (
            ("edgemesh/serve/rest.py", "do_GET"),
        ),
        "consumers": (
            ("edgemesh/fleet/health.py", "_probe", ("body",)),
            ("edgemesh/fleet/router.py", "drain_replica", ("body",)),
        ),
    },
    "fleet_status": {
        "doc": "GET /fleetz document (FleetRouter.status) — what "
               "`edgemesh fleet status` renders",
        "producers": (
            ("edgemesh/fleet/router.py", "status"),
            ("edgemesh/fleet/router.py", "_account_tenant"),
            ("edgemesh/fleet/registry.py", "to_dict"),
            ("edgemesh/fleet/autoscale.py", "status"),
            ("edgemesh/fleet/autoscale.py", "evaluate"),
            ("edgemesh/fleet/autotune.py", "status"),
            ("edgemesh/fleet/admission.py", "stats"),
            ("edgemesh/loadgen/curve.py", "find_knee"),
        ),
        "consumers": (
            ("edgemesh/fleet/cli.py", "cmd_status", ("body",)),
        ),
    },
    "trace_record": {
        "doc": "router-side sampled trace record (request span + attempt "
               "spans) — /fleetz summaries and /debug/traces/<id>",
        "producers": (
            ("edgemesh/fleet/router.py", "_finish_trace"),
            ("edgemesh/fleet/router.py", "_attempt_one"),
            ("edgemesh/fleet/router.py", "_route"),
        ),
        "consumers": (
            ("edgemesh/fleet/router.py", "recent_traces", ("rec", "s")),
            ("edgemesh/fleet/router.py", "get_trace", ("rec", "match")),
        ),
    },
    "pool_view": {
        "doc": "registry pools() entry ({replicas, role, routable}) — the "
               "/fleetz 'pools' block and what the ensemble coordinator's "
               "topology discovery routes by (the model descriptor itself "
               "rides POST /replicas/register's 'model' key, WIRE_CONTRACT)",
        "producers": (
            ("edgemesh/fleet/registry.py", "pools"),
        ),
        "consumers": (
            ("edgemesh/fleet/ensemble.py", "topology", ("e",)),
        ),
    },
}

#: Repo root for resolving producer files (tests repoint this at a tmp
#: tree when exercising EM504 fixtures).
_REPO_ROOT = Path(__file__).resolve().parents[2]

#: produced-key cache: (schema, repo_root) -> frozenset of keys, or None
#: when no producer file was readable (the check then stays silent rather
#: than flagging everything).
_SCHEMA_CACHE: dict[tuple[str, str], frozenset | None] = {}


def _schema_produced_keys(schema: str) -> frozenset | None:
    cache_key = (schema, str(_REPO_ROOT))
    if cache_key in _SCHEMA_CACHE:
        return _SCHEMA_CACHE[cache_key]
    keys: set[str] = set()
    saw_producer = False
    for relpath, func in WIRE_SCHEMAS[schema]["producers"]:
        p = _REPO_ROOT / relpath
        try:
            tree = ast.parse(p.read_text())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == func):
                saw_producer = True
                keys |= _produced_keys(node)
    result = frozenset(keys) if saw_producer else None
    _SCHEMA_CACHE[cache_key] = result
    return result


def _produced_keys(fn: ast.AST) -> set[str]:
    """Every string key this function writes into a dict shape: literal
    dict keys, ``x["k"] = ...`` stores, ``.setdefault("k", ...)``, and
    ``dict(k=...)`` keywords."""
    keys: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Store):
            if (isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                keys.add(node.slice.value)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "setdefault":
                if (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    keys.add(node.args[0].value)
            elif isinstance(f, ast.Name) and f.id == "dict":
                keys.update(kw.arg for kw in node.keywords if kw.arg)
    return keys


# -- route resolution ---------------------------------------------------------


def _path_from_string(s: str) -> str | None:
    """The request path inside a URL-ish string constant."""
    if s.startswith("/"):
        return s
    if "://" in s:
        rest = s.split("://", 1)[1]
        return "/" + rest.split("/", 1)[1] if "/" in rest else None
    return None


def _contract_route(method: str, path: str):
    """The (key, row) for a resolved request path, honoring prefix routes
    (``/debug/traces/<id>``). None when nothing matches under any method;
    the second element of the miss is the set of methods that DO serve the
    path, so EM501 can say "wrong method" instead of "unknown"."""
    base = httputil.route_base(path)
    hit = httputil.WIRE_CONTRACT.get((method, base))
    if hit is not None:
        return (method, base), hit
    for (m, p), row in httputil.WIRE_CONTRACT.items():
        if row.get("prefix") and base.startswith(p):
            if m == method:
                return (m, p), row
    other = {m for (m, p), row in httputil.WIRE_CONTRACT.items()
             if p == base or (row.get("prefix") and base.startswith(p))}
    return None, other


class _FileWire:
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.relpath = repo_relative(path)
        self.source = source
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.disabled: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = DISABLE_RE.search(line)
            if m:
                self.disabled[i] = {r.strip() for r in m.group(1).split(",")}

    # -- shared emit machinery (the concurrency pass's shape) ----------------

    def _scopes_for_line(self, line: int) -> list[ast.AST]:
        return [
            s for s in self._all_scopes
            if s.lineno <= line <= getattr(s, "end_lineno", s.lineno)
        ]

    def _suppressed(self, rule: str, line: int) -> bool:
        if rule in self.disabled.get(line, ()):
            return True
        for scope in self._scopes_for_line(line):
            if rule in self.disabled.get(scope.lineno, ()):
                return True
        return False

    def _context_for_line(self, line: int) -> str:
        best = ""
        for s in self._scopes_for_line(line):
            best = s.name if not best else f"{best}.{s.name}"
        return best

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(rule, line):
            return
        self.findings.append(
            Finding(
                rule=rule,
                severity=WIRE_RULES[rule]["severity"],
                path=self.relpath,
                line=line,
                message=message,
                context=self._context_for_line(line),
                line_text=(self.lines[line - 1].strip()
                           if line <= len(self.lines) else ""),
            )
        )

    def _enclosing_fn(self, line: int):
        fns = [s for s in self._scopes_for_line(line)
               if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))]
        return fns[-1] if fns else None

    def _fn_text(self, fn: ast.AST) -> str:
        end = getattr(fn, "end_lineno", fn.lineno)
        return "\n".join(self.lines[fn.lineno - 1:end])

    # -- driver --------------------------------------------------------------

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError:
            return []  # edgelint already reports EM000 for this file
        self.tree = tree
        self.aliases = _EdgelintAliases()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                self.aliases.visit_import(node)
            elif isinstance(node, ast.ImportFrom):
                self.aliases.visit_import_from(node)
        self._all_scopes = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))
        ]
        self._functions: dict[str, list[ast.AST]] = {}
        for n in self._all_scopes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._functions.setdefault(n.name, []).append(n)

        in_client_dirs = any(d in self.relpath for d in WIRE_CLIENT_DIRS)
        self._check_client_calls(tree, in_client_dirs)
        if in_client_dirs:
            self._check_dial_timeouts(tree)
        self._check_response_discipline(tree)

        server = next(
            (name for name, f in WIRE_SERVERS.items() if f in self.relpath),
            None,
        )
        if server is not None:
            self._check_handlers(server)

        self._check_schemas(tree)

        seen: set[tuple] = set()
        unique: list[Finding] = []
        for f in sorted(self.findings, key=lambda f: (f.line, f.rule)):
            key = (f.rule, f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        return self.findings

    # -- client side: EM501, EM502, EM503 ------------------------------------

    def _resolve_path_expr(self, expr: ast.AST, call_line: int,
                           depth: int = 0) -> str | None:
        """Best-effort request path of a URL expression (see module
        docstring: literal, trailing f-string constant, concatenation,
        ``rep.url("/path")``, httputil path constant, one level of local
        assignment)."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return _path_from_string(expr.value)
        if isinstance(expr, ast.JoinedStr):
            consts = [v.value for v in expr.values
                      if isinstance(v, ast.Constant)
                      and isinstance(v.value, str) and "/" in v.value]
            if consts:
                last = consts[-1]
                return last[last.index("/"):]
            return None
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            right = self._resolve_path_expr(expr.right, call_line, depth)
            if right is not None:
                return right
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            dotted = _dotted(expr)
            if dotted:
                tail = dotted.rsplit(".", 1)[-1]
                if tail in PATH_CONSTS:
                    return PATH_CONSTS[tail]
            if isinstance(expr, ast.Name) and depth < 2:
                fn = self._enclosing_fn(call_line)
                if fn is None:
                    return None
                best = None
                for sub in ast.walk(fn):
                    if (isinstance(sub, ast.Assign)
                            and sub.lineno < call_line
                            and any(isinstance(t, ast.Name)
                                    and t.id == expr.id
                                    for t in sub.targets)):
                        best = sub.value  # last assignment before the call
                if best is not None:
                    return self._resolve_path_expr(best, call_line, depth + 1)
            return None
        if isinstance(expr, ast.Call):
            # ``rep.url("/drain")`` / ``rep.url(KV_EXPORT_PATH)``: any call
            # whose first argument resolves to a path.
            if expr.args:
                return self._resolve_path_expr(expr.args[0], call_line,
                                               depth + 1)
            return None
        return None

    def _classify_transport_call(self, node: ast.Call):
        """(method, url_expr, payload_expr) for a recognized outbound HTTP
        call, else None."""
        if isinstance(node.func, ast.Attribute):
            verb = _TRANSPORT_CALLS.get(node.func.attr)
            if verb is not None and node.args:
                payload = node.args[1] if (verb == "POST"
                                           and len(node.args) > 1) else None
                return verb, node.args[0], payload
            if node.func.attr == "request" and len(node.args) >= 2:
                m = node.args[0]
                if isinstance(m, ast.Constant) and isinstance(m.value, str):
                    return m.value.upper(), node.args[1], None
        dotted = _dotted(node.func)
        if dotted and self.aliases.resolve(dotted) == _URLOPEN:
            has_data = len(node.args) > 1 or any(
                kw.arg == "data" for kw in node.keywords)
            if node.args:
                return ("POST" if has_data else "GET"), node.args[0], None
        return None

    def _headers_dict_for_call(self, node: ast.Call) -> ast.Dict | None:
        """The headers dict literal this call passes, following one level
        of simple local assignment — same visibility contract the retired
        EM109 had."""
        value = next(
            (kw.value for kw in node.keywords if kw.arg == "headers"), None
        )
        if value is None:
            return None
        if isinstance(value, ast.Dict):
            return value
        if isinstance(value, ast.Name):
            fn = self._enclosing_fn(node.lineno)
            if fn is None:
                return None
            best = None
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Assign)
                        and sub.lineno < node.lineno
                        and isinstance(sub.value, ast.Dict)
                        and any(isinstance(t, ast.Name) and t.id == value.id
                                for t in sub.targets)):
                    best = sub.value  # last assignment before the call wins
            return best
        return None

    @staticmethod
    def _dict_has_header(d: ast.Dict, literal: str, const_name: str) -> bool:
        for key in d.keys:
            if key is None:  # {**expansion}: assume the source forwards it
                return True
            if isinstance(key, ast.Constant) and key.value == literal:
                return True
            if isinstance(key, (ast.Name, ast.Attribute)):
                dotted = _dotted(key)
                if dotted and dotted.rsplit(".", 1)[-1] == const_name:
                    return True
        return False

    def _check_client_calls(self, tree: ast.Module,
                            in_client_dirs: bool) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            hit = self._classify_transport_call(node)
            if hit is None:
                continue
            method, url_expr, payload_expr = hit
            path = self._resolve_path_expr(url_expr, node.lineno)
            if path is None:
                continue  # opaque URL: out of static reach
            key, row = _contract_route(method, path)
            if key is None:
                served_as = row  # methods that do serve the path
                if served_as:
                    self._emit(
                        "EM501", node,
                        f"{httputil.route_base(path)!r} is served as "
                        f"{'/'.join(sorted(served_as))}, not {method} — "
                        "this call can only 404/405 (httputil.WIRE_CONTRACT)",
                    )
                else:
                    self._emit(
                        "EM501", node,
                        f"{method} {httputil.route_base(path)!r} matches no "
                        "route in httputil.WIRE_CONTRACT — declare the "
                        "route (and serve it) or fix the path",
                    )
                continue
            if in_client_dirs:
                self._check_client_headers(node, key, row)
            if payload_expr is not None:
                self._check_client_payload(node, payload_expr, key, row)

    def _check_client_headers(self, node: ast.Call, key, row: dict) -> None:
        required = row.get("required_headers", ())
        if not required:
            return
        has_kwarg = any(kw.arg == "headers" for kw in node.keywords)
        headers = self._headers_dict_for_call(node)
        route = f"{key[0]} {key[1]}"
        if headers is None:
            if has_kwarg:
                return  # opaque headers variable: trusted, like EM109 did
            if row.get("strict_headers"):
                self._emit(
                    "EM502", node,
                    f"{route} call sends no headers mapping — the contract "
                    f"marks this route strict: every hop must carry "
                    f"{', '.join(repr(h) for h in required)} "
                    "(trace continuity + the router's budget math)",
                )
            return
        for header in required:
            if not self._dict_has_header(headers, header,
                                         HEADER_CONSTS.get(header, header)):
                self._emit(
                    "EM502", node,
                    f"{route} call builds headers without {header!r} — "
                    "required by its httputil.WIRE_CONTRACT row (add "
                    f"httputil.{HEADER_CONSTS.get(header, header)}, or "
                    "forward the incoming headers)",
                )

    def _check_client_payload(self, node: ast.Call, payload_expr: ast.AST,
                              key, row: dict) -> None:
        d = payload_expr
        if isinstance(d, ast.Name):
            fn = self._enclosing_fn(node.lineno)
            if fn is None:
                return
            best = None
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Assign)
                        and sub.lineno < node.lineno
                        and isinstance(sub.value, ast.Dict)
                        and any(isinstance(t, ast.Name) and t.id == d.id
                                for t in sub.targets)):
                    best = sub.value
            d = best
        if not isinstance(d, ast.Dict):
            return  # opaque payload: out of static reach
        declared = set(row.get("request_keys", ()))
        for k in d.keys:
            if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and k.value not in declared):
                self._emit(
                    "EM503", node,
                    f"payload key {k.value!r} POSTed to {key[1]} is not in "
                    "the route's declared request_keys "
                    f"({sorted(declared) or 'none'}) — the handler will "
                    "never read it (httputil.WIRE_CONTRACT)",
                )

    def _check_dial_timeouts(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if not dotted:
                continue
            resolved = self.aliases.resolve(dotted)
            if resolved not in _DIAL_CALLS:
                continue
            pos = _DIAL_CALLS[resolved]
            has_timeout = any(kw.arg == "timeout" for kw in node.keywords) or (
                pos is not None and len(node.args) > pos
            )
            if not has_timeout:
                self._emit(
                    "EM502", node,
                    f"outbound {resolved}() without an explicit timeout — a "
                    "stalled replica pins this fleet thread forever and the "
                    "router's retry/hedge budget math breaks (pass "
                    "timeout=..., or route through fleet.transport)",
                )

    # -- EM505: response discipline ------------------------------------------

    def _check_response_discipline(self, tree: ast.Module) -> None:
        # Server half: 5xx answered with a dict literal lacking "kind".
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            code_arg = payload_arg = None
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_send" and len(node.args) >= 2):
                code_arg, payload_arg = node.args[0], node.args[1]
            else:
                dotted = _dotted(node.func)
                if (dotted and dotted.rsplit(".", 1)[-1] == "send_json"
                        and len(node.args) >= 3):
                    code_arg, payload_arg = node.args[1], node.args[2]
            if not (isinstance(code_arg, ast.Constant)
                    and isinstance(code_arg.value, int)
                    and code_arg.value >= 500):
                continue
            if not isinstance(payload_arg, ast.Dict):
                continue
            if any(isinstance(k, ast.Constant) and k.value == "kind"
                   for k in payload_arg.keys):
                continue
            self._emit(
                "EM505", node,
                f"bare {code_arg.value} without the structured error "
                "vocabulary — add a \"kind\" field (e.g. \"internal\", "
                "\"kv_wire\") so clients can branch on failure class "
                "instead of parsing messages",
            )
        # Client half: a function that dials out and branches on 503 must
        # mention Retry-After somewhere (the shed contract carries it).
        for fn in self._functions_with_transport_calls(tree):
            text = self._fn_text(fn)
            if "Retry-After" in text or "RETRY_AFTER" in text:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Constant) and node.value == 503:
                    self._emit(
                        "EM505", node,
                        "this function treats 503 responses but never "
                        "honors Retry-After — shed replies always carry it "
                        "(httputil.RETRY_AFTER_HEADER); back off by it "
                        "before retrying",
                    )
                    break

    def _functions_with_transport_calls(self, tree: ast.Module):
        for fn in self._all_scopes:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and self._classify_transport_call(node) is not None):
                    yield fn
                    break

    # -- handler side: EM502 + EM503 on the server files ---------------------

    def _called_names(self, fn: ast.AST) -> set[str]:
        names: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                names.add(node.func.attr)
            elif isinstance(node.func, ast.Name):
                names.add(node.func.id)
        return names

    def _dispatch_closure(self, roots: list[ast.AST]) -> list[ast.AST]:
        """roots + every file-local function reachable through self-calls
        and bare calls — the concurrency pass's descent, flattened."""
        closure: list[ast.AST] = []
        seen: set[int] = set()
        stack = list(roots)
        while stack:
            fn = stack.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            closure.append(fn)
            for name in self._called_names(fn):
                stack.extend(self._functions.get(name, ()))
        return closure

    def _fns_with_path_literal(self, path: str) -> list[ast.AST]:
        out = []
        for fns in self._functions.values():
            for fn in fns:
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Constant)
                            and node.value == path):
                        out.append(fn)
                        break
                    if isinstance(node, (ast.Name, ast.Attribute)):
                        dotted = _dotted(node)
                        tail = dotted.rsplit(".", 1)[-1] if dotted else ""
                        if PATH_CONSTS.get(tail) == path:
                            out.append(fn)
                            break
        return out

    def _check_handlers(self, server: str) -> None:
        rows = [(key, row) for key, row in httputil.WIRE_CONTRACT.items()
                if server in row.get("servers", ())]
        all_dispatch: list[ast.AST] = []
        for (method, path), row in rows:
            roots = self._fns_with_path_literal(path)
            if not roots:
                continue  # declared-but-unserved is the dryrun's call (EM506)
            closure = self._dispatch_closure(roots)
            all_dispatch.extend(roots)
            helpers_called = set()
            for fn in closure:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        dotted = _dotted(node.func)
                        if dotted:
                            helpers_called.add(dotted.rsplit(".", 1)[-1])
            for header in (tuple(row.get("required_headers", ()))
                           + tuple(row.get("forwarded_headers", ()))):
                helper = READ_HELPERS.get(header)
                if helper and helper not in helpers_called:
                    self._emit(
                        "EM502", roots[0],
                        f"handler for {method} {path} never reads "
                        f"{header!r} — the contract requires "
                        f"httputil.{helper}() somewhere in its dispatch "
                        "path (propagation severs at this server)",
                    )
        # EM503 handler half: every body read must be a declared key.
        declared_keys = set()
        for _key, row in rows:
            declared_keys |= set(row.get("request_keys", ()))
        for fn in self._dispatch_closure(all_dispatch):
            self._check_handler_payload_reads(fn, declared_keys)

    def _payload_names(self, fn: ast.AST) -> set[str]:
        """Local names bound to a parsed request body in this function: a
        parameter literally named ``payload``, or a local assigned from
        ``self._read_json()`` / ``read_json_body(...)``."""
        names = {a.arg for a in fn.args.args if a.arg == "payload"}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            dotted = _dotted(node.value.func)
            tail = dotted.rsplit(".", 1)[-1] if dotted else ""
            if tail in ("_read_json", "read_json_body"):
                names.update(t.id for t in node.targets
                             if isinstance(t, ast.Name))
        return names

    def _check_handler_payload_reads(self, fn: ast.AST,
                                     declared: set[str]) -> None:
        names = self._payload_names(fn)
        if not names:
            return
        for node in ast.walk(fn):
            key = None
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in names
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                key = node.args[0].value
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in names
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                key = node.slice.value
            if key is not None and key not in declared:
                self._emit(
                    "EM503", node,
                    f"handler reads payload key {key!r} that no declared "
                    "route for this server carries — a typo here "
                    "silently .get()-defaults forever "
                    "(httputil.WIRE_CONTRACT request_keys)",
                )

    # -- EM504: schema producer/consumer drift -------------------------------

    def _check_schemas(self, tree: ast.Module) -> None:
        for schema, spec in WIRE_SCHEMAS.items():
            for entry in spec["consumers"]:
                relpath, func, seeds = entry
                if relpath not in self.relpath:
                    continue
                produced = _schema_produced_keys(schema)
                if produced is None:
                    continue  # no producer readable: stay silent, not wrong
                for fn in self._functions.get(func, ()):
                    self._check_consumer_fn(fn, schema, set(seeds),
                                            produced, spec)

    def _check_consumer_fn(self, fn: ast.AST, schema: str, seeds: set[str],
                           produced: frozenset, spec: dict) -> None:
        derived = set(seeds)

        def derives(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in derived
            if isinstance(expr, ast.Subscript):
                return derives(expr.value)
            if isinstance(expr, ast.Call):
                f = expr.func
                if isinstance(f, ast.Attribute) and f.attr in (
                        "get", "items", "values", "pop", "setdefault"):
                    return derives(f.value)
                return False
            if isinstance(expr, ast.BoolOp):
                return any(derives(v) for v in expr.values)
            if isinstance(expr, ast.IfExp):
                return derives(expr.body) or derives(expr.orelse)
            return False

        def bind(target: ast.AST) -> None:
            if isinstance(target, ast.Name):
                derived.add(target.id)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for el in target.elts:
                    bind(el)

        # Fixed point: derivation flows through rebinding and loop targets
        # in any statement order.
        for _ in range(4):
            before = len(derived)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and derives(node.value):
                    for t in node.targets:
                        bind(t)
                elif isinstance(node, ast.For) and derives(node.iter):
                    bind(node.target)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for gen in node.generators:
                        if derives(gen.iter):
                            bind(gen.target)
            if len(derived) == before:
                break

        for node in ast.walk(fn):
            key = None
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and derives(node.func.value)):
                key = node.args[0].value
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and derives(node.value)):
                key = node.slice.value
            if key is not None and key not in produced:
                producers = ", ".join(
                    f"{f}:{fname}" for f, fname in spec["producers"])
                self._emit(
                    "EM504", node,
                    f"reads {key!r} from the {schema!r} schema, but no "
                    f"registered producer writes it ({producers}) — "
                    "drifted key or dead read (analysis/wire.py "
                    "WIRE_SCHEMAS)",
                )


def analyze_source(source: str, path: str = "<memory>") -> list[Finding]:
    """Wire-pass entry point (mirrors edgelint.lint_source)."""
    return _FileWire(path, source).run()


# ---------------------------------------------------------------------------
# Layer 2: the wire dryrun (EM506)
# ---------------------------------------------------------------------------
#
# Same shape as the sharding pass's EM405 AbstractMesh dryrun: a registry
# of contracts, each checked by importing the LIVE artifact and
# cross-checking it against the static declaration. Both server modules
# are stdlib-only at import time (no accelerator, no sockets), so this
# runs in the fast tier — and even under --no-contracts.

WIRE_CONTRACTS: list[dict] = [
    {
        "server": "gateway",
        "module": "edgemesh.serve.rest",
        "table": "SERVED_ROUTES",
        "path": "edgemesh/serve/rest.py",
    },
    {
        "server": "frontend",
        "module": "edgemesh.fleet.frontend",
        "table": "SERVED_ROUTES",
        "path": "edgemesh/fleet/frontend.py",
    },
]


def _declared_routes(server: str) -> dict[str, set[str]]:
    declared: dict[str, set[str]] = {}
    for (method, path), row in httputil.WIRE_CONTRACT.items():
        if server in row.get("servers", ()):
            declared.setdefault(method, set()).add(path)
    return declared


def _check_wire_contract(entry: dict) -> list[Finding]:
    import importlib

    server, relpath = entry["server"], entry["path"]
    findings: list[Finding] = []

    def fail(msg: str) -> None:
        findings.append(Finding(
            rule="EM506",
            severity=WIRE_CONTRACT_RULES["EM506"]["severity"],
            path=relpath,
            line=1,
            message=f"wire contract {server!r}: {msg}",
            context=server,
        ))

    try:
        mod = importlib.import_module(entry["module"])
        served_table = getattr(mod, entry.get("table", "SERVED_ROUTES"))
        served = {m: set(paths) for m, paths in served_table.items()}
    except Exception as exc:  # the exception IS the finding, like EM405
        fail(f"dispatch table unimportable: {type(exc).__name__}: {exc}")
        return findings

    declared = _declared_routes(server)
    for method in sorted(set(served) | set(declared)):
        s = served.get(method, set())
        d = declared.get(method, set())
        for p in sorted(s - d):
            others = sorted(m for m, paths in declared.items()
                            if p in paths and m != method)
            if others:
                fail(f"{method} {p} is served but WIRE_CONTRACT declares it "
                     f"under {'/'.join(others)} — method mismatch")
                for m in others:
                    declared[m].discard(p)  # consumed: not also "unserved"
            else:
                fail(f"{method} {p} is served but undeclared — add its "
                     "httputil.WIRE_CONTRACT row")
        for p in sorted(d - s):
            others = sorted(m for m, paths in served.items()
                            if p in paths and m != method)
            if not others:  # method mismatch already reported above
                fail(f"{method} {p} is declared but this server never "
                     "serves it — dead contract row or missing handler")
    return findings


def run_wire_contracts(contracts: list[dict] | None = None) -> list[Finding]:
    """Cross-check every registered server dispatch table against
    ``httputil.WIRE_CONTRACT``. Seconds, no sockets, no accelerator."""
    findings: list[Finding] = []
    for entry in (WIRE_CONTRACTS if contracts is None else contracts):
        findings.extend(_check_wire_contract(entry))
    return findings
