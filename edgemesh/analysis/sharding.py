"""Static sharding & collective-correctness analysis for the parallel stack.

Two layers over the ``shard_map``/collective code in ``parallel/`` (and the
host→jit seams in ``serve/``/``runtime/``), catching the bug class that
otherwise surfaces only in scarce hardware tunnel windows:

**Layer 1 — AST rules (EM401–EM404)**, riding the standard edgelint entry
points (``lint_source``/``lint_file``, baseline, inline disables):

- **EM401 unbound-collective-axis** (error): a collective
  (``lax.psum``/``pmean``/``all_gather``/``ppermute``/``all_to_all``/
  ``axis_index``/…, or ``compat.axis_size``/``compat.pcast``) naming a mesh
  axis that the enclosing ``shard_map`` call site does not bind. The axis
  environment is taken from the mesh construction when it is visible
  (``Mesh(devs, ("sp",))``, ``build_mesh(...)``, ``AbstractMesh(...)``);
  when the mesh is opaque but every ``in_specs``/``out_specs`` entry is a
  literal ``P(...)``, the union of spec axes stands in for it (an axis a
  body reduces over should appear in the specs or a visible mesh — if a
  wider opaque mesh really binds more, carry an inline disable). Bodies are
  resolved through locals, module-level defs, and factory functions
  (``fn = _make_stage(...); shard_map(fn, ...)``), and the walk descends
  into called helpers binding constant-string axis parameters
  (``ring_attend_block(..., axis="sp")``) — the same descent trick
  ``concurrency.py`` uses for self-calls.
- **EM402 shard-spec-mismatch** (error): ``in_specs`` arity vs the body's
  positional parameters AND vs the visible call sites of the mapped
  function (the tp_infer pytree-mirroring trap: a specs tuple whose
  structure visibly diverges from the arguments built in the same scope);
  ``out_specs`` tuple arity vs the body's returned tuple; and any literal
  ``P(...)`` axis name absent from a visible mesh construction's axis
  names. A single (non-tuple) out spec is a valid pytree prefix and is
  never an arity finding.
- **EM403 unreduced-sharded-contraction** (error): the body contracts
  (``@``/``jnp.dot``/``jnp.matmul``/``jnp.einsum``/``lax.dot_general``)
  over a dimension ``in_specs`` marks sharded on axis A, then returns the
  (partial) result without a ``psum(..., A)`` on the path while
  ``out_specs`` claims it replicated over A — silent wrong numbers on
  every chip. ``check_vma=False`` call sites are called out in the
  message: with the replication checker off, nothing at trace time would
  have caught it either.
- **EM404 retrace-hazard** (warning): a host-computed int (``len(...)``,
  ``.shape[i]`` arithmetic) flowing into a jitted call's arguments in
  ``serve/``/``runtime/`` without passing through the blessed bucketing
  vocabulary (``utils/bucketing.bucket_pow2`` — the ``s_cap`` pow2 ladder
  the continuous engine converged on). Raw host sizes as static/jit args
  mint one compiled program per distinct value; the engine pays the
  retrace exactly when it is busiest.

**Layer 2 — AbstractMesh dryrun contracts (EM405)**, the semantic
companion in the style of ``analysis/contracts.py``: every public
shard_map wrapper (tp_infer, ring_attention, ulysses, pipeline, spmd) is
registered in ``SHARDING_CONTRACTS`` and traced under
``jax.sharding.AbstractMesh`` layouts (tp2 / tp8 / dp2×tp4 / pp2 / sp2 /
the 4D training mesh) via ``jax.eval_shape`` — no devices, sub-second on
CPU — so "does tp8 even trace" is a fast-tier test, not a tunnel-window
discovery. A failure names the wrapper AND the layout.

Suppression and baselining are the standard edgelint mechanics
(``# edgelint: disable=EM401``, fingerprint baseline). See
docs/ANALYSIS.md for the full rule table and the dryrun workflow.
"""

from __future__ import annotations

import ast

from edgemesh.analysis.edgelint import _Aliases as _EdgelintAliases
from edgemesh.analysis.edgelint import _dotted_name as _dotted
from edgemesh.analysis.edgelint import _is_jit_expr
from edgemesh.analysis.findings import DISABLE_RE, Finding, repo_relative

RULES: dict[str, dict] = {
    "EM401": {
        "name": "unbound-collective-axis",
        "severity": "error",
        "summary": "collective names a mesh axis the enclosing shard_map does not bind",
    },
    "EM402": {
        "name": "shard-spec-mismatch",
        "severity": "error",
        "summary": "in_specs/out_specs arity or axis names diverge from body/mesh/call site",
    },
    "EM403": {
        "name": "unreduced-sharded-contraction",
        "severity": "error",
        "summary": "sharded contraction returned without psum while out_specs claims replication",
    },
    "EM404": {
        "name": "retrace-hazard",
        "severity": "warning",
        "summary": "host-computed size flows into a jitted call without blessed bucketing",
    },
}

#: Layer-2 rule (reported by run_sharding_contracts, not the AST walk).
SHARDING_CONTRACT_RULES: dict[str, dict] = {
    "EM405": {
        "name": "sharding-dryrun-failure",
        "severity": "error",
        "summary": "registered shard_map wrapper fails its AbstractMesh layout dryrun",
    },
}

# ---------------------------------------------------------------------------
# Tables
# ---------------------------------------------------------------------------

#: Collectives that take an axis name, mapped to (positional index, kwarg
#: name) of that argument. Keyed by the LAST component; accepted only when
#: the resolved dotted path sits under jax.lax or edgemesh.utils.compat
#: (or is a bare import of one of those names).
_COLLECTIVES: dict[str, tuple[int, str]] = {
    "psum": (1, "axis_name"),
    "pmean": (1, "axis_name"),
    "pmax": (1, "axis_name"),
    "pmin": (1, "axis_name"),
    "psum_scatter": (1, "axis_name"),
    "all_gather": (1, "axis_name"),
    "ppermute": (1, "axis_name"),
    "pshuffle": (1, "axis_name"),
    "all_to_all": (1, "axis_name"),
    "axis_index": (0, "axis_name"),
    "axis_size": (0, "axis_name"),  # compat shim
    "pcast": (1, "axis_name"),      # compat shim
    "qpsum": (1, "axis_name"),      # quantized all-reduce (parallel/collectives)
}

_COLLECTIVE_HOMES = (
    "jax.lax.", "edgemesh.utils.compat.", "edgemesh.parallel.collectives.",
)
#: Bare-name fallback for the compat/collectives helpers (their only
#: legitimate homes are those modules; fixtures import them by name).
_COMPAT_BARE = {"axis_size", "pcast", "qpsum"}

#: Collectives that REDUCE over the axis (clear EM403 partial-ness).
_REDUCERS = {"psum", "pmean", "pmax", "pmin", "psum_scatter", "qpsum"}

#: The five canonical mesh axes (parallel/mesh.py AXES) — what
#: build_mesh/auto_mesh always bind.
_MESH_AXES = ("dp", "pp", "sp", "ep", "tp")

# EM404 scope + surfaces (mirrors EM110's jitted-name discovery).
_EM404_DIRS = ("edgemesh/serve/", "edgemesh/runtime/")
_EM404_IMPORT_PREFIXES = ("forward_", "generate")
_EM404_IMPORT_EXTRA = {"_decode_loop", "_spec_rounds"}
#: Blessed host→jit size sanitizers (utils/bucketing.py).
_BLESSED_BUCKETING = {"bucket_pow2"}
#: Host calls whose result is tainted iff any argument is.
_TAINT_THROUGH = {"max", "min", "sum", "int", "round", "abs"}

_DESCENT_DEPTH = 4  # callee-descent limit for EM401


# ---------------------------------------------------------------------------
# The per-file pass
# ---------------------------------------------------------------------------


class _FileSharding:
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.relpath = repo_relative(path)
        self.source = source
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.disabled: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = DISABLE_RE.search(line)
            if m:
                self.disabled[i] = {r.strip() for r in m.group(1).split(",")}

    # -- shared emit machinery (same contract as concurrency.py) ------------

    def _scopes_for_line(self, line: int) -> list[ast.AST]:
        return [
            s for s in self._all_scopes
            if s.lineno <= line <= getattr(s, "end_lineno", s.lineno)
        ]

    def _suppressed(self, rule: str, line: int) -> bool:
        if rule in self.disabled.get(line, ()):
            return True
        for scope in self._scopes_for_line(line):
            if rule in self.disabled.get(scope.lineno, ()):
                return True
        return False

    def _context_for_line(self, line: int) -> str:
        best = ""
        for s in self._scopes_for_line(line):
            best = s.name if not best else f"{best}.{s.name}"
        return best

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(rule, line):
            return
        self.findings.append(
            Finding(
                rule=rule,
                severity=RULES[rule]["severity"],
                path=self.relpath,
                line=line,
                message=message,
                context=self._context_for_line(line),
                line_text=(self.lines[line - 1].strip() if line <= len(self.lines) else ""),
            )
        )

    # -- driver --------------------------------------------------------------

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError:
            return []  # edgelint already reports EM000 for this file
        self.tree = tree
        self.aliases = _EdgelintAliases()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                self.aliases.visit_import(node)
            elif isinstance(node, ast.ImportFrom):
                self.aliases.visit_import_from(node)
        self._all_scopes = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
        ]
        self._all_defs = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node

        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and self._is_shard_map(node):
                self._check_site(node)
        self._rule_retrace(tree)

        seen: set[tuple] = set()
        unique: list[Finding] = []
        for f in sorted(self.findings, key=lambda f: (f.line, f.rule)):
            key = (f.rule, f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        return self.findings

    # -- resolution helpers --------------------------------------------------

    def _is_shard_map(self, node: ast.Call) -> bool:
        d = _dotted(node.func)
        if not d:
            return False
        resolved = self.aliases.resolve(d)
        return resolved.rsplit(".", 1)[-1] == "shard_map"

    def _enclosing_fn(self, line: int):
        fns = [
            s for s in self._scopes_for_line(line)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        return fns[-1] if fns else None

    def _local_assign_value(self, name: str, line: int) -> ast.AST | None:
        """Latest ``name = <value>`` before ``line`` in the innermost
        enclosing function chain (outer scopes searched when the innermost
        has no binding — the make_spmd_loss closure pattern)."""
        fns = [
            s for s in self._scopes_for_line(line)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for fn in reversed(fns):
            best, best_line = None, -1
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Assign)
                    and best_line < sub.lineno < line
                    and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in sub.targets
                    )
                ):
                    best, best_line = sub.value, sub.lineno
            if best is not None:
                return best
        return None

    def _deref(self, expr: ast.AST, line: int, depth: int = 0) -> ast.AST:
        if depth < 4 and isinstance(expr, ast.Name):
            v = self._local_assign_value(expr.id, line)
            if v is not None:
                return self._deref(v, line, depth + 1)
        return expr

    def _find_def(self, name: str, near_line: int | None = None):
        """The def ``name`` resolves to: the innermost one enclosing
        ``near_line`` if any, else a module-level (un-nested) one."""
        candidates = [d for d in self._all_defs if d.name == name]
        if not candidates:
            return None
        if near_line is not None:
            local = [
                d for d in candidates
                if any(
                    s is not d and d.lineno <= getattr(s, "end_lineno", s.lineno)
                    and s.lineno <= d.lineno
                    for s in self._scopes_for_line(near_line)
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                )
            ]
            if local:
                return local[-1]
        toplevel = [
            d for d in candidates
            if not any(
                p is not d and isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef))
                and p.lineno <= d.lineno <= getattr(p, "end_lineno", p.lineno)
                for p in self._all_defs
            )
        ]
        return toplevel[0] if toplevel else candidates[0]

    def _resolve_body(self, expr: ast.AST, line: int, depth: int = 0):
        """The function def (or Lambda) a shard_map body expression names —
        resolved through locals, module-level defs, and one factory hop
        (``fn = _make_stage(...)`` where ``_make_stage`` returns an inner
        def)."""
        if depth > 3:
            return None
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Name):
            d = self._find_def(expr.id, near_line=line)
            if d is not None:
                return d
            v = self._local_assign_value(expr.id, line)
            if v is not None:
                return self._resolve_body(v, line, depth + 1)
            return None
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
            factory = self._find_def(expr.func.id, near_line=line)
            if factory is None:
                return None
            inner = {
                n.name: n
                for n in ast.walk(factory)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not factory
            }
            for node in _own_statements(factory):
                if isinstance(node, ast.Return) and node.value is not None:
                    if isinstance(node.value, ast.Name) and node.value.id in inner:
                        return inner[node.value.id]
                    if isinstance(node.value, ast.Lambda):
                        return node.value
        return None

    # -- mesh / spec parsing -------------------------------------------------

    def _mesh_env(self, expr: ast.AST | None, line: int) -> tuple[set[str], bool]:
        """(axis names, known) for the ``mesh=`` expression. Known only when
        a construction with literal axis names is visible."""
        if expr is None:
            return set(), False
        e = self._deref(expr, line)
        if not isinstance(e, ast.Call):
            return set(), False
        d = _dotted(e.func)
        if not d:
            return set(), False
        last = self.aliases.resolve(d).rsplit(".", 1)[-1]
        if last in ("build_mesh", "auto_mesh"):
            return set(_MESH_AXES), True
        if last == "Mesh":
            names_arg = e.args[1] if len(e.args) >= 2 else next(
                (kw.value for kw in e.keywords if kw.arg == "axis_names"), None
            )
            names = _str_constants(names_arg)
            if names is not None:
                return names, True
            return set(), False
        if last == "AbstractMesh":
            # shape_tuple form: (("dp", 2), ("tp", 4)) — every string
            # constant inside it is an axis name.
            if e.args:
                names = {
                    n.value for n in ast.walk(e.args[0])
                    if isinstance(n, ast.Constant) and isinstance(n.value, str)
                }
                return names, True
        return set(), False

    def _parse_specs(self, expr: ast.AST | None, line: int):
        """Returns (axes, literal, node) where node is ('P', entries) for a
        single spec (entries: None | str | tuple[str, ...] | '?'),
        ('seq', [nodes]) for a tuple/list of specs, or ('opaque',).
        ``literal`` means every entry everywhere was resolvable."""
        if expr is None:
            return set(), False, ("opaque",)
        e = self._deref(expr, line)
        if isinstance(e, ast.Constant) and e.value is None:
            return set(), True, ("P", [])
        if isinstance(e, ast.Call):
            d = _dotted(e.func)
            last = self.aliases.resolve(d).rsplit(".", 1)[-1] if d else ""
            if last in ("P", "PartitionSpec"):
                axes: set[str] = set()
                entries: list = []
                literal = not e.keywords
                for a in e.args:
                    if isinstance(a, ast.Constant) and a.value is None:
                        entries.append(None)
                    elif isinstance(a, ast.Constant) and isinstance(a.value, str):
                        entries.append(a.value)
                        axes.add(a.value)
                    elif isinstance(a, (ast.Tuple, ast.List)):
                        names = _str_constants(a)
                        if names is None:
                            entries.append("?")
                            literal = False
                        else:
                            entries.append(tuple(sorted(names)))
                            axes.update(names)
                    elif isinstance(a, ast.Starred):
                        entries.append("?")
                        literal = False
                    else:
                        entries.append("?")
                        literal = False
                return axes, literal, ("P", entries)
            return set(), False, ("opaque",)
        if isinstance(e, (ast.Tuple, ast.List)):
            axes_all: set[str] = set()
            literal_all = True
            children = []
            for el in e.elts:
                ax, lit, node = self._parse_specs(el, line)
                axes_all |= ax
                literal_all = literal_all and lit
                children.append(node)
            return axes_all, literal_all, ("seq", children)
        return set(), False, ("opaque",)

    # -- site checking -------------------------------------------------------

    def _check_site(self, site: ast.Call) -> None:
        body_expr = _call_arg(site, 0, "f")
        mesh_expr = _call_arg(site, 1, "mesh")
        in_expr = _call_arg(site, 2, "in_specs")
        out_expr = _call_arg(site, 3, "out_specs")
        vma_expr = _call_arg(site, 4, "check_vma")
        vma_off = (
            isinstance(vma_expr, ast.Constant) and vma_expr.value is False
        )
        line = site.lineno

        mesh_axes, mesh_known = self._mesh_env(mesh_expr, line)
        in_axes, in_lit, in_node = self._parse_specs(in_expr, line)
        out_axes, out_lit, out_node = self._parse_specs(out_expr, line)
        body = (
            self._resolve_body(body_expr, line) if body_expr is not None else None
        )

        # EM402: spec axis names vs a visible mesh construction.
        if mesh_known:
            for ax in sorted((in_axes | out_axes) - mesh_axes):
                self._emit(
                    "EM402", site,
                    f"spec axis {ax!r} is not an axis of this shard_map's "
                    f"mesh (mesh binds: {', '.join(sorted(mesh_axes)) or 'nothing'})"
                    " — the program fails at trace time on every layout",
                )

        # EM402: in_specs arity vs body params and vs visible call sites.
        if in_node[0] == "seq":
            n_in = len(in_node[1])
            bounds = _positional_param_bounds(body)
            if bounds is not None and not (bounds[0] <= n_in <= bounds[1]):
                required, total = bounds
                takes = (
                    f"{total}" if required == total
                    else f"{required} to {total}"
                )
                self._emit(
                    "EM402", site,
                    f"in_specs carries {n_in} spec(s) but the body takes "
                    f"{takes} positional parameter(s) — shard_map requires "
                    "one spec per argument (specs are per-arg pytree prefixes)",
                )
            n_call = self._mapped_call_argcount(site)
            if n_call is not None and n_call != n_in:
                self._emit(
                    "EM402", site,
                    f"in_specs carries {n_in} spec(s) but the mapped function "
                    f"is called with {n_call} argument(s) in this scope — the "
                    "specs tuple visibly diverges from the arguments it must "
                    "mirror",
                )

        # EM402: out_specs tuple arity vs the body's returned tuple.
        if out_node[0] == "seq" and body is not None and not isinstance(body, ast.Lambda):
            n_out = len(out_node[1])
            for node in _own_statements(body):
                if isinstance(node, ast.Return) and isinstance(node.value, ast.Tuple):
                    n_ret = len(node.value.elts)
                    if n_ret != n_out:
                        self._emit(
                            "EM402", site,
                            f"out_specs carries {n_out} spec(s) but the body "
                            f"returns {n_ret} value(s) (line {node.lineno})",
                        )
                    break

        # Axis environment for EM401: the mesh when visible, else the spec
        # axes when every spec is literal.
        if mesh_known:
            env, closed = mesh_axes, True
        elif in_lit and out_lit:
            env, closed = in_axes | out_axes, True
        else:
            env, closed = set(), False

        if closed and body is not None:
            self._walk_collectives(body, env, site, {}, frozenset(), 0)

        if body is not None and in_node[0] == "seq":
            self._check_unreduced(site, body, in_node[1], out_node, vma_off)

    def _mapped_call_argcount(self, site: ast.Call) -> int | None:
        """Argument count at visible call sites of the mapped function:
        the immediate ``shard_map(...)(args)`` form, or calls of the name
        the result is assigned to, in the same function."""
        parent = self._parents.get(site)
        if isinstance(parent, ast.Call) and parent.func is site:
            if any(isinstance(a, ast.Starred) for a in parent.args):
                return None
            return len(parent.args)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 and isinstance(
            parent.targets[0], ast.Name
        ):
            target = parent.targets[0].id
            fn = self._enclosing_fn(site.lineno)
            scope = fn if fn is not None else self.tree
            for node in ast.walk(scope):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == target
                    and node.lineno > site.lineno
                ):
                    if any(isinstance(a, ast.Starred) for a in node.args):
                        return None
                    return len(node.args)
        return None

    # -- EM401 ---------------------------------------------------------------

    def _collective_name(self, node: ast.Call) -> str | None:
        d = _dotted(node.func)
        if not d:
            return None
        resolved = self.aliases.resolve(d)
        last = resolved.rsplit(".", 1)[-1]
        if last not in _COLLECTIVES:
            return None
        if any(resolved.startswith(h) for h in _COLLECTIVE_HOMES):
            return last
        # Bare compat helpers (axis_size/pcast) keep their names everywhere.
        if resolved == last and last in _COMPAT_BARE:
            return last
        return None

    def _axis_names_from(self, expr: ast.AST | None,
                         bindings: dict[str, str]) -> list[str] | None:
        """Constant axis name(s) of a collective's axis argument, resolved
        through constant-string parameter bindings. None = unresolvable."""
        if expr is None:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return [expr.value]
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = []
            for el in expr.elts:
                sub = self._axis_names_from(el, bindings)
                if sub is None:
                    return None
                out.extend(sub)
            return out
        if isinstance(expr, ast.Name) and expr.id in bindings:
            return [bindings[expr.id]]
        return None

    def _walk_collectives(self, body, env: set[str], site: ast.Call,
                          bindings: dict[str, str], stack: frozenset,
                          depth: int) -> None:
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            cname = self._collective_name(node)
            if cname is not None:
                pos, kwname = _COLLECTIVES[cname]
                axis_expr = _call_arg(node, pos, kwname)
                names = self._axis_names_from(axis_expr, bindings)
                if names is None:
                    continue
                for ax in names:
                    if ax not in env:
                        self._emit(
                            "EM401", node,
                            f"{cname}(...) over axis {ax!r}, but the "
                            f"enclosing shard_map (line {site.lineno}) binds "
                            f"only {{{', '.join(sorted(env)) or ''}}} — an "
                            "unbound collective axis fails at trace time on "
                            "every layout",
                        )
                continue
            # Descend into called helpers, binding constant-string args to
            # their parameters (ring_attend_block(..., axis="sp")).
            if depth >= _DESCENT_DEPTH or not isinstance(node.func, ast.Name):
                continue
            callee = self._find_def(node.func.id, near_line=node.lineno)
            if callee is None or callee.name in stack or callee is body:
                continue
            new_bindings = _bind_string_args(callee, node, bindings)
            self._walk_collectives(
                callee, env, site, new_bindings, stack | {callee.name},
                depth + 1,
            )

    # -- EM403 ---------------------------------------------------------------

    def _check_unreduced(self, site: ast.Call, body, in_specs: list,
                         out_node, vma_off: bool) -> None:
        if isinstance(body, ast.Lambda):
            return
        params = [a.arg for a in (*body.args.posonlyargs, *body.args.args)]
        if len(params) != len(in_specs):
            return
        spec_of: dict[str, list] = {}
        for name, node in zip(params, in_specs):
            if node[0] == "P":
                spec_of[name] = node[1]
        if not spec_of:
            return
        taint: dict[str, set[str]] = {}

        def entry_axes(entry) -> set[str]:
            if isinstance(entry, str) and entry != "?":
                return {entry}
            if isinstance(entry, tuple):
                return set(entry)
            return set()

        def expr_taint(e: ast.AST) -> set[str]:
            if isinstance(e, ast.Name):
                return set(taint.get(e.id, set()))
            if isinstance(e, ast.BinOp):
                t = expr_taint(e.left) | expr_taint(e.right)
                if isinstance(e.op, ast.MatMult):
                    t |= _contraction_axes(
                        spec_entries(e.left), spec_entries(e.right), entry_axes
                    )
                return t
            if isinstance(e, ast.UnaryOp):
                return expr_taint(e.operand)
            if isinstance(e, ast.Call):
                cname = self._collective_name(e)
                if cname in _REDUCERS:
                    base = expr_taint(e.args[0]) if e.args else set()
                    pos, kwname = _COLLECTIVES[cname]
                    names = self._axis_names_from(_call_arg(e, pos, kwname), {})
                    if names is None:
                        return set()  # unknown reduction: assume it covers
                    return base - set(names)
                d = _dotted(e.func)
                last = self.aliases.resolve(d).rsplit(".", 1)[-1] if d else ""
                t: set[str] = set()
                for a in e.args:
                    t |= expr_taint(a)
                for kw in e.keywords:
                    t |= expr_taint(kw.value)
                if last in ("dot", "matmul") and len(e.args) >= 2:
                    t |= _contraction_axes(
                        spec_entries(e.args[0]), spec_entries(e.args[1]),
                        entry_axes,
                    )
                elif last == "einsum" and len(e.args) >= 3 and isinstance(
                    e.args[0], ast.Constant
                ) and isinstance(e.args[0].value, str):
                    t |= _einsum_contraction_axes(
                        e.args[0].value,
                        [spec_entries(a) for a in e.args[1:]],
                        entry_axes,
                    )
                elif last == "dot_general" and len(e.args) >= 2:
                    dims = _call_arg(e, 2, "dimension_numbers")
                    t |= _dot_general_contraction_axes(
                        dims, spec_entries(e.args[0]), spec_entries(e.args[1]),
                        entry_axes,
                    )
                return t
            if isinstance(e, (ast.Attribute, ast.Subscript, ast.Starred)):
                return expr_taint(e.value)
            if isinstance(e, (ast.Tuple, ast.List)):
                t = set()
                for el in e.elts:
                    t |= expr_taint(el)
                return t
            return set()

        def spec_entries(e: ast.AST) -> list | None:
            if isinstance(e, ast.Name):
                return spec_of.get(e.id)
            return None

        out_entries: list = []
        if out_node[0] == "seq":
            out_entries = out_node[1]

        def out_axes_at(i: int) -> set[str] | None:
            node = out_node if out_node[0] != "seq" else (
                out_entries[i] if i < len(out_entries) else ("opaque",)
            )
            if node[0] != "P":
                return None  # opaque out spec: cannot judge replication
            axes: set[str] = set()
            for entry in node[1]:
                if entry == "?":
                    return None
                axes |= entry_axes(entry)
            return axes

        for stmt in _own_statements(body):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
                stmt.targets[0], ast.Name
            ):
                name = stmt.targets[0].id
                taint[name] = expr_taint(stmt.value)
                src = spec_entries(stmt.value)
                if src is not None:
                    spec_of[name] = src
            elif isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
                name = stmt.target.id
                taint[name] = taint.get(name, set()) | expr_taint(stmt.value)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                elts = (
                    stmt.value.elts
                    if isinstance(stmt.value, ast.Tuple)
                    else [stmt.value]
                )
                for i, el in enumerate(elts):
                    t = expr_taint(el)
                    if not t:
                        continue
                    claimed = out_axes_at(i)
                    if claimed is None:
                        continue
                    for ax in sorted(t - claimed):
                        vma_note = (
                            " (and this call site passes check_vma=False, "
                            "so the trace-time replication checker is off)"
                            if vma_off else ""
                        )
                        self._emit(
                            "EM403", stmt,
                            f"returned value is a PARTIAL sum over sharded "
                            f"axis {ax!r} (contraction over an in_specs-"
                            f"sharded dimension) but out_specs claims it "
                            f"replicated — add lax.psum(..., {ax!r}) before "
                            f"returning{vma_note}",
                        )

    # -- EM404 ---------------------------------------------------------------

    def _rule_retrace(self, tree: ast.Module) -> None:
        if not any(d in self.relpath for d in _EM404_DIRS):
            return
        jitted: set[str] = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.ImportFrom)
                and node.module
                and node.module.startswith("edgemesh.")
            ):
                for a in node.names:
                    if (
                        a.name.startswith(_EM404_IMPORT_PREFIXES)
                        or a.name in _EM404_IMPORT_EXTRA
                    ):
                        jitted.add(a.asname or a.name)
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _is_jit_expr(node.value.func, self.aliases):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted.add(t.id)
        for fn in self._all_defs:
            if any(_is_jit_expr(d, self.aliases) for d in fn.decorator_list):
                jitted.add(fn.name)

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            is_jit_call = (
                isinstance(node.func, ast.Name) and node.func.id in jitted
            ) or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr.endswith("_jit")
            )
            if not is_jit_call:
                continue
            values = list(node.args) + [kw.value for kw in node.keywords]
            for arg in values:
                if self._em404_tainted(arg, node.lineno, frozenset()):
                    self._emit(
                        "EM404", node,
                        "host-computed size (len()/.shape arithmetic) flows "
                        "into a jitted call — every distinct value mints a "
                        "compile-cache entry and the engine retraces under "
                        "load; quantize it through "
                        "utils.bucketing.bucket_pow2 (the blessed ladder)",
                    )
                    break

    def _em404_tainted(self, expr: ast.AST, line: int,
                       seen: frozenset) -> bool:
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            last = (self.aliases.resolve(d).rsplit(".", 1)[-1] if d else "")
            if last in _BLESSED_BUCKETING:
                return False  # sanitized: the ladder bounds the key space
            if last == "len":
                return True
            if last in _TAINT_THROUGH:
                return any(
                    self._em404_tainted(a, line, seen) for a in expr.args
                )
            return False
        if isinstance(expr, ast.Subscript):
            if (
                isinstance(expr.value, ast.Attribute)
                and expr.value.attr == "shape"
            ):
                return True
            return self._em404_tainted(expr.value, line, seen)
        if isinstance(expr, ast.BinOp):
            return self._em404_tainted(expr.left, line, seen) or (
                self._em404_tainted(expr.right, line, seen)
            )
        if isinstance(expr, ast.UnaryOp):
            return self._em404_tainted(expr.operand, line, seen)
        if isinstance(expr, ast.Name):
            if expr.id in seen:
                return False
            v = self._local_assign_value(expr.id, line)
            if v is None:
                return False
            return self._em404_tainted(v, line, seen | {expr.id})
        return False


# ---------------------------------------------------------------------------
# Module-level helpers
# ---------------------------------------------------------------------------


def _call_arg(call: ast.Call, pos: int, kwname: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == kwname:
            return kw.value
    if pos < len(call.args) and not isinstance(call.args[pos], ast.Starred):
        return call.args[pos]
    return None


def _str_constants(node: ast.AST | None) -> set[str] | None:
    """All-string-constant tuple/list → the set of strings; else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: set[str] = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
            else:
                return None
        return out
    return None


def _positional_param_bounds(body) -> tuple[int, int] | None:
    """(required, total) positional parameter counts of a body — defaulted
    parameters are optional, so any spec arity in that range is legal."""
    if body is None:
        return None
    args = body.args
    if args.vararg is not None:
        return None
    total = len(args.posonlyargs) + len(args.args)
    return total - len(args.defaults), total


def _own_statements(fn):
    """fn's statements in source order, descending into compound statements
    but NOT into nested function defs (those run on their own schedule)."""
    stack = list(reversed(getattr(fn, "body", [])))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        for field in ("body", "orelse", "finalbody"):
            stack.extend(reversed(getattr(node, field, [])))
        for handler in getattr(node, "handlers", []):
            stack.extend(reversed(handler.body))


def _bind_string_args(callee, call: ast.Call,
                      caller_bindings: dict[str, str]) -> dict[str, str]:
    """Constant-string argument bindings for a callee: explicit args win,
    string-constant defaults fill the rest (the ``axis: str = "sp"``
    idiom)."""
    params = [a.arg for a in (*callee.args.posonlyargs, *callee.args.args)]
    bindings: dict[str, str] = {}
    defaults = callee.args.defaults
    if defaults:
        for name, d in zip(params[len(params) - len(defaults):], defaults):
            if isinstance(d, ast.Constant) and isinstance(d.value, str):
                bindings[name] = d.value
    for a, d in zip(callee.args.kwonlyargs, callee.args.kw_defaults):
        if d is not None and isinstance(d, ast.Constant) and isinstance(d.value, str):
            bindings[a.arg] = d.value

    def value_of(expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name) and expr.id in caller_bindings:
            return caller_bindings[expr.id]
        return None

    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(params):
            v = value_of(arg)
            if v is not None:
                bindings[params[i]] = v
    kwonly = {a.arg for a in callee.args.kwonlyargs}
    for kw in call.keywords:
        if kw.arg and (kw.arg in params or kw.arg in kwonly):
            v = value_of(kw.value)
            if v is not None:
                bindings[kw.arg] = v
    return bindings


def _contraction_axes(lhs_entries, rhs_entries, entry_axes) -> set[str]:
    """Mesh axes a matmul contracts over: the LHS's last dim and the RHS's
    second-to-last dim (the batched-matmul convention)."""
    axes: set[str] = set()
    if lhs_entries:
        axes |= entry_axes(lhs_entries[-1])
    if rhs_entries and len(rhs_entries) >= 2:
        axes |= entry_axes(rhs_entries[-2])
    elif rhs_entries and len(rhs_entries) == 1:
        axes |= entry_axes(rhs_entries[-1])  # vector RHS: its only dim
    return axes


def _einsum_contraction_axes(subscript: str, operand_entries,
                             entry_axes) -> set[str]:
    if "->" not in subscript or "." in subscript:
        return set()
    ins, out = subscript.replace(" ", "").split("->", 1)
    in_subs = ins.split(",")
    contracted = {c for sub in in_subs for c in sub if c not in out}
    axes: set[str] = set()
    for sub, entries in zip(in_subs, operand_entries):
        if entries is None or len(entries) != len(sub):
            continue
        for pos, letter in enumerate(sub):
            if letter in contracted:
                axes |= entry_axes(entries[pos])
    return axes


def _dot_general_contraction_axes(dims: ast.AST | None, lhs_entries,
                                  rhs_entries, entry_axes) -> set[str]:
    """Literal ``dimension_numbers=(((lc,), (rc,)), ...)`` → the mesh axes
    on the contracted dims of either operand's spec."""
    if not isinstance(dims, (ast.Tuple, ast.List)) or not dims.elts:
        return set()
    contract = dims.elts[0]
    if not isinstance(contract, (ast.Tuple, ast.List)) or len(contract.elts) != 2:
        return set()

    def int_list(node: ast.AST) -> list[int]:
        if not isinstance(node, (ast.Tuple, ast.List)):
            return []
        return [
            el.value for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, int)
        ]

    axes: set[str] = set()
    for idx_node, entries in ((contract.elts[0], lhs_entries),
                              (contract.elts[1], rhs_entries)):
        if entries is None:
            continue
        for i in int_list(idx_node):
            if 0 <= i < len(entries):
                axes |= entry_axes(entries[i])
    return axes


def analyze_source(source: str, path: str = "<memory>") -> list[Finding]:
    """Sharding-pass entry point (mirrors concurrency.analyze_source)."""
    return _FileSharding(path, source).run()


# ---------------------------------------------------------------------------
# Layer 2 — AbstractMesh dryrun contracts (EM405)
# ---------------------------------------------------------------------------
#
# Each entry registers a public shard_map wrapper with the mesh layouts it
# must trace under. Runners build tiny ABSTRACT arguments (jax.eval_shape
# trees) and drive the wrapper's production construction path — the same
# spec-building code the engines use — under jax.sharding.AbstractMesh, so
# tp8 traces on a 1-CPU box with no devices. A runner returns a list of
# problem strings (empty = green); raising is the finding.

#: Named mesh layouts: axis (name, size) tuples for AbstractMesh.
LAYOUTS: dict[str, tuple[tuple[str, int], ...]] = {
    "tp2": (("dp", 1), ("tp", 2)),
    "tp8": (("dp", 1), ("tp", 8)),
    "dp2xtp4": (("dp", 2), ("tp", 4)),
    "pp2": (("pp", 2),),
    "sp2": (("sp", 2),),
    "sp4": (("sp", 4),),
    "4d": (("dp", 2), ("pp", 2), ("sp", 2), ("ep", 1), ("tp", 2)),
}


def _layout_str(name: str) -> str:
    return "×".join(f"{ax}{n}" for ax, n in LAYOUTS[name] if n > 1) or "1"


def _abstract_mesh(name: str):
    from jax.sharding import AbstractMesh

    return AbstractMesh(tuple(LAYOUTS[name]))


def _dryrun_cfg(num_heads: int = 8, num_kv_heads: int = 8):
    """Tiny abstract config whose heads/FFN divide every registered tp
    degree (8 heads, 8 kv heads, 128 FFN → tp2/tp4/tp8 all divide)."""
    from edgemesh.models.families import tiny_config

    return tiny_config("llama").replace(
        num_heads=num_heads, num_kv_heads=num_kv_heads, attention_impl="xla"
    )


def _abstract_params(cfg):
    import jax

    from edgemesh.models.transformer import init_params

    return jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))


def _dryrun_tp_infer(mesh, collective_mode: str = "psum",
                     comm_dtype: str = "int8") -> list[str]:
    import jax
    import jax.numpy as jnp

    from edgemesh.models.transformer import init_kv_cache
    from edgemesh.parallel.tp_infer import make_tp_mapped, tp_param_specs

    cfg = _dryrun_cfg()
    params = _abstract_params(cfg)
    specs = tp_param_specs(cfg, params, mesh)
    b = 2 * mesh.shape["dp"]
    max_seq = 16
    cache = jax.eval_shape(lambda: init_kv_cache(cfg, b, max_seq))
    lens = jax.ShapeDtypeStruct((b,), jnp.int32)
    kvv = jax.ShapeDtypeStruct((b, max_seq), jnp.bool_)
    problems: list[str] = []
    for is_decode, s in ((False, 8), (True, 1)):
        mapped = make_tp_mapped(
            cfg, mesh, specs, "xla", is_decode,
            collective_mode=collective_mode, comm_dtype=comm_dtype,
        )
        tokens = jax.ShapeDtypeStruct((b, s), jnp.int32)
        pos = jax.ShapeDtypeStruct((b, s), jnp.int32)
        logits, k, v = jax.eval_shape(
            mapped, params, tokens, pos, kvv, cache.k, cache.v, lens
        )
        step = "decode" if is_decode else "prefill"
        if logits.shape != (b, s, cfg.vocab_size):
            problems.append(
                f"{step} logits {logits.shape} != (batch, seq, vocab)"
            )
        if (k.shape, k.dtype) != (cache.k.shape, cache.k.dtype):
            problems.append(
                f"{step} cache avals drifted: {k.shape}/{k.dtype} vs "
                f"{cache.k.shape}/{cache.k.dtype}"
            )
    return problems


def _dryrun_tp_infer_qpsum(mesh) -> list[str]:
    """The quantized-wire tp program (collective_mode="qpsum"), both comm
    dtypes that actually quantize. The fp8 arm is skipped ONLY when this
    jax has no float8 type — a ValueError out of the trace itself must
    stay a finding, not a skip."""
    import jax.numpy as jnp

    problems = _dryrun_tp_infer(mesh, collective_mode="qpsum")
    if getattr(jnp, "float8_e4m3fn", None) is not None:
        problems += _dryrun_tp_infer(
            mesh, collective_mode="qpsum", comm_dtype="fp8"
        )
    return problems


def _dryrun_tp_infer_qpsum_overlap(mesh) -> list[str]:
    """The chunked comm/compute-overlap tp program."""
    return _dryrun_tp_infer(mesh, collective_mode="qpsum_overlap")


def _dryrun_collectives(mesh) -> list[str]:
    """qpsum itself under shard_map: every comm dtype over the tp axis,
    plus a non-divisible trailing dim (the plain-psum fallback path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from edgemesh.parallel.collectives import COMM_DTYPES, qpsum
    from edgemesh.utils.compat import shard_map

    tp = mesh.shape["tp"]
    problems: list[str] = []
    # 48 divides tp 2/4/8; 9 divides none of them (fallback coverage).
    for h in (48, 9):
        for dtype in COMM_DTYPES:
            if dtype == "fp8" and getattr(jnp, "float8_e4m3fn", None) is None:
                continue
            mapped = shard_map(
                lambda xs, dtype=dtype: qpsum(xs, "tp", dtype=dtype),
                mesh=mesh,
                in_specs=(P("tp", None),),
                out_specs=P("tp", None),
                check_vma=False,
            )
            x = jax.ShapeDtypeStruct((tp * 2, h), jnp.float32)
            out = jax.eval_shape(mapped, x)
            if out.shape != (tp * 2, h) or out.dtype != jnp.float32:
                problems.append(
                    f"qpsum[{dtype}, h={h}] aval {out.shape}/{out.dtype} "
                    f"!= input ({tp * 2}, {h})/float32"
                )
    return problems


def _dryrun_seq_attention(mesh, attention_fn) -> list[str]:
    import jax
    import jax.numpy as jnp
    from functools import partial

    sp = mesh.shape["sp"]
    seq = 4 * sp
    q = jax.ShapeDtypeStruct((1, seq, 4, 8), jnp.float32)
    k = jax.ShapeDtypeStruct((1, seq, 2, 8), jnp.float32)
    pos = jax.ShapeDtypeStruct((1, seq), jnp.int32)
    valid = jax.ShapeDtypeStruct((1, seq), jnp.bool_)
    out = jax.eval_shape(partial(attention_fn, mesh=mesh), q, k, k, pos, valid)
    if out.shape != (1, seq, 4, 8):
        return [f"output {out.shape} != q shape (1, {seq}, 4, 8)"]
    return []


def _dryrun_ring(mesh) -> list[str]:
    from edgemesh.parallel.ring_attention import ring_attention

    return _dryrun_seq_attention(mesh, ring_attention)


def _dryrun_ulysses(mesh) -> list[str]:
    from edgemesh.parallel.ulysses import ulysses_attention

    return _dryrun_seq_attention(mesh, ulysses_attention)


def _dryrun_pipeline(mesh) -> list[str]:
    import jax
    import jax.numpy as jnp

    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_kv_cache
    from edgemesh.parallel.pipeline import make_pipeline_mapped

    cfg = tiny_config("llama").replace(attention_impl="xla")
    num_micro, mbs, max_seq, s = 2, 1, 16, 8
    b = num_micro * mbs
    params = _abstract_params(cfg)
    cache = jax.eval_shape(lambda: init_kv_cache(cfg, b, max_seq))
    mapped = make_pipeline_mapped(cfg, mesh, num_micro, mbs, is_decode=False)
    x = jax.ShapeDtypeStruct((num_micro, mbs, s, cfg.hidden_size), jnp.float32)
    pos = jax.ShapeDtypeStruct((num_micro, mbs, s), jnp.int32)
    kvv = jax.ShapeDtypeStruct((num_micro, mbs, max_seq), jnp.bool_)
    lens = jax.ShapeDtypeStruct((num_micro, mbs), jnp.int32)
    k, v, out = jax.eval_shape(
        mapped, params["layers"], cache.k, cache.v, x, pos, kvv, lens
    )
    problems: list[str] = []
    if out.shape != (num_micro, mbs, s, cfg.hidden_size):
        problems.append(f"stage output {out.shape} != microbatched hidden")
    if k.shape != cache.k.shape:
        problems.append(f"cache avals drifted: {k.shape} vs {cache.k.shape}")
    return problems


def _dryrun_spmd(mesh) -> list[str]:
    import jax
    import jax.numpy as jnp

    from edgemesh.models.families import tiny_config
    from edgemesh.parallel.spmd import make_spmd_loss

    cfg = tiny_config("llama")
    params = _abstract_params(cfg)
    loss_fn = make_spmd_loss(cfg, mesh, num_micro=2)
    B = 2 * mesh.shape["dp"]
    S = 4 * mesh.shape["sp"]
    tokens = jax.ShapeDtypeStruct((B, S), jnp.int32)
    lengths = jax.ShapeDtypeStruct((B,), jnp.int32)
    loss = jax.eval_shape(loss_fn, params, tokens, lengths)
    if loss.shape != () or str(loss.dtype) != "float32":
        return [f"loss aval {loss.shape}/{loss.dtype} != scalar float32"]
    return []


#: The registry: every public shard_map wrapper, with the layouts it must
#: trace under. Adding a wrapper without registering it here leaves "does
#: tp8 even trace" to the next hardware window — don't.
SHARDING_CONTRACTS: list[dict] = [
    {
        "wrapper": "tp_infer",
        "path": "edgemesh/parallel/tp_infer.py",
        "layouts": ("tp2", "tp8", "dp2xtp4"),
        "runner": _dryrun_tp_infer,
    },
    {
        "wrapper": "tp_infer_qpsum",
        "path": "edgemesh/parallel/tp_infer.py",
        "layouts": ("tp2", "tp8", "dp2xtp4"),
        "runner": _dryrun_tp_infer_qpsum,
    },
    {
        "wrapper": "tp_infer_qpsum_overlap",
        "path": "edgemesh/parallel/tp_infer.py",
        "layouts": ("tp2", "tp8", "dp2xtp4"),
        "runner": _dryrun_tp_infer_qpsum_overlap,
    },
    {
        "wrapper": "collectives",
        "path": "edgemesh/parallel/collectives.py",
        "layouts": ("tp2", "tp8", "dp2xtp4"),
        "runner": _dryrun_collectives,
    },
    {
        "wrapper": "ring_attention",
        "path": "edgemesh/parallel/ring_attention.py",
        "layouts": ("sp2", "sp4"),
        "runner": _dryrun_ring,
    },
    {
        "wrapper": "ulysses",
        "path": "edgemesh/parallel/ulysses.py",
        "layouts": ("sp2", "sp4"),
        "runner": _dryrun_ulysses,
    },
    {
        "wrapper": "pipeline",
        "path": "edgemesh/parallel/pipeline.py",
        "layouts": ("pp2",),
        "runner": _dryrun_pipeline,
    },
    {
        "wrapper": "spmd",
        "path": "edgemesh/parallel/spmd.py",
        "layouts": ("4d",),
        "runner": _dryrun_spmd,
    },
]


def run_sharding_contracts() -> list[Finding]:
    """Trace every registered shard_map wrapper under its AbstractMesh
    layouts; returns EM405 findings (empty = green). Degrades to an empty
    run on jax builds without AbstractMesh — the AST layer still gates."""
    try:
        from jax.sharding import AbstractMesh  # noqa: F401
    except ImportError:  # pragma: no cover — modern jax always has it
        return []
    findings: list[Finding] = []
    for contract in SHARDING_CONTRACTS:
        wrapper, path = contract["wrapper"], contract["path"]
        for layout in contract["layouts"]:
            mesh = _abstract_mesh(layout)
            try:
                problems = contract["runner"](mesh)
            except Exception as e:  # noqa: BLE001 — a trace failure IS the finding
                findings.append(Finding(
                    "EM405", "error", path, 1,
                    f"{wrapper} failed to trace under layout {layout} "
                    f"({_layout_str(layout)}): {type(e).__name__}: {e}",
                    context=wrapper,
                ))
                continue
            for msg in problems:
                findings.append(Finding(
                    "EM405", "error", path, 1,
                    f"{wrapper} under layout {layout} ({_layout_str(layout)}): {msg}",
                    context=wrapper,
                ))
    return findings
