"""Finding records, fingerprints, and the grandfathering baseline.

A finding's fingerprint deliberately excludes the line NUMBER: baselines
must survive unrelated edits above the finding, so identity is
(rule, path, enclosing scope, stripped source line text) — the same scheme
ruff/mypy baselining tools converged on.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import asdict, dataclass, field
from pathlib import Path

SEVERITIES = ("error", "warning")

#: Inline suppression: ``# edgelint: disable=EM105`` (comma-separate for
#: several rules). Shared by the AST linter and the concurrency pass.
DISABLE_RE = re.compile(r"#\s*edgelint:\s*disable=([A-Z0-9, ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str  # e.g. "EM101"
    severity: str  # "error" | "warning"
    path: str  # repo-relative, forward slashes
    line: int  # 1-based
    message: str
    context: str = ""  # dotted name of the enclosing function/class, if any
    line_text: str = ""  # stripped source of the flagged line

    def fingerprint(self) -> str:
        key = "\x1f".join((self.rule, self.path, self.context, self.line_text))
        return hashlib.sha1(key.encode("utf-8", "replace")).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        ctx = f" [{self.context}]" if self.context else ""
        return f"{where}: {self.rule} {self.severity}: {self.message}{ctx}"


@dataclass
class Baseline:
    """Committed set of grandfathered finding fingerprints."""

    fingerprints: set[str] = field(default_factory=set)
    entries: list[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str | Path) -> "Baseline":
        p = Path(path)
        if not p.exists():
            return cls()
        data = json.loads(p.read_text())
        entries = data.get("findings", [])
        return cls({e["fingerprint"] for e in entries}, entries)

    @classmethod
    def from_findings(cls, findings: list[Finding]) -> "Baseline":
        entries = [
            {
                "fingerprint": f.fingerprint(),
                "rule": f.rule,
                "path": f.path,
                "context": f.context,
                "line_text": f.line_text,
            }
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ]
        return cls({e["fingerprint"] for e in entries}, entries)

    def save(self, path: str | Path) -> None:
        body = {
            "comment": (
                "Grandfathered edgelint findings. Regenerate with "
                "`python -m edgemesh.analysis --write-baseline` after "
                "reviewing that every new entry is intentional."
            ),
            "findings": self.entries,
        }
        Path(path).write_text(json.dumps(body, indent=2) + "\n")

    def filter(self, findings: list[Finding]) -> list[Finding]:
        """Findings NOT covered by the baseline."""
        return [f for f in findings if f.fingerprint() not in self.fingerprints]


def default_baseline_path() -> Path:
    return Path(__file__).parent / "baseline.json"


def repo_relative(path: str | Path) -> str:
    """Best-effort repo-relative POSIX path (fingerprints must not depend on
    the checkout location)."""
    p = Path(path).resolve()
    # The repo root is the parent of the "edgemesh" package directory.
    root = Path(__file__).resolve().parent.parent.parent
    try:
        return p.relative_to(root).as_posix()
    except ValueError:
        return p.as_posix()
