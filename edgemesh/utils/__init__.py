from edgemesh.utils.tracing import (  # noqa: F401
    JsonlLogger,
    PhaseTimer,
    Stopwatch,
    capture_profile,
    phase_report,
    reset_phases,
    trace,
)
