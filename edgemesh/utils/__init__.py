from edgemesh.utils.tracing import (  # noqa: F401
    JsonlLogger,
    capture_profile,
    phase_report,
    reset_phases,
    trace,
)
