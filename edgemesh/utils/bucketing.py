"""Blessed shape-bucketing vocabulary for host→jit boundaries.

Every host-computed integer that becomes a SHAPE inside a jitted program
(a static argnum, a pad target, a packed-token capacity) keys a compile
cache entry. Passing the raw value — ``len(prompt)``, ``cu[-1]``,
``tokens.shape[1] + k`` — mints one compiled program per distinct value,
and the serving engine pays a multi-second retrace exactly when it is
busiest (a new prompt length arrives under load). The fix is always the
same: quantize the value onto a small ladder so the compile-key space is
O(log(max)) instead of O(distinct values).

This module is that ladder — extracted from the ``s_cap`` power-of-two
bucketing the continuous engine's ragged boundary launch converged on
(serve/continuous.py), so every future host→jit seam spells it the same
way. The static analyzer's EM404 rule (analysis/sharding.py) recognizes
these helpers as sanitizers: a host-computed size flowing into a jitted
call in serve//runtime/ must pass through one of them.
"""

from __future__ import annotations

__all__ = ["bucket_pow2", "POW2_FLOOR"]

#: Default smallest bucket: small enough that short prompts don't pay a
#: large pad, large enough that the ladder has few rungs below typical
#: prompt lengths (16 → 9 rungs to 4096).
POW2_FLOOR = 16


def bucket_pow2(n: int, floor: int = POW2_FLOOR) -> int:
    """Round ``n`` up onto the doubling ladder anchored at ``floor``.

    Returns the smallest ``floor * 2**k`` (k >= 0) that is >= ``n`` — the
    compile-key ladder for shape-determining host ints. ``floor`` itself
    need not be a power of two: the decode-only ragged boundary anchors
    its ladder at ``n_slots`` so the steady state is exactly ONE compiled
    program (cap == n_slots), and admission waves climb doublings of it.
    """
    if floor <= 0:
        raise ValueError(f"bucket_pow2 floor must be positive, got {floor}")
    cap = floor
    while cap < n:
        cap *= 2
    return cap
