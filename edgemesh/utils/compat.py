"""JAX API-drift shims — the ONE module allowed to touch drifted spellings.

``shard_map`` has moved twice across the jax versions this codebase meets
(``jax.experimental.shard_map.shard_map`` → ``jax.shard_map``, with the
replication-check kwarg renamed ``check_rep`` → ``check_vma``), and
``lax.pcast`` (varying-manual-axes casts) does not exist before the vma
type system does. Call sites importing either spelling directly break on
the other side of the drift — the exact failure mode that took out all 7
seed ring-attention tests. Everything outside this module goes through
these wrappers; edgelint's EM101 rule enforces that (this file is its one
allowlisted exception).
"""

from __future__ import annotations

import jax
from jax import lax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``.

    ``check_vma`` carries the modern name; on pre-vma jax it maps onto
    ``check_rep`` (same meaning: verify per-axis replication/varying types
    of the body's outputs against ``out_specs``).
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        # The move to jax.shard_map and the check_rep→check_vma rename were
        # separate drift events: key the kwarg spelling on the signature,
        # not on where the function lives.
        import inspect

        kw = (
            "check_vma"
            if "check_vma" in inspect.signature(sm).parameters
            else "check_rep"
        )
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **{kw: check_vma})
    from jax.experimental.shard_map import shard_map as _sm  # noqa: EM101-exempt

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def axis_size(axis_name):
    """Version-portable ``lax.axis_size``: static size of a manual mesh axis
    from inside a shard_map/pmap body. Pre-drift jax has no ``lax.axis_size``;
    the axis environment carries the same (static) answer."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    from jax._src.core import get_axis_env

    return get_axis_env().axis_size(axis_name)


def register_compile_event_listener(fn) -> bool:
    """Version-portable ``jax.monitoring`` duration-listener registration.

    ``fn(event_name, duration_s)`` is invoked for every monitoring duration
    event (the compile pipeline emits ``/jax/core/compile/*`` keys). The
    listener signature has drifted — newer jax passes extra keyword
    metadata — so the adapter swallows ``**kwargs``. Returns False when
    this jax has no monitoring hooks at all (the caller degrades to
    counting nothing rather than failing: telemetry is optional by
    construction)."""
    monitoring = getattr(jax, "monitoring", None)
    if monitoring is None:
        try:
            from jax import monitoring  # older spelling: submodule only
        except ImportError:
            return False
    register = getattr(monitoring, "register_event_duration_secs_listener", None)
    if register is None:
        return False

    def _adapter(name, duration_s, **_kwargs):
        fn(name, duration_s)

    register(_adapter)
    return True


def register_cache_event_listener(fn) -> bool:
    """Version-portable ``jax.monitoring`` plain-event registration.

    ``fn(event_name)`` is invoked for every monitoring *event* (no
    duration) — the persistent compilation cache emits
    ``/jax/compilation_cache/cache_hits`` on every disk-cache hit and
    ``/jax/compilation_cache/compile_requests_use_cache`` per lookup, which
    is how a warm-started replica proves its compiles came from the shared
    cache. Newer jax passes extra keyword metadata; the adapter swallows
    it. Returns False when this jax has no monitoring hooks (the caller
    degrades to counting nothing — telemetry is optional)."""
    monitoring = getattr(jax, "monitoring", None)
    if monitoring is None:
        try:
            from jax import monitoring  # older spelling: submodule only
        except ImportError:
            return False
    register = getattr(monitoring, "register_event_listener", None)
    if register is None:
        return False

    def _adapter(name, **_kwargs):
        fn(name)

    register(_adapter)
    return True


def enable_compilation_cache(cache_dir) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir`` so every
    process sharing the directory reuses each other's XLA compiles — the
    warm-start lever for replica scale-up (docs/FLEET.md "Autoscaling with
    warm starts"). The gate knobs (min compile time / min entry size) have
    drifted across jax versions, so each is applied best-effort: a missing
    knob degrades to that version's default rather than failing the serve.
    Returns False only when the cache directory itself cannot be set."""
    try:
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    except Exception:
        return False
    # Cache EVERYTHING: the default min-compile-time gate (1s) would skip
    # exactly the small programs a CPU test fleet compiles, and scale-up
    # replicas want every hit they can get.
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # knob not in this jax: its default applies
            pass
    # The cache initializes AT MOST ONCE, on the first compile: a compile
    # that ran before this call (a device-readiness probe, an eagerly built
    # model) latches it "disabled" and every later write silently no-ops.
    # Resetting forces re-initialization against the directory just set.
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # private API drift: the next compile may still init
        pass
    return True


def aot_cost_analysis(fn, args) -> dict | None:
    """Version-portable AOT cost capture: ``fn.lower(*args).compile()
    .cost_analysis()`` normalized to ``{"flops", "bytes_accessed",
    "output_bytes"}`` (floats, each None where XLA withholds it).

    Every layer here has drifted: ``lower`` is absent on plain functions,
    ``cost_analysis`` has returned a per-device list, a bare dict, and
    None across versions, and its keys are free-text ("flops", "bytes
    accessed", "bytes accessedout{}" / "bytes accessed output") that
    backends populate inconsistently — TPU runtimes may withhold the
    whole table. Callers (the compute observatory, obs/compute.py) treat
    None as "cost model unavailable" and keep serving, so this NEVER
    raises: any failure — tracing, compilation, analysis — degrades to
    None. ``args`` should be the call's arguments with array leaves
    replaced by ``jax.ShapeDtypeStruct`` (capture them BEFORE dispatch:
    donated buffers are deleted by the launch itself)."""
    try:
        lower = getattr(fn, "lower", None)
        if lower is None:
            return None
        ca = lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return None

        def _num(value):
            try:
                v = float(value)
            except (TypeError, ValueError):
                return None
            return v if v >= 0.0 else None

        out_bytes = None
        for key, value in ca.items():
            if "bytes accessed" in key and "out" in key:
                out_bytes = _num(value)
                break
        return {
            "flops": _num(ca.get("flops")),
            "bytes_accessed": _num(ca.get("bytes accessed")),
            "output_bytes": out_bytes,
        }
    except Exception:
        return None


def pcast(x, axis_name, *, to: str = "varying"):
    """Version-portable ``lax.pcast``.

    On jax with the varying-manual-axes type system, casts ``x``'s vma type
    along ``axis_name`` (scan carries whose zero inits must match the
    device-varying type their ppermuted updates acquire). On pre-vma jax
    there is no vma type to cast — the identity is exact, and the enclosing
    ``check_rep`` machinery tracks replication on its own.
    """
    fn = getattr(lax, "pcast", None)
    if fn is not None:
        return fn(x, axis_name, to=to)
    return x
