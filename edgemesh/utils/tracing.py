"""Tracing / profiling / structured logs (SURVEY.md §5.1, §5.5).

The reference's observability is ``time.time()`` deltas around ``generate``
and log lines pasted into a spreadsheet (``combiner_fp.py:336-350``,
``try.py:309-337``). Here:

- ``trace(name)``: context manager that both stamps a ``jax.profiler``
  TraceAnnotation (visible in TensorBoard/XProf timelines when a profile is
  being captured) and accumulates wall time into a process-local registry.
- ``phase_report()`` / ``reset_phases()``: read/clear the accumulated
  per-phase totals — how prefill vs decode split is measured without a
  profiler attached.
- ``capture_profile(dir)``: whole-program XLA profile capture
  (jax.profiler.start_trace/stop_trace) for the real deep-dives.
- ``JsonlLogger``: one-JSON-object-per-line run logs, the same convention
  as the eval harness's results.jsonl and the supervisor's event log.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from pathlib import Path
from typing import Any

log = logging.getLogger("edgemesh.obs")

_lock = threading.Lock()
_phase_totals: dict[str, float] = defaultdict(float)
_phase_counts: dict[str, int] = defaultdict(int)


class PhaseTimer:
    """Handle yielded by :func:`trace`: ``elapsed_s`` carries the region's
    wall time once the block exits (0.0 while still inside). Lets callers
    consume the SAME measurement the phase registry and the
    ``edgemesh_phase_seconds`` histogram record, instead of re-deriving it
    from raw clock reads (edgelint EM107)."""

    __slots__ = ("name", "elapsed_s")

    def __init__(self, name: str):
        self.name = name
        self.elapsed_s = 0.0


class Stopwatch:
    """Monotonic wall-clock stopwatch owned by the obs substrate — the
    sanctioned way for ``serve/``/``runtime/`` code to measure an elapsed
    window that is part of a RESULT payload (tokens/sec, stream
    ``elapsed_s``) rather than a span (edgelint EM107 keeps raw
    ``time.perf_counter`` reads out of the serving stack)."""

    __slots__ = ("_t0",)

    def __init__(self):
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        """Seconds since construction (or the last :meth:`restart`)."""
        return time.perf_counter() - self._t0

    def restart(self) -> None:
        self._t0 = time.perf_counter()


@contextmanager
def trace(name: str):
    """Annotate a region for the JAX profiler AND accumulate its wall time
    (both the process-local phase registry below and the PROCESS-DEFAULT obs
    registry's ``edgemesh_phase_seconds`` histogram — trace() regions have
    no registry handle, so a ``serve_rest(registry=...)`` override renders
    phases only when it IS the process default; ``/stats``'s ``phases`` key
    always carries them). Yields a :class:`PhaseTimer` whose ``elapsed_s``
    is filled in on exit, so callers reuse the region's own measurement."""
    import jax

    handle = PhaseTimer(name)
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        try:
            yield handle
        finally:
            dt = time.perf_counter() - t0
            handle.elapsed_s = dt
            with _lock:
                _phase_totals[name] += dt
                _phase_counts[name] += 1
            from edgemesh.obs.metrics import get_registry

            get_registry().histogram(
                "edgemesh_phase_seconds",
                "trace() region wall time by phase", ("phase",)
            ).labels(phase=name).observe(dt)


def phase_report() -> dict[str, dict[str, float]]:
    """{name: {total_s, count, mean_s}} for every traced region so far."""
    with _lock:
        return {
            name: {
                "total_s": _phase_totals[name],
                "count": _phase_counts[name],
                "mean_s": _phase_totals[name] / max(_phase_counts[name], 1),
            }
            for name in _phase_totals
        }


def reset_phases() -> None:
    with _lock:
        _phase_totals.clear()
        _phase_counts.clear()


@contextmanager
def capture_profile(log_dir: str | Path):
    """Capture a full device/host profile under ``log_dir`` (TensorBoard
    'profile' plugin format). Wrap ONE representative region — traces are
    large."""
    import jax

    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class JsonlLogger:
    """Append-only structured run log; every record gets a timestamp."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        #: malformed lines skipped by the most recent ``read()`` — a torn
        #: write from a crashed process is data loss worth surfacing, not a
        #: reason the whole log becomes unreadable.
        self.malformed = 0

    def log(self, event: str, **fields: Any) -> None:
        record = {"ts": time.time(), "event": event, **fields}
        with self._lock:
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")

    def read(self) -> list[dict]:
        """Every parseable record. A truncated/partial line (torn write —
        e.g. the process died mid-``f.write``) is skipped and counted in
        ``self.malformed`` instead of raising and losing the whole log."""
        if not self.path.exists():
            self.malformed = 0
            return []
        records: list[dict] = []
        bad = 0
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    bad += 1
        self.malformed = bad
        if bad:
            log.warning("%s: skipped %d malformed line(s)", self.path, bad)
        return records
