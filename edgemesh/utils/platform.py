"""Backend identification.

The session image's remote-TPU tunnel registers its PJRT plugin under the
platform name "axon" — NOT "tpu" — so ``jax.default_backend() == "tpu"``
is False on the very hardware the Pallas kernels target, silently routing
production runs onto interpret/einsum fallbacks (round-1 VERDICT weak #4's
root cause). Centralize the check here and inspect the device descriptor,
not just the platform string.
"""

from __future__ import annotations

import functools

import jax


class DeviceUnavailableError(SystemExit):
    """The device backend did not answer the bounded first-contact probe.

    Subclasses SystemExit so CLI entries exit with the actionable message
    unchanged, while programmatic callers (bench.py's stale-artifact
    fallback) can catch the specific condition."""


def device_sync(x) -> None:
    """Reliable completion barrier for timing.

    On the tunneled "axon" platform, ``Array.block_until_ready()`` returns
    before the producing program has finished (measured: ~0.7 ms for a
    program whose results take ~900 ms to materialize), so wall-clock
    windows closed with it can exclude nearly all device work. A 1-element
    device→host copy cannot complete early — the bytes don't exist until
    the producing executable has run — so force one on a single leaf.
    All outputs of one XLA executable materialize together, hence syncing
    any element of any output leaf fences the whole program.
    """
    import numpy as np

    leaves = jax.tree.leaves(x)
    if leaves:
        np.asarray(jax.numpy.ravel(leaves[0])[:1])


def tree_sync(tree) -> None:
    """``device_sync`` for a whole pytree whose leaves may come from many
    independent transfers (e.g. per-leaf ``device_put``): one jitted
    reduction consumes every leaf, so its single-scalar readback can't
    complete until all of them are resident. Syncing leaf-by-leaf instead
    would pay one tunnel round-trip per leaf."""
    import numpy as np

    leaves = jax.tree.leaves(tree)
    if not leaves:
        return
    total = jax.jit(
        lambda xs: sum(jax.numpy.ravel(x)[0].astype(jax.numpy.float32) for x in xs)
    )(leaves)
    np.asarray(total)


@functools.cache
def on_tpu() -> bool:
    """True when the default JAX backend drives real TPU hardware (including
    tunneled platforms whose name is not "tpu"). Cached per process — the
    backend cannot change once initialized."""
    name = (jax.default_backend() or "").lower()
    if name == "tpu" or name == "axon":
        return True
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    kind = (getattr(dev, "device_kind", "") or "").lower()
    plat = (getattr(dev, "platform", "") or "").lower()
    return "tpu" in kind or "tpu" in plat or "axon" in plat


def ensure_device_ready(timeout_s: float | None = None, _probe=None) -> None:
    """Bounded first-contact probe for the device backend.

    The axon remote-TPU tunnel has been observed to wedge so hard that the
    very first dispatch blocks forever; a CLI command then hangs with zero
    diagnostics (round-2 judge measured >600s on `edgemesh eval`). Run a
    trivial jitted op in a daemon thread and give it ``timeout_s`` seconds
    (env ``EDGEMESH_DEVICE_INIT_TIMEOUT``, default 300, 0 disables); on
    timeout, exit with an actionable message instead of hanging. The probe
    thread stays blocked in the dead dispatch — it is a daemon, so process
    exit is unaffected.
    """
    import os
    import threading

    import numpy as np

    if timeout_s is None:
        timeout_s = float(os.environ.get("EDGEMESH_DEVICE_INIT_TIMEOUT", "300"))
    if timeout_s <= 0:
        return

    def probe():
        np.asarray(jax.jit(lambda: jax.numpy.zeros((1,), jax.numpy.float32))())

    probe = _probe or probe
    done = threading.Event()
    errs: list[BaseException] = []

    def run():
        try:
            probe()
        except BaseException as e:  # surface backend-init errors, not just hangs
            errs.append(e)
        finally:
            done.set()

    threading.Thread(target=run, daemon=True).start()
    if not done.wait(timeout_s):
        # Read the platform list from config, NOT jax.default_backend():
        # the latter initializes the backend and would block right here.
        platforms = getattr(jax.config, "jax_platforms", None) or "(default)"
        raise DeviceUnavailableError(
            f"device backend did not answer within {timeout_s:.0f}s "
            f"(jax_platforms={platforms!r}) — the remote-TPU tunnel is likely "
            "wedged. Fixes: pin the CPU backend with `JAX_PLATFORMS=cpu "
            "edgemesh ...` (this CLI honors the env var even under a "
            "sitecustomize override), or raise EDGEMESH_DEVICE_INIT_TIMEOUT "
            "(seconds; 0 disables this check)."
        )
    if errs:
        raise errs[0]
