"""Backend identification.

The session image's remote-TPU tunnel registers its PJRT plugin under the
platform name "axon" — NOT "tpu" — so ``jax.default_backend() == "tpu"``
is False on the very hardware the Pallas kernels target, silently routing
production runs onto interpret/einsum fallbacks (round-1 VERDICT weak #4's
root cause). Centralize the check here and inspect the device descriptor,
not just the platform string.
"""

from __future__ import annotations

import functools

import jax


@functools.cache
def on_tpu() -> bool:
    """True when the default JAX backend drives real TPU hardware (including
    tunneled platforms whose name is not "tpu"). Cached per process — the
    backend cannot change once initialized."""
    name = (jax.default_backend() or "").lower()
    if name == "tpu" or name == "axon":
        return True
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    kind = (getattr(dev, "device_kind", "") or "").lower()
    plat = (getattr(dev, "platform", "") or "").lower()
    return "tpu" in kind or "tpu" in plat or "axon" in plat
