"""Measurement self-archiving: one dated on-chip artifact per completed run.

Health windows on the tunneled TPU are rare and can open at any hour; every
measurement entry point (bench.py, the 8B serving drive) archives its own
result so the record — and bench.py's stale-fallback corpus — never depends
on a human copying numbers out of a window by hand.
"""

from __future__ import annotations

import json
import time
from pathlib import Path


def archive_result(
    result: dict, prefix: str, directory: str | Path, path: Path | None = None
) -> Path | None:
    """Write a stamped COPY of ``result`` (the caller's dict — often already
    printed to stdout — is never mutated) to
    ``directory/<prefix>_<UTC stamp>.json``, or overwrite ``path`` when
    given (continuous per-stage archiving rewrites one file per run).
    Dated names sort chronologically, and the date is the second ``_``
    field — the shape bench.py's stale fallback parses. Archiving must
    never fail the measurement itself: any OSError returns None."""
    stamp = time.strftime("%Y-%m-%d_%H%M%S", time.gmtime())
    payload = {**result, "measured_at_utc": stamp}
    if path is None:
        path = Path(directory) / f"{prefix}_{stamp}.json"
    try:
        path.write_text(json.dumps(payload, indent=2))
    except OSError:
        return None
    return path
