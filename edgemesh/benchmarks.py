"""Decode-throughput microbenchmark with perf accounting.

Measures the BASELINE.json headline (decode tokens/sec/chip) on a
Llama-3.2-1B-shaped model — the same architecture the reference benchmarks on
A100 (BASELINE.md Table 3: bf16 51.84 tok/s, int8 25.83 tok/s — int8 2×
SLOWER there; the bar this module exists to beat is int8 ≥ bf16 on TPU).

``headline_benchmark`` runs bf16 AND every int8 execution path (w8a16
epilogue-dequant, XLA w8a8 dynamic, fused Pallas w8a8) at the same
preset/batch, picks the fastest int8 path by measurement, and reports the
comparison plus roofline accounting: decode is HBM-bandwidth-bound (every
weight byte is read once per step), so effective GB/s = weight-bytes x
steps / time, quoted against the chip's peak.

Random weights: throughput is weight-value-independent; quality numbers come
from the eval harness with real checkpoints, never from here.
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.families import config_for_family
from edgemesh.models.transformer import init_params
from edgemesh.ops.int8 import quantize_params
from edgemesh.runtime import generate

# Reference numbers (BASELINE.md Table 3, A100 40GB, generated-tokens/sec).
REFERENCE_TOK_S = {"bf16": 51.84, "int8": 25.83}

# Peak HBM bandwidth per chip for roofline accounting. v5e: 819 GB/s
# (public spec); overridable for other generations.
HBM_PEAK_GBS = float(os.environ.get("EDGEMESH_HBM_PEAK_GBS", "819"))

PRESETS = {
    # Llama-3.2-1B-Instruct architecture (HF config) — the reference's refiner
    # model and its published single-model rows.
    "llama1b": dict(
        vocab_size=128256, hidden_size=2048, num_layers=16, num_heads=32,
        num_kv_heads=8, intermediate_size=8192, max_seq_len=2048,
        tie_embeddings=True,
    ),
    # CI-sized smoke preset.
    "tiny": dict(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=256, max_seq_len=512, dtype="float32",
    ),
    # Llama-3-8B architecture (HF config) — the BASELINE.json north-star
    # model ("int8 Llama-3-8B ≥2k tok/s aggregate on v5e-8"). ~8.9 GB as
    # int8: fits ONE v5e chip's HBM, but only via the fabricate-int8 build
    # below (a bf16 init would be ~16 GB and OOM before quantizing).
    "llama8b": dict(
        vocab_size=128256, hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, intermediate_size=14336, max_seq_len=2048,
        tie_embeddings=False,
    ),
}


def fabricate_int8_params(cfg) -> dict:
    """Random INT8 param tree built directly at int8 — no bf16 intermediate.

    Throughput is weight-value-independent (module docstring), so for
    models whose bf16 init would not fit HBM (llama8b: ~16 GB vs the chip's
    16 GB) the bench fabricates the quantized tree leaf-by-leaf: int8
    kernels + unit scales + int8 embedding, exactly the layout
    quantize_params + quantize_embedding produce."""
    from edgemesh.models.transformer import init_params

    h, nh, kh, hd = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inter, L, V = cfg.intermediate_size, cfg.num_layers, cfg.vocab_size

    def q(key, *shape):
        # crc32, not hash(): str hashing is PYTHONHASHSEED-randomized per
        # process and would make the fabricated tree non-reproducible.
        import zlib

        # Per-leaf progress: each leaf is its own device dispatch (up to
        # ~1.9 GB for the 8B mlp), and the r3 tunnel wedge hit exactly here
        # with nothing logged for 900s — feed the stall watchdog per leaf so
        # a slow-but-alive fabricate isn't killed and a wedge names its leaf.
        _progress(f"fabricate leaf {key} {tuple(shape)}")
        ki = jax.random.fold_in(jax.random.PRNGKey(0), zlib.crc32(key.encode()) % (2**31))
        out = jax.jit(
            lambda: jax.random.randint(ki, shape, -127, 128, jnp.int32).astype(jnp.int8)
        )()
        out.block_until_ready()
        return out

    def dense_q(key, i, o):
        return {"kernel_q": q(key, L, i, o), "scales": jnp.full((L, o), 0.01, jnp.float32)}

    # Norm scales via a tiny real init (cheap); everything big is int8.
    tiny = cfg.replace(num_layers=1, vocab_size=8)
    norm = init_params(tiny, jax.random.PRNGKey(1))["final_norm"]
    stacked_norm = {k: jnp.broadcast_to(v[None], (L, *v.shape)) for k, v in norm.items()}
    layers = {
        "attn_norm": stacked_norm,
        "mlp_norm": stacked_norm,
        "q": dense_q("q", h, nh * hd),
        "k": dense_q("k", h, kh * hd),
        "v": dense_q("v", h, kh * hd),
        "o": dense_q("o", nh * hd, h),
        "gate": dense_q("gate", h, inter),
        "up": dense_q("up", h, inter),
        "down": dense_q("down", inter, h),
    }
    params = {
        "embed": {
            "weight_q": q("embed", V, h),
            "scales": jnp.full((V,), 0.01, jnp.float32),
        },
        "layers": layers,
        "final_norm": norm,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {
            "kernel_q": jnp.squeeze(q("lm_head", 1, h, V), 0),
            "scales": jnp.full((V,), 0.01, jnp.float32),
        }
    return params


# One prompt shape for every serving-wave workload: the fixed 3-digit index
# keeps all prompts — warmup included — in ONE length bucket regardless of
# request count (a 2-digit format put request 100+ in a new bucket, paying
# a 20-40s admission compile mid-measurement).
_WAVE_QUESTION = "benchmark question number {i:03d}, please answer at length?"


def _e2e_latency(r: dict) -> float:
    """End-to-end request latency from submit: queue wait + decode wall."""
    return r["t_end"] - r["t_start"] + r["queue_s"]


#: ``EDGEMESH_BENCH_QUALITY=0`` drops the quality blocks from bench
#: artifacts (the stages still run — only the block is skipped).
QUALITY_GATE_ENV = "EDGEMESH_BENCH_QUALITY"


def bench_quality_block(rollup: dict | None,
                        agreement: float | None = None) -> dict | None:
    """The bench stages' shared ``quality`` block (docs/OBSERVABILITY.md
    "The quality observatory"): a fixed-schema projection of an engine's
    :class:`~edgemesh.obs.quality.QualityTracker` rollup plus the
    ensemble agreement EWMA, so artifacts diff across rounds even as the
    rollup grows keys. Returns None when ``EDGEMESH_BENCH_QUALITY=0`` —
    the schema and the skip gate are pinned in tests/test_bench_partial.py."""
    if os.environ.get(QUALITY_GATE_ENV, "1") == "0":
        return None
    rollup = rollup if isinstance(rollup, dict) else {}
    return {
        "requests": rollup.get("requests", 0),
        "low_confidence_requests": rollup.get("low_confidence_requests", 0),
        "confidence_ewma": rollup.get("confidence_ewma"),
        "confidence_min_seen": rollup.get("confidence_min_seen"),
        "entropy_ewma": rollup.get("entropy_ewma"),
        "agreement_ewma": agreement,
    }


def _run_waves(eng, n_requests: int, waves: int, budgets=None, label: str = "serving",
               question: str | None = None):
    """The round-4 variance protocol, in ONE place for every serving-style
    benchmark: warm ONE request in the SAME prompt-length bucket as the
    timed requests (admission prefill compiles per bucket; a fresh compile
    costs 20-40s over the tunnel and must not bleed into the first timed
    admission), then run ``waves`` independent bursts and report per-wave
    aggregate tok/s. ``budgets`` cycles per-request ``max_new`` caps (the
    mixed admission workload); None submits at the uniform engine budget.
    ``question`` overrides the wave prompt template (must keep the fixed
    3-digit index so every request stays in one length bucket) — the ragged
    ablation's prefill-heavy shape pads it. Returns (wave_tok_s,
    [(budget, result)], wall_all, warmup stats)."""
    question = question or _WAVE_QUESTION
    _progress(f"{label}: warmup compile")
    # Ragged engines compile the boundary launch per packed-capacity rung
    # (a doubling ladder keyed on how many admissions share the launch) —
    # warm the rungs a wave will actually hit (single admission, half
    # batch, full batch) so no rung compiles mid-measurement. Segmented /
    # dense engines compile per prompt bucket only: one request suffices.
    sizes = [1]
    if getattr(eng, "_ragged", False):
        sizes = sorted({1, max(2, eng.n_slots // 2), eng.n_slots})
    for n in sizes:
        futs = [
            eng.submit(question.format(i=900 + j),
                       max_new=min(budgets) if budgets else None)
            for j in range(n)
        ]
        [f.result() for f in futs]
    warm_stats = eng.stats()
    wave_tok_s: list[float] = []
    results: list[tuple] = []
    t0_all = time.perf_counter()
    for w in range(waves):
        _progress(f"{label} wave {w + 1}/{waves}: {n_requests} requests")
        t0 = time.perf_counter()
        futs = []
        for i in range(n_requests):
            q = question.format(i=w * n_requests + i)
            b = budgets[i % len(budgets)] if budgets else None
            futs.append((b, eng.submit(q, max_new=b)))
        wave = [(b, f.result()) for b, f in futs]
        wall = time.perf_counter() - t0
        wave_tok_s.append(sum(r["generated"] for _, r in wave) / wall)
        results.extend(wave)
    return wave_tok_s, results, time.perf_counter() - t0_all, warm_stats


def serving_benchmark(
    preset: str | None = None,
    precision: str = "int8",
    quant_mode: str = "w8a16",
    slots: int = 8,
    chunk: int = 32,
    kv_backend: str = "paged",
    n_requests: int = 35,
    max_new: int = 64,
    built: tuple | None = None,
    waves: int = 3,
    ragged: bool | None = None,
    prompt_pad: int = 0,
    budgets: tuple[int, ...] | None = None,
) -> dict[str, Any]:
    """Continuous-batching serving throughput (serve/continuous.py): N
    concurrent requests stream through the resident decode loop; reports
    aggregate generated tok/s, completed requests/s, and end-to-end request
    latency percentiles (queue + decode). The reference has no serving path
    at all — its fabric never carried model traffic (SURVEY.md §2.3).

    ``ragged`` passes through to the engine (None = the engine default:
    ragged boundary launches on paged backends; False = the segmented
    per-request-prefill arm — the ragged ablation's baseline).
    ``prompt_pad`` appends that many filler characters to every question
    (one fixed bucket — the prefill-heavy batch shape); ``budgets`` cycles
    per-request max_new caps (the 50/50 mixed shape).

    Variance protocol (round 4): the round-3 single 24-request burst swung
    ±40% run to run — too noisy to gate optimizations. Now ``waves``
    independent bursts of ``n_requests`` run back to back (105 requests
    total at the defaults) and the headline is the MEDIAN wave's aggregate
    tok/s, with the min/max spread reported alongside so any residual
    noise is visible in the artifact itself."""
    from edgemesh.agents.orchestrator import Agent
    from edgemesh.models.tokenizer import ByteTokenizer
    from edgemesh.serve.continuous import ContinuousEngine

    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    if built is not None:
        cfg, params = built
        if precision == "int8":
            cfg = cfg.replace(quant_mode=quant_mode)
    else:
        cfg, params = _build(preset, precision, quant_mode)
    agent = Agent(
        role="qa", cfg=cfg, params=params, tokenizer=ByteTokenizer(),
        sampling=SamplingParams(
            max_new_tokens=max_new, temperature=0.7, top_k=50, top_p=0.9,
            repetition_penalty=1.2, do_sample=True,
        ),
        prefix_cache=False,
    )
    # Fresh registry per run: the "obs" block below must describe THIS
    # engine's traffic, not every serving stage sharing the process default.
    from edgemesh.obs import Registry

    eng = ContinuousEngine(agent, slots=slots, chunk=chunk,
                           kv_backend=kv_backend, registry=Registry(),
                           ragged=ragged)
    try:
        import numpy as np

        question = _WAVE_QUESTION + ("x" * prompt_pad if prompt_pad else "")
        wave_tok_s, tagged, wall_all, warm_stats = _run_waves(
            eng, n_requests, waves, budgets=list(budgets) if budgets else None,
            label=f"serving/{kv_backend} slots={slots}"
            + (" ragged" if getattr(eng, "_ragged", False) else ""),
            question=question,
        )
        results = [r for _, r in tagged]
        generated = sum(r["generated"] for r in results)
        lats = [_e2e_latency(r) for r in results]
        tok_s = float(np.median(wave_tok_s))
        spread = (
            (max(wave_tok_s) - min(wave_tok_s)) / tok_s if tok_s else 0.0
        )
        # Engine counters accumulate from start; report the timed window's
        # delta so the warmup requests (up to three rungs' worth on ragged
        # engines) don't skew the diagnosis keys.
        stats = eng.stats()
        for k in ("requests", "segments", "admitted_mid_flight",
                  "ragged_boundaries", "ragged_prefill_tokens",
                  "ragged_decode_tokens"):
            if k in stats:
                stats[k] -= warm_stats.get(k, 0)
        _progress(
            f"serving/{kv_backend}: median {tok_s:.1f} tok/s over {waves} "
            f"waves (spread {100 * spread:.0f}%), "
            f"{len(results) / wall_all:.2f} req/s"
        )
        return {
            "metric": f"serving_tok_s_{preset}_{precision}_{kv_backend}",
            "value": round(tok_s, 2),
            "unit": "tok/s/chip",
            "wave_tok_s": [round(t, 2) for t in wave_tok_s],
            "spread_pct": round(100 * spread, 1),
            "req_s": round(len(results) / wall_all, 3),
            "generated": generated,
            "latency_s_p50": round(float(np.percentile(lats, 50)), 4),
            "latency_s_p95": round(float(np.percentile(lats, 95)), 4),
            "stats": stats,
            # The obs view of the same run: TTFT/queue-wait/inter-token
            # aggregates from the engine's span tracker (compact form — the
            # full histograms ride /metrics, not the bench artifact).
            "obs": eng.obs.registry.summary(prefix="edgemesh_"),
            # Compute-ledger rollup (obs/compute.py): per-boundary device
            # time, cost-model flops/bytes, and roofline for THIS run's
            # launches. None when the ledger is disabled
            # (EDGEMESH_COMPUTE_SAMPLE=0 — the overhead-gate off arm).
            "compute": eng.compute.rollup() or None,
            # Pool-ledger rollup (obs/memory.py): peak occupancy, the
            # per-tenant split, and leak/conservation counters for THIS
            # run. None on dense backends or with the ledger disabled
            # (EDGEMESH_MEM_LEDGER=0 — the overhead-gate off arm).
            "mem": eng.mem.rollup() or None,
            # Quality-tracker rollup (obs/quality.py): per-request answer
            # confidence/entropy EWMAs for THIS run's traffic. None with
            # EDGEMESH_BENCH_QUALITY=0.
            "quality": bench_quality_block(eng.quality.rollup()),
        }
    finally:
        eng.close()


def ragged_ablation_benchmark(
    preset: str | None = None,
    precision: str = "int8",
    quant_mode: str = "w8a16",
    slots: int = 8,
    chunk: int = 32,
    built: tuple | None = None,
    waves: int = 2,
    n_requests: int = 24,
) -> dict[str, Any]:
    """Ragged-vs-segmented serving A/B across batch shapes (the ablation
    for ops/paged_attention.ragged_paged_attention): the SAME engine and
    workload, with only the boundary structure toggled — ``ragged=True``
    runs admission prefill + resident decode as ONE launch per segment
    boundary, ``ragged=False`` keeps the per-request donated prefills plus
    the trailing bridge (the pre-ragged wave structure).

    Three shapes bracket the mixing regimes:
    - ``decode_heavy``: short prompts, long budgets — admissions are rare,
      boundaries are almost pure bridge steps.
    - ``prefill_heavy``: padded prompts, tiny budgets — requests churn, so
      nearly every boundary carries admission chunks.
    - ``mixed_50_50``: budgets cycle (8, 96) — half the requests retire
      quickly and back-fill, so prefill chunks and resident decode rows
      genuinely share launches.

    Keys: ``serving_{ragged|segmented}_{shape}_tok_s`` plus the
    ``ragged_over_segmented_{shape}`` ratio (the PERFORMANCE.md pin:
    >= 1.0 at every shape)."""
    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    if built is None:
        built = _build(preset, precision, quant_mode)
    # The prefill-heavy pad scales with the model context so small presets
    # (tiny: 512) keep decode room after the engine's overshoot margin.
    pad = min(600, int(built[0].max_seq_len) // 4)
    shapes: dict[str, dict[str, Any]] = {
        "decode_heavy": dict(max_new=96, prompt_pad=0),
        "prefill_heavy": dict(max_new=8, prompt_pad=pad),
        "mixed_50_50": dict(max_new=96, budgets=(8, 96)),
    }
    out: dict[str, Any] = {"slots": slots, "chunk": chunk, "waves": waves}
    for shape, kw in shapes.items():
        for arm, ragged in (("ragged", True), ("segmented", False)):
            r = serving_benchmark(
                preset, precision, quant_mode, slots=slots, chunk=chunk,
                kv_backend="paged", n_requests=n_requests, built=built,
                waves=waves, ragged=ragged, **kw,
            )
            out[f"serving_{arm}_{shape}_tok_s"] = r["value"]
            if ragged:
                out[f"serving_ragged_{shape}_latency_s_p50"] = r["latency_s_p50"]
        seg = out[f"serving_segmented_{shape}_tok_s"]
        out[f"ragged_over_segmented_{shape}"] = (
            round(out[f"serving_ragged_{shape}_tok_s"] / seg, 3) if seg else 0.0
        )
        _progress(
            f"ragged-ablation/{shape}: ragged "
            f"{out[f'serving_ragged_{shape}_tok_s']} vs segmented {seg} tok/s "
            f"(x{out[f'ragged_over_segmented_{shape}']})"
        )
    return out


def admission_policy_benchmark(
    preset: str | None = None,
    precision: str = "int8",
    quant_mode: str = "w8a16",
    slots: int = 8,
    chunk: int = 32,
    kv_backend: str = "paged",
    n_requests: int = 36,
    built: tuple | None = None,
    waves: int = 3,
    budgets: tuple[int, ...] = (16, 64, 128),
) -> dict[str, Any]:
    """FIFO vs SJF admission on a MIXED workload (VERDICT r4 item 6): each
    wave cycles per-request budgets through ``budgets``, so short jobs queue
    behind long ones under FIFO. SJF orders admission by the known
    ``max_new`` — on this workload the 16-token jobs stop paying the
    128-token jobs' decode time in queue, which is where the serving p50
    (3.59 s against a 0.078 s TTFT in BENCH_r03) actually lives. Reports
    per-policy median-wave tok/s plus overall AND short-job latency
    percentiles — the SLO table in docs/SERVING.md reads straight from
    these keys."""
    from edgemesh.agents.orchestrator import Agent
    from edgemesh.models.tokenizer import ByteTokenizer
    from edgemesh.serve.continuous import ContinuousEngine

    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    if built is not None:
        cfg, params = built
        if precision == "int8":
            cfg = cfg.replace(quant_mode=quant_mode)
    else:
        cfg, params = _build(preset, precision, quant_mode)
    out: dict[str, Any] = {
        "budgets": list(budgets), "n_requests": n_requests, "waves": waves,
    }
    import numpy as np

    from edgemesh.obs import Registry

    for policy in ("fifo", "sjf"):
        agent = Agent(
            role="qa", cfg=cfg, params=params, tokenizer=ByteTokenizer(),
            sampling=SamplingParams(
                max_new_tokens=max(budgets), temperature=0.7, top_k=50,
                top_p=0.9, repetition_penalty=1.2, do_sample=True,
            ),
            prefix_cache=False,
        )
        eng = ContinuousEngine(agent, slots=slots, chunk=chunk,
                               kv_backend=kv_backend, admission=policy,
                               registry=Registry())
        try:
            wave_tok_s, tagged, _, _ = _run_waves(
                eng, n_requests, waves, budgets=budgets,
                label=f"admission/{policy}",
            )
            lat_all = [_e2e_latency(r) for _, r in tagged]
            lat_short = [
                _e2e_latency(r) for b, r in tagged if b == min(budgets)
            ]
            out[f"{policy}_tok_s"] = round(float(np.median(wave_tok_s)), 2)
            out[f"{policy}_latency_s_p50"] = round(float(np.percentile(lat_all, 50)), 4)
            out[f"{policy}_latency_s_p95"] = round(float(np.percentile(lat_all, 95)), 4)
            out[f"{policy}_short_latency_s_p50"] = round(float(np.percentile(lat_short, 50)), 4)
            out[f"{policy}_short_latency_s_p95"] = round(float(np.percentile(lat_short, 95)), 4)
            _progress(
                f"admission/{policy}: {out[f'{policy}_tok_s']} tok/s, "
                f"p50 {out[f'{policy}_latency_s_p50']}s "
                f"(short p50 {out[f'{policy}_short_latency_s_p50']}s)"
            )
        finally:
            eng.close()
    return out


def _build_tp_engine(cfg, params, tp: int, collective_mode: str,
                     collective_dtype: str):
    """One definition of the bench's tp-engine construction: validates the
    device budget up front (a missing-chips failure should read as capacity,
    not a shard_map trace error) and leaves attention_impl to the engine's
    platform default (flash on real TPU, cfg's setting on the CPU mesh)."""
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.tp_infer import TPInferenceEngine

    have = jax.device_count()
    if have < tp:
        raise RuntimeError(
            f"tp{tp} stage needs {tp} devices, have {have} (run in a "
            f"pod-slice window, or EDGEMESH_BENCH_TP8=0 to skip)"
        )
    return TPInferenceEngine(
        cfg, params, build_mesh(dp=1, tp=tp),
        collective_mode=collective_mode, comm_dtype=collective_dtype,
    )


def tp_serving_benchmark(
    preset: str | None = None,
    precision: str = "int8",
    quant_mode: str = "w8a16",
    tp: int = 8,
    collective_mode: str = "qpsum_overlap",
    collective_dtype: str = "int8",
    slots: int = 8,
    chunk: int = 32,
    n_requests: int = 35,
    max_new: int = 64,
    built: tuple | None = None,
    waves: int = 3,
) -> dict[str, Any]:
    """Continuous-batching serving throughput THROUGH the tensor-parallel
    shard_map engine (parallel/tp_infer.py) — the ``serving_tp8_tok_s``
    headline. Same wave protocol as :func:`serving_benchmark`; the engine
    runs the dense backend with the tp engine's quantized/overlapped
    collective joins (``collective_mode``/``collective_dtype``), and the
    artifact carries the exact wire bytes the joins shipped
    (edgemesh_collective_bytes_total)."""
    from edgemesh.agents.orchestrator import Agent
    from edgemesh.models.tokenizer import ByteTokenizer
    from edgemesh.obs import Registry
    from edgemesh.serve.continuous import ContinuousEngine

    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    if built is not None:
        cfg, params = built
        if precision == "int8":
            cfg = cfg.replace(quant_mode=quant_mode)
    else:
        cfg, params = _build(preset, precision, quant_mode)
    tp_eng = _build_tp_engine(cfg, params, tp, collective_mode, collective_dtype)
    agent = Agent(
        role="qa", cfg=cfg, params=params, tokenizer=ByteTokenizer(),
        sampling=SamplingParams(
            max_new_tokens=max_new, temperature=0.7, top_k=50, top_p=0.9,
            repetition_penalty=1.2, do_sample=True,
        ),
        prefix_cache=False,
    )
    registry = Registry()
    eng = ContinuousEngine(agent, slots=slots, chunk=chunk,
                           kv_backend="dense", registry=registry,
                           tp_engine=tp_eng)
    try:
        import numpy as np

        wave_tok_s, tagged, wall_all, _ = _run_waves(
            eng, n_requests, waves,
            label=f"serving/tp{tp} {collective_mode}/{collective_dtype}",
        )
        results = [r for _, r in tagged]
        lats = [_e2e_latency(r) for r in results]
        tok_s = float(np.median(wave_tok_s))
        snap = registry.snapshot()
        wire = sum(
            s["value"]
            for s in snap.get("edgemesh_collective_bytes_total", {}).get(
                "samples", [])
        )
        _progress(
            f"serving/tp{tp}: median {tok_s:.1f} tok/s "
            f"({collective_mode}/{collective_dtype}, "
            f"{wire / 1e6:.1f} MB collective wire)"
        )
        return {
            "metric": f"serving_tp{tp}_tok_s",
            "value": round(tok_s, 2),
            "unit": "tok/s",
            "tp": tp,
            "collective_mode": collective_mode,
            "collective_dtype": collective_dtype,
            "wave_tok_s": [round(t, 2) for t in wave_tok_s],
            "req_s": round(len(results) / wall_all, 3),
            "latency_s_p50": round(float(np.percentile(lats, 50)), 4),
            "latency_s_p95": round(float(np.percentile(lats, 95)), 4),
            "collective_bytes": int(wire),
            "stats": eng.stats(),
        }
    finally:
        eng.close()


def collective_ablation_benchmark(
    preset: str | None = None,
    precision: str = "int8",
    quant_mode: str = "w8a16",
    tp: int = 8,
    batches: tuple[int, ...] = (8, 32),
    decode_steps: int = 32,
    built: tuple | None = None,
    repeats: int = 2,
) -> dict[str, Any]:
    """bf16-psum vs int8-qpsum vs qpsum+overlap on the SAME tp mesh and
    params: per-arm decode tok/s at each batch, the ratio keys the
    PERFORMANCE.md targets pin (qpsum >= psum, overlap >= qpsum), and the
    quality delta — greedy-token agreement of each quantized arm against
    the bf16-psum arm's tokens (>= 0.999 is the ship gate: EQuARX-grade
    wire quantization must be invisible to sampling)."""
    import numpy as np

    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    if built is not None:
        cfg, params = built
        if precision == "int8":
            cfg = cfg.replace(quant_mode=quant_mode)
    else:
        cfg, params = _build(preset, precision, quant_mode)
    arms = (
        ("psum", "psum", "bf16"),
        ("qpsum", "qpsum", "int8"),
        ("qpsum_overlap", "qpsum_overlap", "int8"),
    )
    out: dict[str, Any] = {"collective_tp": tp, "collective_batches": list(batches)}
    tokens_by_arm: dict[tuple, Any] = {}
    for name, mode, dtype in arms:
        eng = _build_tp_engine(cfg, params, tp, mode, dtype)
        acct = eng.collective_accounting(batch=1)
        out[f"collective_{name}_bytes_per_step"] = acct["bytes_per_step"]
        for b in batches:
            prompts = jax.random.randint(
                jax.random.PRNGKey(7), (b, 16), 0, cfg.vocab_size
            )
            lengths = jnp.full((b,), 16, jnp.int32)
            _progress(f"collective/{name} b{b}: warmup compile")
            toks = eng.generate_greedy(prompts, lengths, max_new=decode_steps)
            toks.block_until_ready()
            tokens_by_arm[(name, b)] = np.asarray(toks)
            best = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                eng.generate_greedy(prompts, lengths,
                                    max_new=decode_steps).block_until_ready()
                best = min(best, time.perf_counter() - t0)
            out[f"collective_{name}_b{b}_tok_s"] = round(
                b * decode_steps / best, 2
            )
        del eng
    for b in batches:
        base = out[f"collective_psum_b{b}_tok_s"]
        ref = tokens_by_arm[("psum", b)]
        for name in ("qpsum", "qpsum_overlap"):
            v = out[f"collective_{name}_b{b}_tok_s"]
            out[f"{name}_over_psum_b{b}"] = round(v / base, 3) if base else 0.0
            out[f"{name}_greedy_agreement_b{b}"] = round(
                float(np.mean(tokens_by_arm[(name, b)] == ref)), 4
            )
        out[f"overlap_over_qpsum_b{b}"] = round(
            out[f"collective_qpsum_overlap_b{b}_tok_s"]
            / out[f"collective_qpsum_b{b}_tok_s"], 3,
        ) if out[f"collective_qpsum_b{b}_tok_s"] else 0.0
        _progress(
            f"collective-ablation b{b}: psum {base} / qpsum "
            f"{out[f'collective_qpsum_b{b}_tok_s']} / overlap "
            f"{out[f'collective_qpsum_overlap_b{b}_tok_s']} tok/s, "
            f"agreement {out[f'qpsum_greedy_agreement_b{b}']}"
        )
    return out


_T0 = time.perf_counter()
LAST_PROGRESS = time.monotonic()
_ARCHIVE_PATH = None  # per-run continuous-archive target (emit_partial)

# Latest complete-so-far headline result. Updated (and re-printed to stdout)
# after EVERY finished stage so a stall mid-run still leaves the driver a
# parseable JSON line — round 2's bench lost all its numbers to a tunnel
# wedge precisely because results only printed at the very end.
_PARTIAL: dict[str, Any] = {}


def _progress(msg: str) -> None:
    """Stderr breadcrumbs so a hung run (e.g. an unresponsive TPU tunnel —
    observed mid-round-2: even trivial dispatches blocked forever) shows
    WHERE it stopped in the driver's captured tail."""
    global LAST_PROGRESS
    LAST_PROGRESS = time.monotonic()
    print(f"[bench +{time.perf_counter() - _T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def emit_partial(result: dict[str, Any]) -> None:
    """Record ``result`` as the best-known headline and print it to stdout.

    The driver parses the last JSON line on stdout; printing after each
    stage means the parseable answer monotonically improves instead of
    existing only at a finish line the tunnel may never let us reach.

    Rebinds (never mutates) the module global: the watchdog thread reads it
    concurrently, and an in-place clear()+update() would open a window where
    the watchdog sees a half-built dict (or dies iterating a mutating one)."""
    import json

    global _PARTIAL, _ARCHIVE_PATH
    _PARTIAL = dict(result)
    if "metric" in result:
        print(json.dumps(result), flush=True)
        # Continuous archiving (bench.py sets EDGEMESH_BENCH_ARCHIVE=1): one
        # dated file per run, rewritten after every stage — a watchdog
        # stall-exit or stage wedge still leaves the freshest partial on
        # disk for the stale-fallback corpus. Env-gated so CPU tests
        # calling emit_partial never litter artifacts/ with bogus entries.
        if os.environ.get("EDGEMESH_BENCH_ARCHIVE") == "1":
            from pathlib import Path

            from edgemesh.utils.record import archive_result

            _ARCHIVE_PATH = archive_result(
                result, "bench", Path(__file__).parent.parent / "artifacts",
                path=_ARCHIVE_PATH,
            ) or _ARCHIVE_PATH


def start_stall_watchdog(timeout_s: float | None = None) -> None:
    """Daemon thread that hard-exits (rc=3) if no benchmark stage completes
    for ``timeout_s`` seconds. The axon TPU tunnel has been observed to
    block forever on a single dispatch; without this a driver-run bench
    hangs until an external kill with no diagnostic at all. Before exiting
    it re-prints the partial-results line (if any stage finished) so the
    stall costs the remaining stages, not the whole run."""
    import json
    import threading

    timeout_s = timeout_s or float(os.environ.get("EDGEMESH_BENCH_STALL_TIMEOUT", "900"))

    def watch():
        while True:
            time.sleep(30)
            stalled = time.monotonic() - LAST_PROGRESS
            if stalled > timeout_s:
                print(
                    f"[bench] STALLED: no stage progress for {stalled:.0f}s "
                    "(device tunnel unresponsive?) — aborting",
                    file=sys.stderr, flush=True,
                )
                partial = _PARTIAL  # snapshot the rebound-not-mutated global
                if "metric" in partial:
                    out = dict(partial)
                    out["stalled_after_s"] = round(time.perf_counter() - _T0, 1)
                    print(json.dumps(out), flush=True)
                os._exit(3)

    threading.Thread(target=watch, daemon=True).start()


def _tree_bytes(params) -> int:
    # int4 kernels are nibble-packed into int8 (ops/int4.py), so itemsize
    # accounting is already honest for every dtype in the tree.
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


def _build(preset: str, precision: str, quant_mode: str):
    from edgemesh.utils.platform import tree_sync

    _progress(f"build {preset}/{precision}: init_params")
    cfg = config_for_family("llama", **PRESETS[preset])
    if preset != "tiny":
        cfg = cfg.replace(dtype="bfloat16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    if precision == "int8":
        from edgemesh.ops.int8 import quantize_embedding

        _progress("quantize_params")
        params = quantize_embedding(quantize_params(params))
        params = jax.tree.map(lambda x: jax.device_put(x), params)
        cfg = cfg.replace(quant_mode=quant_mode)
    elif precision in ("int4", "int4_g64"):
        from edgemesh.ops.int4 import quantize_params_int4
        from edgemesh.ops.int8 import quantize_embedding

        _progress(f"quantize_params_{precision}")
        # "int4" = per-channel scales (fastest: fused unpack, one epilogue
        # scale); "int4_g64" = 64-wide grouped scales — the product default
        # (ModelSpec.int4_group_size), whose segmented contraction measures
        # slower. The headline reports BOTH so the shipped configuration is
        # never an unmeasured one.
        g = 64 if precision == "int4_g64" else 0
        params = quantize_embedding(quantize_params_int4(params, group_size=g))
        params = jax.tree.map(lambda x: jax.device_put(x), params)
    tree_sync(params)
    _progress("params resident on device")
    return cfg, params


def decode_benchmark(
    preset: str | None = None,
    precision: str | None = None,
    quant_mode: str = "w8a16",
    batch: int = 8,
    prompt_len: int = 32,
    decode_steps: int = 128,
    repeats: int = 3,
    built: tuple | None = None,
    kv_backend: str = "dense",
    approx_top_k: bool = False,
) -> dict[str, Any]:
    """One (precision, quant_mode, batch, kv_backend) point: best-of-`repeats`
    decode tok/s with TTFT and bandwidth-utilization accounting. ``built``
    reuses a (cfg, params) pair from a previous call (headline_benchmark
    builds each precision once — a 1B init+quantize+transfer is not free).
    ``kv_backend="paged"`` runs the paged KV cache + page-table-walking Pallas
    kernel (runtime/paged_generate.py, the HeadInfer-analog config of
    BASELINE.json)."""
    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    precision = precision or os.environ.get("EDGEMESH_BENCH_PRECISION", "int8")
    if preset not in PRESETS:
        raise ValueError(f"unknown preset {preset!r}; choose from {sorted(PRESETS)}")
    if built is not None:
        cfg, params = built
        if precision == "int8":
            cfg = cfg.replace(quant_mode=quant_mode)
    else:
        cfg, params = _build(preset, precision, quant_mode)

    sampling = SamplingParams(
        max_new_tokens=decode_steps, temperature=0.7, top_k=50, top_p=0.9,
        repetition_penalty=1.2, do_sample=True, approx_top_k=approx_top_k,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size, jnp.int32
    )
    lengths = jnp.full((batch,), prompt_len, jnp.int32)
    if kv_backend in ("paged", "paged_int8"):
        from edgemesh.runtime.paged_generate import generate_paged

        if kv_backend == "paged_int8":
            run = partial(generate_paged, kv_quant=True)
        else:
            run = generate_paged
    elif kv_backend == "quant":
        from edgemesh.runtime.quant_kv import generate_quant_kv

        run = generate_quant_kv
    elif kv_backend == "dense":
        run = generate
    else:
        raise ValueError(f"unknown kv_backend {kv_backend!r}")

    # Warmup compiles prefill + decode loop; then take the best of `repeats`.
    _progress(f"{precision}/{quant_mode}/{kv_backend} b{batch}: warmup compile")
    run(cfg, params, tokens, lengths, sampling)
    _progress("warmup done; timing")
    # Ambient compute ledger: the runtime paths route their prefill/decode
    # launches through it, so the artifact carries cost_analysis-backed
    # flops/bytes + measured launch times for the exact boundaries timed.
    from edgemesh.obs import ComputeLedger, Registry, ledger_scope

    ledger = ComputeLedger(registry=Registry(), engine="bench-decode",
                           sample=1)
    best_tps, best_ttft = 0.0, float("inf")
    with ledger_scope(ledger):
        for _ in range(repeats):
            r = run(cfg, params, tokens, lengths, sampling)
            best_tps = max(best_tps, r.decode_tok_s)
            best_ttft = min(best_ttft, r.prefill_time_s)
    # Pop (not get): a headline run hits this 7+ times and traces are large —
    # capture exactly one representative decode (tracing.py's own contract).
    profile_dir = os.environ.pop("EDGEMESH_BENCH_PROFILE", None)
    if profile_dir:
        from edgemesh.utils.tracing import capture_profile

        with capture_profile(profile_dir):
            run(cfg, params, tokens, lengths, sampling)
        _progress(f"profile captured -> {profile_dir}")
    _progress(f"{precision}/{quant_mode}/{kv_backend} b{batch}: {best_tps:.1f} tok/s")

    # Roofline: each decode step streams the full weight set from HBM once
    # (batch rides in the MXU's other operand dim), so steps/sec x
    # weight-bytes is the effective read bandwidth.
    weight_bytes = _tree_bytes(params)
    steps_per_s = best_tps / batch
    eff_gbs = steps_per_s * weight_bytes / 1e9
    baseline = REFERENCE_TOK_S.get(precision, REFERENCE_TOK_S["bf16"])
    return {
        "metric": f"decode_tok_s_llama3.2-1b_{precision}_b{batch}",
        "value": round(best_tps, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(best_tps / baseline, 3),
        "ttft_s": round(best_ttft, 4),
        "decode_steps": decode_steps,
        "batch": batch,
        "weight_gb": round(weight_bytes / 1e9, 3),
        "hbm_eff_gbs": round(eff_gbs, 1),
        "hbm_util": round(eff_gbs / HBM_PEAK_GBS, 3),
        "compute": ledger.rollup() or None,
    }


def router_overhead_benchmark(n_requests: int = 40, max_new: int = 8) -> dict[str, Any]:
    """The fleet router's tax: direct-to-replica vs through-router request
    latency (p50/p99) against ONE local replica, so the delta is purely the
    router's own work — balancer pick, registry bookkeeping, obs recording,
    and one extra loopback HTTP hop. No retries/hedges fire (the replica is
    healthy), which is the point: this measures the overhead every request
    pays, not the failure machinery.

    Four arms: ``direct`` (no router), ``routed`` (router, tracing sampled
    OUT — zero span I/O, flight recorder detached), ``traced`` (tracing
    sampled in: router + replica both flush span JSONL records), and
    ``recorded`` (tracing back OFF, the flight recorder attached), so
    ``tracing_overhead_*`` prices the trace substrate and
    ``recorder_overhead_*`` prices the always-on flight ring — the
    "cheap enough to never turn off" claim as a tracked number
    (acceptance: recorder p50 within 2% of the recorder-off arm). The router's
    obs registry summary and ONE fully assembled cross-process trace (the
    last traced request, router + replica spans, skew-corrected, with its
    critical path) ride the result JSON — the artifact shows both the
    counters and a real trace that produced the numbers. Tiny synthetic
    model — the replica's decode time is the same constant in every arm
    and cancels in the deltas."""
    import tempfile
    from pathlib import Path

    from edgemesh.agents.orchestrator import Ensemble, build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
    from edgemesh.fleet import FleetRouter, HttpTransport, ReplicaRegistry, serve_fleet
    from edgemesh.obs import Registry, load_trace
    from edgemesh.serve import serve_rest
    from edgemesh.utils.tracing import JsonlLogger

    import numpy as np

    agent = build_agent(AgentSpec(
        role="qa", model=ModelSpec(),
        sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                repetition_penalty=1.0),
    ))
    log_dir = Path(tempfile.mkdtemp(prefix="edgemesh-bench-trace-"))
    replica_log = log_dir / "replica.jsonl"
    router_log = log_dir / "router.jsonl"
    # Continuous engine so the replica emits real queued/prefill/decode
    # spans — the assembled sample trace shows the full pipeline.
    # Local trace_sample=0: the DIRECT arm (header-less requests) must not
    # pay span I/O the routed arm skips, or the overhead delta is biased.
    # The traced arm still flushes — the router's header carries sampled=1,
    # which overrides the replica's local rate.
    # flight_capacity=0: the routed/traced arms run with the recorder
    # detached; the "recorded" arm attaches one live below, so the A/B
    # isolates exactly the ring's append cost.
    srv = serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1", port=0,
                     block=False, continuous=True, batch=2,
                     span_log=replica_log, trace_sample=0.0,
                     flight_capacity=0)
    replica_url = f"http://127.0.0.1:{srv.server_address[1]}"
    obs = Registry()
    registry = ReplicaRegistry([("r0", replica_url)])
    # trace_sample starts at 0 (the "routed" arm measures the router with
    # span I/O off); the "traced" arm flips it to 1.0 — the attribute is
    # read per request, which is exactly what makes the A/B clean.
    router = FleetRouter(registry, balancer="least_outstanding",
                         obs_registry=obs, span_log=router_log,
                         trace_sample=0.0)
    front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
    transport = HttpTransport()

    def measure(url: str, label: str) -> list[float]:
        payload = {"question": "benchmark question, please answer?"}
        _progress(f"router-overhead: warmup via {label}")
        status, _ = transport.post_json(url, payload, timeout_s=600.0)
        if status != 200:
            raise RuntimeError(f"{label} warmup answered {status}")
        lats = []
        for _ in range(n_requests):
            t0 = time.perf_counter()
            status, _ = transport.post_json(url, payload, timeout_s=600.0)
            if status != 200:
                raise RuntimeError(f"{label} request answered {status}")
            lats.append(time.perf_counter() - t0)
        return lats

    try:
        routed_url = f"http://127.0.0.1:{front.server_address[1]}/generate"
        direct = measure(f"{replica_url}/generate", "direct")
        routed = measure(routed_url, "router")
        # Ledger-off arm: the replica engine's compute ledger disabled
        # (the EDGEMESH_COMPUTE_SAMPLE=0 configuration) under otherwise
        # identical conditions — the delta vs `routed` is the ledger's
        # whole steady-state cost (two counter bumps per launch plus one
        # sampled fence in N). Acceptance gate (PERFORMANCE.md): routed
        # p50 within 2% of this arm.
        eng = srv.batcher
        eng.compute.enabled = False
        ledgeroff = measure(routed_url, "router, ledger off")
        eng.compute.enabled = True
        # Mem-ledger-off arm (EDGEMESH_MEM_LEDGER=0 configuration): the
        # delta vs `routed` is the pool ledger's whole steady-state cost —
        # one attributed dict update per pool transition, all under the
        # engine lock the transition already holds. Gate (PERFORMANCE.md
        # "The memory observatory"): routed p50 within 2% of this arm.
        eng.mem.enabled = False
        memledgeroff = measure(routed_url, "router, mem ledger off")
        eng.mem.enabled = True
        # Quality-off arm (EDGEMESH_QUALITY=0 configuration): the device
        # tail rides the decode loop either way (it is fused into the
        # sampler's softmax and cannot be toggled without a recompile), so
        # this arm prices exactly what the flag controls — the host-side
        # sink: four float accumulations per segment row plus the retire
        # bookkeeping. Gate (PERFORMANCE.md "The quality observatory"):
        # routed p50 within 2% of this arm.
        eng.quality.enabled = False
        qualityoff = measure(routed_url, "router, quality off")
        eng.quality.enabled = True
        router.trace_sample = 1.0
        traced = measure(routed_url, "router+tracing")
        # Recorder arm: tracing back OFF, the flight ring attached live —
        # the delta vs `routed` is the always-on recorder's whole cost.
        from edgemesh.obs.flight import FlightRecorder

        router.trace_sample = 0.0
        eng.obs.flight = FlightRecorder(registry=eng.obs.registry,
                                        snapshot_source=eng.load_digest)
        recorded = measure(routed_url, "router+recorder")
        ring_records = len(eng.obs.flight)

        def pct(xs, q):
            return round(float(np.percentile(xs, q)), 6)

        overhead_p50 = pct(routed, 50) - pct(direct, 50)
        tracing_p50 = pct(traced, 50) - pct(routed, 50)
        recorder_p50 = pct(recorded, 50) - pct(routed, 50)
        ledger_ratio = (
            round(pct(routed, 50) / pct(ledgeroff, 50), 4)
            if pct(ledgeroff, 50) else None
        )
        mem_ledger_ratio = (
            round(pct(routed, 50) / pct(memledgeroff, 50), 4)
            if pct(memledgeroff, 50) else None
        )
        quality_ratio = (
            round(pct(routed, 50) / pct(qualityoff, 50), 4)
            if pct(qualityoff, 50) else None
        )
        _progress(
            f"router-overhead: p50 {pct(direct, 50) * 1e3:.2f}ms direct vs "
            f"{pct(routed, 50) * 1e3:.2f}ms routed (+{overhead_p50 * 1e3:.2f}ms), "
            f"tracing +{tracing_p50 * 1e3:.2f}ms, "
            f"recorder +{recorder_p50 * 1e3:.2f}ms, "
            f"ledger ratio {ledger_ratio}"
        )
        # One real assembled trace rides the artifact: the last traced
        # request, stitched across the router and replica span logs.
        sample_trace = None
        router_recs = JsonlLogger(router_log).read()
        if router_recs:
            sample_trace = load_trace(
                router_recs[-1]["trace_id"], [router_log, replica_log]
            )
        return {
            "metric": "router_overhead_p50_s",
            "value": round(overhead_p50, 6),
            "unit": "s",
            "n_requests": n_requests,
            "direct_p50_s": pct(direct, 50),
            "direct_p99_s": pct(direct, 99),
            "routed_p50_s": pct(routed, 50),
            "routed_p99_s": pct(routed, 99),
            "overhead_p99_s": round(pct(routed, 99) - pct(direct, 99), 6),
            "traced_p50_s": pct(traced, 50),
            "traced_p99_s": pct(traced, 99),
            "tracing_overhead_p50_s": round(tracing_p50, 6),
            "tracing_overhead_p99_s": round(pct(traced, 99) - pct(routed, 99), 6),
            # The flight-recorder arm: absolute percentiles + the delta vs
            # the recorder-off routed arm. The acceptance gate
            # (PERFORMANCE.md): recorder p50 within 2% of recorder-off.
            "recorder_p50_s": pct(recorded, 50),
            "recorder_p99_s": pct(recorded, 99),
            "recorder_overhead_p50_s": round(recorder_p50, 6),
            "recorder_overhead_p99_s": round(pct(recorded, 99) - pct(routed, 99), 6),
            "recorder_ring_records": ring_records,
            # The compute-ledger arm: routed (ledger on, the default) vs
            # the same path with the ledger disabled. The gate
            # (PERFORMANCE.md "The compute observatory"): ratio <= 1.02.
            "ledgeroff_p50_s": pct(ledgeroff, 50),
            "ledgeroff_p99_s": pct(ledgeroff, 99),
            "ledger_overhead_p50_s": round(pct(routed, 50) - pct(ledgeroff, 50), 6),
            "ledger_overhead_ratio": ledger_ratio,
            # The pool-ledger arm: routed (mem ledger on, the default) vs
            # the same path with it disabled. The gate (PERFORMANCE.md
            # "The memory observatory"): ratio <= 1.02.
            "memledgeroff_p50_s": pct(memledgeroff, 50),
            "memledgeroff_p99_s": pct(memledgeroff, 99),
            "mem_ledger_overhead_p50_s": round(
                pct(routed, 50) - pct(memledgeroff, 50), 6),
            "mem_ledger_overhead_ratio": mem_ledger_ratio,
            # The quality-tracker arm: routed (tracker on, the default) vs
            # the same path with the host-side sink disabled. The gate
            # (PERFORMANCE.md "The quality observatory"): ratio <= 1.02.
            "qualityoff_p50_s": pct(qualityoff, 50),
            "qualityoff_p99_s": pct(qualityoff, 99),
            "quality_overhead_p50_s": round(
                pct(routed, 50) - pct(qualityoff, 50), 6),
            "quality_overhead_ratio": quality_ratio,
            "compute": eng.compute.rollup() or None,
            "mem": eng.mem.rollup() or None,
            "quality": bench_quality_block(eng.quality.rollup()),
            "sample_trace": sample_trace,
            # The obs view of the routed arms (counters + router histogram).
            "obs": obs.summary(prefix="edgemesh_fleet_"),
        }
    finally:
        front.shutdown()
        srv.shutdown()
        if srv.batcher is not None:
            srv.batcher.close()
        # The sample trace is already embedded in the result JSON; the
        # span logs themselves are scratch.
        import shutil

        shutil.rmtree(log_dir, ignore_errors=True)


def adaptive_router_benchmark(n_requests: int = 24, concurrency: int = 6,
                              max_new: int = 8, slow_layers: int = 6,
                              slow_hidden: int = 128,
                              slow_max_new: int = 32) -> dict[str, Any]:
    """Telemetry-driven routing vs least-outstanding on a SKEWED fleet.

    Three in-process continuous replicas: two fast (tiny default model) and
    one deliberately degraded (``slow_layers``/``slow_hidden`` + a
    ``slow_max_new`` token budget — genuinely slower prefill AND decode,
    the "one bad edge device" scenario of the profiling-driven-placement
    line). Two arms run the identical concurrent workload through the real
    fleet frontend:

    - ``least_outstanding``: the pre-telemetry default — queue depth is the
      only signal, so the idle slow replica keeps winning picks and every
      request routed there drags the tail.
    - ``telemetry`` + ``hedge_auto``: the zero-config adaptive router —
      replicas weighted by the load digests their ``/readyz`` bodies ship
      (refreshed by the health prober), hedge delay auto-tuned to the live
      decayed p95. No thresholds configured anywhere.

    Reported: p50/p99 per arm, the p99 ratio (the headline —
    ``adaptive_over_least_outstanding_p99`` > 1 means the telemetry loop
    wins), SLO goodput per arm against a target derived from the fast
    replicas' warmup latency, and how many requests each arm actually sent
    to the degraded replica (the mechanism, checkable from the artifact)."""
    import threading

    import numpy as np

    from edgemesh.agents.orchestrator import Ensemble, build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
    from edgemesh.fleet import (
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )
    from edgemesh.obs import Registry
    from edgemesh.serve import serve_rest

    transport = HttpTransport()

    def _replica(model: ModelSpec, budget: int):
        agent = build_agent(AgentSpec(
            role="qa", model=model,
            sampling=SamplingParams(max_new_tokens=budget, do_sample=False,
                                    repetition_penalty=1.0),
        ))
        return serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1",
                          port=0, block=False, continuous=True, batch=2,
                          registry=Registry(), trace_sample=0.0)

    _progress("adaptive-router: building 2 fast + 1 degraded replica")
    servers = {
        # Registered FIRST so least_outstanding's tie-break prefers it —
        # the worst case the telemetry balancer must route around.
        "slow": _replica(ModelSpec(num_layers=slow_layers,
                                   hidden_size=slow_hidden), slow_max_new),
        "fast-0": _replica(ModelSpec(), max_new),
        "fast-1": _replica(ModelSpec(), max_new),
    }
    urls = {rid: f"http://127.0.0.1:{srv.server_address[1]}"
            for rid, srv in servers.items()}
    payload = {"question": "benchmark question, please answer?"}

    def _percentile(xs, q):
        return round(float(np.percentile(xs, q)), 6)

    try:
        # Warm every replica (compiles + seeds its digest EWMAs) and
        # derive the SLO target from the FAST replicas' steady state.
        fast_lats = []
        for rid, url in urls.items():
            for _ in range(2):
                t0 = time.perf_counter()
                status, _ = transport.post_json(f"{url}/generate", payload,
                                                timeout_s=600.0)
                if status != 200:
                    raise RuntimeError(f"warmup on {rid} answered {status}")
                lat = time.perf_counter() - t0
            if rid.startswith("fast"):
                fast_lats.append(lat)  # second (post-compile) request only
        slo_target_s = max(4.0 * float(np.median(fast_lats)), 0.25)

        def run_arm(balancer: str, hedge_auto: bool):
            obs = Registry()
            registry = ReplicaRegistry(list(urls.items()))
            prober = HealthProber(registry, transport=transport,
                                  interval_s=0.25, obs_registry=obs).start()
            prober.probe_once()  # digests fresh before the first pick
            router = FleetRouter(
                registry, balancer=balancer, transport=transport,
                obs_registry=obs, hedge_auto=hedge_auto,
                attempt_timeout_s=300.0, default_deadline_s=600.0,
            )
            front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
            gen_url = f"http://127.0.0.1:{front.server_address[1]}/generate"
            lats: list[float] = []
            errors: list[str] = []
            lock = threading.Lock()
            remaining = list(range(n_requests))

            def worker():
                while True:
                    with lock:
                        if not remaining:
                            return
                        i = remaining.pop()
                    t0 = time.perf_counter()
                    try:
                        status, body = transport.post_json(
                            gen_url, payload, timeout_s=600.0)
                    except Exception as e:
                        # A transport-level failure must fail the ARM, not
                        # silently shrink the sample the percentiles and
                        # goodput are computed over.
                        with lock:
                            errors.append(f"request {i}: {e}")
                        continue
                    lat = time.perf_counter() - t0
                    with lock:
                        if status != 200:
                            errors.append(f"request {i}: {status} {body}")
                        else:
                            lats.append(lat)

            threads = [threading.Thread(target=worker, daemon=True)
                       for _ in range(concurrency)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            prober.stop()
            front.shutdown()
            if errors:
                raise RuntimeError(f"{balancer} arm failed: {errors[:3]}")
            summary = obs.summary(prefix="edgemesh_fleet_")
            routed_slow = summary.get(
                'edgemesh_fleet_routed_total{replica="slow"}', 0)
            hedged = sum(v for k, v in summary.items()
                         if k.startswith("edgemesh_fleet_hedged_total"))
            goodput = sum(1 for v in lats if v <= slo_target_s) / len(lats)
            return {
                "p50_s": _percentile(lats, 50),
                "p99_s": _percentile(lats, 99),
                "goodput": round(goodput, 4),
                "routed_to_slow": routed_slow,
                "hedged": hedged,
            }

        _progress("adaptive-router: arm 1/2 least_outstanding")
        lo = run_arm("least_outstanding", hedge_auto=False)
        _progress("adaptive-router: arm 2/2 telemetry + auto hedge")
        ad = run_arm("telemetry", hedge_auto=True)
        ratio = round(lo["p99_s"] / ad["p99_s"], 4) if ad["p99_s"] else None
        _progress(
            f"adaptive-router: p99 {lo['p99_s'] * 1e3:.0f}ms LO vs "
            f"{ad['p99_s'] * 1e3:.0f}ms adaptive ({ratio}x), goodput "
            f"{lo['goodput']:.2f} -> {ad['goodput']:.2f}"
        )
        return {
            "metric": "adaptive_over_least_outstanding_p99",
            "value": ratio,
            "unit": "x",
            "n_requests": n_requests,
            "concurrency": concurrency,
            "slo_target_s": round(slo_target_s, 6),
            "least_outstanding_p50_s": lo["p50_s"],
            "least_outstanding_p99_s": lo["p99_s"],
            "least_outstanding_goodput": lo["goodput"],
            "least_outstanding_routed_to_slow": lo["routed_to_slow"],
            "adaptive_p50_s": ad["p50_s"],
            "adaptive_p99_s": ad["p99_s"],
            "adaptive_goodput": ad["goodput"],
            "adaptive_routed_to_slow": ad["routed_to_slow"],
            "adaptive_hedged": ad["hedged"],
        }
    finally:
        for srv in servers.values():
            srv.shutdown()
            if srv.batcher is not None:
                srv.batcher.close()


def load_curve_benchmark(n_replicas: int = 2, duration_s: float = 4.0,
                         max_new: int = 8,
                         point_factors: tuple[float, ...] = (0.5, 1.0, 2.0, 4.0),
                         ) -> dict[str, Any]:
    """The load observatory's bench stage: goodput vs offered load.

    Boots ``n_replicas`` in-process continuous replicas (tiny synthetic
    model — the CURVE SHAPE is under test, not the kernels) behind the
    real fleet frontend, estimates the fleet's capacity from warm
    latency, then drives the frontend OPEN-LOOP (edgemesh/loadgen/) at
    ``point_factors`` multiples of that capacity with a two-tenant
    Poisson mix (interactive + batch). Reported: one goodput point per
    offered load (aggregate + per tenant), the saturation knee, and
    whether the curve collapsed past it — the headline is
    ``load_curve_knee_rps``, the offered load this stack should be run
    at. A closed-loop driver cannot produce any of these numbers:
    coordinated omission hides exactly the past-knee region
    (docs/OBSERVABILITY.md "The load observatory")."""
    from edgemesh.agents.orchestrator import Ensemble, build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
    from edgemesh.fleet import FleetRouter, HttpTransport, ReplicaRegistry, serve_fleet
    import threading

    from edgemesh.loadgen import (
        LengthMix,
        OpenLoopGenerator,
        PoissonProcess,
        TenantSpec,
        Workload,
        http_target,
        run_curve,
    )
    from edgemesh.obs import Registry
    from edgemesh.serve import serve_rest

    transport = HttpTransport()

    def _replica():
        agent = build_agent(AgentSpec(
            role="qa", model=ModelSpec(),
            sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                    repetition_penalty=1.0),
        ))
        # Paged backend so the memory observatory has a pool to attribute:
        # the curve then carries occupancy + exhaustion forecast per point
        # (the forecast AT the knee is the capacity-planning number).
        return serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1",
                          port=0, block=False, continuous=True, batch=2,
                          kv_backend="paged", registry=Registry(),
                          trace_sample=0.0)

    _progress(f"load-curve: building {n_replicas} in-process replicas")
    servers = [_replica() for _ in range(n_replicas)]
    front = None
    try:
        urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
        for url in urls:
            status, _ = transport.post_json(
                f"{url}/generate",
                {"question": "load curve warmup question?"},
                timeout_s=600.0)
            if status != 200:
                raise RuntimeError(f"warmup on {url} answered {status}")

        obs = Registry()
        registry = ReplicaRegistry(
            (f"replica-{i}", url) for i, url in enumerate(urls)
        )
        router = FleetRouter(registry, balancer="least_outstanding",
                             transport=transport, obs_registry=obs,
                             attempt_timeout_s=300.0,
                             default_deadline_s=600.0, max_attempts=1)
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
        gen_url = f"http://127.0.0.1:{front.server_address[1]}/generate"
        target = http_target(gen_url, timeout_s=600.0)

        # Narrow prompt mix: the curve stage measures the SERVING stack's
        # shape, and a fresh prompt-length compile bucket mid-point would
        # masquerade as a latency cliff. (Long-tail mixes are the e2e
        # tests' and the CLI's beat.)
        prompt_mix = LengthMix(median=80, sigma=0.0, lo=80, hi=80)

        def make_workload(rate: float, seed: int = 5) -> Workload:
            return Workload([
                TenantSpec(name="interactive",
                           arrival=PoissonProcess(max(0.1, rate * 2 / 3),
                                                  seed=11),
                           prompt_mix=prompt_mix, lane="interactive"),
                TenantSpec(name="batch",
                           arrival=PoissonProcess(max(0.1, rate / 3),
                                                  seed=13),
                           prompt_mix=prompt_mix, lane="batch"),
            ], seed=seed)

        # Warm the compile ladder with WORKLOAD-SHAPED prompts (session
        # prompts tokenize differently from the warmup constant), then
        # calibrate capacity + loaded latency with a short CLOSED-loop
        # probe — sequential warm latency overestimates capacity badly
        # once the generator, frontend, and engines share one GIL.
        _progress("load-curve: compile-ladder warm pass")
        OpenLoopGenerator(target, make_workload(3.0, seed=7).build_schedule(4.0),
                          slo_latency_s=600.0, duration_s=4.0).run()
        _progress("load-curve: closed-loop capacity calibration")
        cal_lats: list[float] = []
        cal_lock = threading.Lock()
        cal_stop = time.perf_counter() + 2.5
        cal_prompt = make_workload(3.0, seed=7).build_schedule(4.0)[0].prompt

        def cal_worker():
            while time.perf_counter() < cal_stop:
                t0 = time.perf_counter()
                status, _ = target({"question": cal_prompt}, {})
                if status == 200:
                    with cal_lock:
                        cal_lats.append(time.perf_counter() - t0)

        cal_threads = [threading.Thread(target=cal_worker, daemon=True)
                       for _ in range(2 * n_replicas)]
        for t in cal_threads:
            t.start()
        for t in cal_threads:
            t.join()
        if not cal_lats:
            raise RuntimeError("load-curve calibration produced no throughput")
        cal_lats.sort()
        capacity_rps = len(cal_lats) / 2.5
        slo_latency_s = max(
            4.0 * cal_lats[int(0.95 * (len(cal_lats) - 1))], 0.25
        )

        mem_points: list[dict] = []

        def make_run(rate: float) -> dict:
            # Overload windows must span several SLOs: a saturated fleet
            # serves ~capacity*slo good requests as a one-off transient
            # while its queues fill, and a short window would report that
            # transient as steady-state goodput (mis-placing the knee).
            dur = duration_s
            if rate > 2.0 * capacity_rps:
                dur = max(duration_s, 4.0 * slo_latency_s)
            _progress(f"load-curve: offered {rate:.1f} rps for {dur:.1f}s")
            gen = OpenLoopGenerator(target,
                                    make_workload(rate).build_schedule(dur),
                                    slo_latency_s=slo_latency_s,
                                    duration_s=dur)
            report = gen.run()
            # Snapshot the memory observatory at each point: the tightest
            # exhaustion forecast across the fleet and the cumulative peak
            # occupancy, in rate order (run_curve projects a fixed point
            # schema, so mem rides beside the curve, not inside it).
            cell: dict[str, Any] = {"requested_rps": rate,
                                    "min_forecast_s": None,
                                    "peak_resident_pages": None}
            for s in servers:
                eng = s.batcher
                if eng is None:
                    continue
                m = (eng.load_digest() or {}).get("mem")
                if isinstance(m, dict):
                    f = m.get("forecast_s")
                    if isinstance(f, (int, float)) and (
                            cell["min_forecast_s"] is None
                            or f < cell["min_forecast_s"]):
                        cell["min_forecast_s"] = f
                peak = (eng.mem.rollup() or {}).get("peak_resident_pages")
                if isinstance(peak, int):
                    cell["peak_resident_pages"] = (
                        (cell["peak_resident_pages"] or 0) + peak
                    )
            mem_points.append(cell)
            return report

        rates = [round(capacity_rps * f, 3) for f in point_factors]
        curve = run_curve(make_run, rates)
        knee_mem = next(
            (c for c, p in zip(mem_points, curve["points"])
             if p.get("offered_rps") == curve.get("knee_offered_rps")),
            None,
        )
        _progress(
            f"load-curve: knee {curve['knee_offered_rps']} rps offered -> "
            f"{curve['knee_goodput_rps']} rps goodput "
            f"(collapse: {curve['collapsed']})"
        )
        return {
            "metric": "load_curve_knee_rps",
            "value": curve["knee_offered_rps"],
            "unit": "req/s",
            "n_replicas": n_replicas,
            "duration_s": duration_s,
            "estimated_capacity_rps": round(capacity_rps, 3),
            "slo_latency_s": round(slo_latency_s, 6),
            "knee_goodput_rps": curve["knee_goodput_rps"],
            "collapsed": curve["collapsed"],
            "points": curve["points"],
            # The memory observatory beside the curve: per-point pool
            # snapshots (rate order matches points) and the forecast AT
            # the knee — how close to pool exhaustion the recommended
            # operating point runs (docs/OBSERVABILITY.md).
            "mem_points": mem_points,
            "mem_forecast_at_knee_s": (
                knee_mem.get("min_forecast_s") if knee_mem else None
            ),
            "mem_peak_resident_pages": max(
                (c["peak_resident_pages"] for c in mem_points
                 if c["peak_resident_pages"] is not None),
                default=None,
            ),
        }
    finally:
        if front is not None:
            front.shutdown()
        for srv in servers:
            srv.shutdown()
            if srv.batcher is not None:
                srv.batcher.close()


def disagg_benchmark(n_replicas: int = 3, duration_s: float = 4.0,
                     max_new: int = 8, prefill_threshold_chars: int = 250,
                     long_chars: int = 350, chat_chars: int = 60,
                     ) -> dict[str, Any]:
    """Prefill/decode disaggregation A/B: homogeneous vs tiered fleet on a
    mixed long-prefill/chatty open-loop workload (docs/FLEET.md "Tiered
    serving and KV streaming").

    Boots ``n_replicas`` in-process PAGED continuous replicas (tiny
    synthetic model — the routing/transfer layer is under test, not the
    kernels) and drives the same seeded two-tenant workload — a chatty
    interactive tenant plus a long-prompt bulk tenant — through two router
    arms: homogeneous least-outstanding, and tiered (long prefills to the
    prefill tier, KV streamed to the decode tier, shared prefix cache on).
    The headline is ``disagg_ttft_p99_ratio`` = homogeneous chat-tenant
    p99 / tiered chat-tenant p99 (> 1 means tiering protected the chatty
    tenant's TTFT from long-prefill stalls; the non-streaming front door's
    response latency IS its TTFT), alongside per-arm goodput and the KV
    wire bytes the tiered arm actually moved."""
    import threading

    from edgemesh.agents.orchestrator import Ensemble, build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
    from edgemesh.fleet import (
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )
    from edgemesh.loadgen import (
        LengthMix,
        OpenLoopGenerator,
        PoissonProcess,
        TenantSpec,
        Workload,
        http_target,
    )
    from edgemesh.obs import Registry
    from edgemesh.serve import serve_rest

    transport = HttpTransport()

    def _replica():
        agent = build_agent(AgentSpec(
            role="qa", model=ModelSpec(),
            sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                    repetition_penalty=1.0),
        ))
        return serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1",
                          port=0, block=False, continuous=True, batch=2,
                          kv_backend="paged", registry=Registry(),
                          trace_sample=0.0)

    _progress(f"disagg: building {n_replicas} in-process paged replicas")
    servers = [_replica() for _ in range(n_replicas)]
    fronts: list = []
    probers: list = []
    try:
        urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
        long_q = "why " * (long_chars // 4)
        chat_q = "chat warmup question?"
        for url in urls:
            # Warm BOTH prompt-shape compile buckets per replica, plus the
            # export gather (the tiered arm's first transfer must not pay
            # a compile mid-measurement).
            for q in (chat_q, long_q):
                status, _ = transport.post_json(
                    f"{url}/generate", {"question": q}, timeout_s=600.0)
                if status != 200:
                    raise RuntimeError(f"warmup on {url} answered {status}")
            status, _ = transport.post_json(
                f"{url}/kv/export", {"question": long_q}, timeout_s=600.0)
            if status != 200:
                raise RuntimeError(f"export warmup on {url} answered {status}")

        # Closed-loop capacity calibration on the chat shape (the tenant
        # whose TTFT the A/B judges) — same rationale as load_curve.
        cal_lats: list[float] = []
        cal_lock = threading.Lock()
        cal_stop = time.perf_counter() + 2.0

        def cal_worker(url):
            while time.perf_counter() < cal_stop:
                t0 = time.perf_counter()
                status, _ = transport.post_json(
                    f"{url}/generate", {"question": chat_q}, timeout_s=600.0)
                if status == 200:
                    with cal_lock:
                        cal_lats.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=cal_worker, args=(u,), daemon=True)
                   for u in urls for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not cal_lats:
            raise RuntimeError("disagg calibration produced no throughput")
        cal_lats.sort()
        capacity_rps = len(cal_lats) / 2.0
        slo_latency_s = max(
            4.0 * cal_lats[int(0.95 * (len(cal_lats) - 1))], 0.25
        )
        # Well below the closed-loop estimate: the A/B judges long-prefill
        # INTERFERENCE with chatty TTFT, and an over-the-knee overload
        # would swamp that signal with pure queueing collapse in both arms
        # (the in-process replicas share one GIL with the generator).
        chat_rate = max(0.5, 0.35 * capacity_rps)
        bulk_rate = max(0.25, 0.08 * capacity_rps)

        def make_workload(seed: int = 5) -> Workload:
            return Workload([
                TenantSpec(name="chat",
                           arrival=PoissonProcess(chat_rate, seed=11),
                           prompt_mix=LengthMix(median=chat_chars, sigma=0.0,
                                                lo=chat_chars, hi=chat_chars),
                           lane="interactive"),
                TenantSpec(name="bulk",
                           arrival=PoissonProcess(bulk_rate, seed=13),
                           prompt_mix=LengthMix(median=long_chars, sigma=0.0,
                                                lo=long_chars, hi=long_chars),
                           lane="batch"),
            ], seed=seed)

        def run_arm(tiered: bool):
            obs = Registry()
            registry = ReplicaRegistry(
                (f"replica-{i}", u) for i, u in enumerate(urls)
            )
            router = FleetRouter(
                registry, balancer="least_outstanding", transport=transport,
                obs_registry=obs, attempt_timeout_s=300.0,
                default_deadline_s=600.0, max_attempts=2, tiered=tiered,
                prefill_threshold_chars=prefill_threshold_chars,
            )
            prober = HealthProber(registry, transport=transport,
                                  interval_s=0.5, obs_registry=obs,
                                  on_digest=router.note_digest).start()
            probers.append(prober)
            front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
            fronts.append(front)
            target = http_target(
                f"http://127.0.0.1:{front.server_address[1]}/generate",
                timeout_s=600.0,
            )
            if tiered:
                # Prime the transfer path through THIS router (export →
                # import compile + the tier split) outside the window.
                target({"question": long_q}, {})
            arm = "tiered" if tiered else "homogeneous"
            _progress(f"disagg: {arm} arm at chat {chat_rate:.1f} + bulk "
                      f"{bulk_rate:.1f} rps for {duration_s:.1f}s")
            gen = OpenLoopGenerator(
                target, make_workload().build_schedule(duration_s),
                slo_latency_s=slo_latency_s, duration_s=duration_s,
            )
            report = gen.run()
            # Tear the arm down before the next one measures: a leftover
            # prober polling every replica (and an extra bound frontend)
            # would be asymmetric background load on the later arm. The
            # outer finally re-stops idempotently.
            prober.stop()
            front.shutdown()
            return report, obs, router

        homog, _, _ = run_arm(tiered=False)
        tiered_rep, tiered_obs, tiered_router = run_arm(tiered=True)

        def chat_p99(report):
            cell = (report.get("tenants") or {}).get("chat") or {}
            return cell.get("latency_s_p99")

        h_p99, t_p99 = chat_p99(homog), chat_p99(tiered_rep)
        ratio = (
            round(h_p99 / t_p99, 4)
            if h_p99 is not None and t_p99 not in (None, 0) else None
        )
        fleet = tiered_obs.summary(prefix="edgemesh_fleet_")
        kv_bytes = int(sum(
            v for k, v in fleet.items()
            if k.startswith("edgemesh_fleet_kv_transfer_bytes_total")
            and not isinstance(v, dict)
        ))
        tiered_outcomes = {
            k.split('outcome="')[1].rstrip('"}'): int(v)
            for k, v in fleet.items()
            if k.startswith("edgemesh_fleet_tiered_total")
            and not isinstance(v, dict)
        }
        _progress(f"disagg: chat p99 {h_p99} -> {t_p99} "
                  f"(ratio {ratio}); kv bytes {kv_bytes}")
        return {
            "metric": "disagg_ttft_p99_ratio",
            "value": ratio,
            "unit": "x",
            "n_replicas": n_replicas,
            "duration_s": duration_s,
            "slo_latency_s": round(slo_latency_s, 6),
            "estimated_capacity_rps": round(capacity_rps, 3),
            "prefill_threshold_chars": prefill_threshold_chars,
            "homogeneous_chat_p99_s": h_p99,
            "tiered_chat_p99_s": t_p99,
            "homogeneous_goodput_ratio": homog.get("goodput_ratio"),
            "tiered_goodput_ratio": tiered_rep.get("goodput_ratio"),
            "homogeneous_tenants": homog.get("tenants"),
            "tiered_tenants": tiered_rep.get("tenants"),
            "kv_transfer_bytes": kv_bytes,
            "tiered_outcomes": tiered_outcomes,
            "tiers": tiered_router.status()["tiers"],
            # Per-replica pool-ledger rollups across BOTH arms (the
            # replicas persist between them): peak occupancy, per-tenant
            # split, and leak/conservation counters for the paged pools
            # the KV transfers spliced into (obs/memory.py).
            "mem": {
                f"replica-{i}": (s.batcher.mem.rollup() or None)
                for i, s in enumerate(servers)
                if s.batcher is not None
            } or None,
        }
    finally:
        for prober in probers:
            prober.stop()
        for front in fronts:
            front.shutdown()
        for srv in servers:
            srv.shutdown()
            if srv.batcher is not None:
                srv.batcher.close()


_COLD_START_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64}
    sampling: {max_new_tokens: 4, do_sample: false, repetition_penalty: 1.0}
"""


def cold_start_benchmark(boot_timeout_s: float = 600.0) -> dict[str, Any]:
    """Cold-start-to-first-token, cache-cold vs cache-warm — the number the
    autoscaler's warm-start story is judged by (docs/PERFORMANCE.md
    "Cold-start targets"; docs/FLEET.md "Autoscaling with warm starts").

    Spawns the same `edgemesh serve --continuous` subprocess twice against
    ONE persistent compilation cache directory (--compile-cache-dir): the
    first spawn populates it (the cache-cold arm), the second compiles
    from disk hits (the warm arm). Each arm's wall is spawn → first 200
    from /generate — the full client-visible cold start, process boot and
    model build included. The headline is the warm arm;
    ``cold_start_warm_over_cold`` < 1 is the cache paying."""
    import shutil
    import socket
    import subprocess
    import sys
    import tempfile
    from pathlib import Path

    from edgemesh.fleet.transport import HttpTransport, TransportError

    transport = HttpTransport()
    work = Path(tempfile.mkdtemp(prefix="edgemesh-coldstart-"))
    cache_dir = work / "compile-cache"
    cache_dir.mkdir()
    cfg = work / "replica.yaml"
    cfg.write_text(_COLD_START_YAML)

    def one_spawn(label: str) -> float:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        _progress(f"cold-start: spawning {label} replica on port {port}")
        t0 = time.monotonic()
        proc = subprocess.Popen(
            [sys.executable, "-m", "edgemesh.cli", "serve",
             "--config", str(cfg), "--port", str(port),
             "--continuous", "--batch", "2",
             "--compile-cache-dir", str(cache_dir)],
            env=os.environ.copy(),
        )
        try:
            deadline = time.monotonic() + boot_timeout_s
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"{label} replica exited rc={proc.returncode}")
                try:
                    status, _ = transport.post_json(
                        f"http://127.0.0.1:{port}/generate",
                        {"question": "cold start probe?"}, timeout_s=60.0)
                except TransportError:
                    time.sleep(0.2)
                    continue
                if status == 200:
                    return time.monotonic() - t0
                time.sleep(0.2)
            raise RuntimeError(f"{label} replica never answered")
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    try:
        cold_s = one_spawn("cache-cold")
        cache_entries = sum(1 for p in cache_dir.iterdir()
                            if p.name.endswith("-cache"))
        warm_s = one_spawn("cache-warm")
        ratio = round(warm_s / cold_s, 4) if cold_s else None
        _progress(f"cold-start: cold {cold_s:.1f}s -> warm {warm_s:.1f}s "
                  f"(ratio {ratio}, {cache_entries} cache entries)")
        return {
            "metric": "cold_start_first_token_s",
            "value": round(warm_s, 3),
            "unit": "s",
            "cold_start_cold_s": round(cold_s, 3),
            "cold_start_warm_s": round(warm_s, 3),
            "cold_start_warm_over_cold": ratio,
            "cold_start_cache_entries": cache_entries,
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def autoscale_benchmark(duration_s: float = 6.0, max_new: int = 8,
                        ) -> dict[str, Any]:
    """The closed control loop under rising load: one in-process replica
    behind the real frontend with ``--admission auto`` semantics and the
    autoscaler attached; an open-loop generator offers ~3x the measured
    single-replica capacity, and the stage reports how fast the scaler
    turned observed overload into a second serving replica
    (``autoscale_time_to_scale_s`` — with warm starts this is the number
    that makes scale-up useful at all), plus the tuner's final state."""
    import threading

    from edgemesh.agents.orchestrator import Ensemble, build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
    from edgemesh.fleet import (
        AutoScaler,
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )
    from edgemesh.loadgen import (
        LengthMix,
        OpenLoopGenerator,
        PoissonProcess,
        TenantSpec,
        Workload,
        http_target,
    )
    from edgemesh.obs import Registry
    from edgemesh.serve import serve_rest

    transport = HttpTransport()
    servers: list = []
    lock = threading.Lock()

    def _replica():
        agent = build_agent(AgentSpec(
            role="qa", model=ModelSpec(),
            sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                    repetition_penalty=1.0),
        ))
        srv = serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1",
                         port=0, block=False, continuous=True, batch=2,
                         registry=Registry(), trace_sample=0.0)
        with lock:
            servers.append(srv)
        return srv

    class InProcessLauncher:
        """The autoscaler's spawn seam over in-process replicas — the
        control law is under test, not process boot."""

        def __init__(self, registry):
            self.registry = registry
            self._n = 0
            self._pending = 0

        def pending(self) -> int:
            with lock:
                return self._pending

        def spawn(self) -> str:
            with lock:
                self._n += 1
                self._pending += 1
                rid = f"scale-{self._n}"

            def boot():
                try:
                    srv = _replica()
                    url = f"http://127.0.0.1:{srv.server_address[1]}"
                    transport.post_json(f"{url}/generate",
                                        {"question": "warmup?"},
                                        timeout_s=600.0)
                    self.registry.register(rid, url)
                finally:
                    with lock:
                        self._pending -= 1

            threading.Thread(target=boot, daemon=True).start()
            return rid

        def stop(self, rid: str) -> None:
            pass  # in-process replicas share teardown below

    _progress("autoscale: booting the seed replica")
    seed = _replica()
    front = prober = scaler = None
    try:
        url0 = f"http://127.0.0.1:{seed.server_address[1]}"
        status, _ = transport.post_json(f"{url0}/generate",
                                        {"question": "warmup?"},
                                        timeout_s=600.0)
        if status != 200:
            raise RuntimeError(f"warmup answered {status}")
        obs = Registry()
        registry = ReplicaRegistry([("replica-0", url0)])
        router = FleetRouter(registry, balancer="least_outstanding",
                             transport=transport, obs_registry=obs,
                             attempt_timeout_s=300.0,
                             default_deadline_s=600.0, max_attempts=1,
                             admission_auto=True, admission_floor=2,
                             admission_ceiling=64)
        launcher = InProcessLauncher(registry)
        scaler = AutoScaler(registry, launcher, router=router,
                            min_replicas=1, max_replicas=2,
                            up_after=2, cooldown_s=2.0, interval_s=0.5,
                            # The stage measures time-to-scale-UP; the
                            # post-window lull must not reap the spawn.
                            down_after=10**6,
                            obs_registry=obs)
        router.autoscaler = scaler
        prober = HealthProber(registry, transport=transport,
                              interval_s=0.5,
                              on_incident=router.observe_incident,
                              on_digest=router.note_digest).start()
        scaler.start()
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
        target = http_target(
            f"http://127.0.0.1:{front.server_address[1]}/generate",
            timeout_s=600.0)

        # Calibrate single-replica capacity closed-loop, then offer 3x it.
        t_cal = time.perf_counter() + 2.0
        served = 0
        while time.perf_counter() < t_cal:
            s, _ = target({"question": "calibration question?"}, {})
            served += 1 if s == 200 else 0
        capacity_rps = max(0.5, served / 2.0)
        rate = 3.0 * capacity_rps
        _progress(f"autoscale: offering {rate:.1f} rps "
                  f"(~3x capacity {capacity_rps:.1f})")
        wl = Workload([TenantSpec(
            name="load", arrival=PoissonProcess(rate, seed=11),
            prompt_mix=LengthMix(median=60, sigma=0.0, lo=60, hi=60),
        )], seed=5)
        # Watch for the second replica CONCURRENTLY with the load window:
        # the spawn usually lands mid-run, and stamping it only after
        # gen.run() returned would floor the headline at duration_s no
        # matter how fast the scaler actually was.
        scale_seen = threading.Event()
        scaled_box: list[float] = []
        t_start = time.monotonic()

        def watch():
            while not scale_seen.is_set():
                if len(registry.available()) >= 2:
                    scaled_box.append(time.monotonic() - t_start)
                    scale_seen.set()
                    return
                time.sleep(0.1)

        watcher = threading.Thread(target=watch, daemon=True)
        watcher.start()
        gen = OpenLoopGenerator(target, wl.build_schedule(duration_s),
                                slo_latency_s=600.0, duration_s=duration_s)
        report = gen.run()
        # The spawn may land after the window closes; give it a beat.
        scale_seen.wait(timeout=30.0)
        scale_seen.set()  # stop the watcher either way
        watcher.join(timeout=5.0)
        scaled_at = scaled_box[0] if scaled_box else None
        events = scaler.status()["recent_events"]
        tuner = router.tuner.status()
        _progress(f"autoscale: scaled={'yes' if scaled_at else 'NO'} "
                  f"at {scaled_at}s; tuner limit {tuner['limit']}")
        return {
            "metric": "autoscale_time_to_scale_s",
            "value": None if scaled_at is None else round(scaled_at, 3),
            "unit": "s",
            "autoscale_scaled": scaled_at is not None,
            "autoscale_replicas": len(registry.available()),
            "autoscale_events": events,
            "autoscale_offered_rps": round(rate, 3),
            "autoscale_capacity_rps": round(capacity_rps, 3),
            "autoscale_goodput_ratio": report.get("goodput_ratio"),
            "tuner_limit": tuner["limit"],
            "tuner_knee": tuner["knee"],
            "tuner_windows": tuner["windows"],
        }
    finally:
        if prober is not None:
            prober.stop()
        if scaler is not None:
            scaler.stop()
        if front is not None:
            front.shutdown()
        for srv in servers:
            srv.shutdown()
            if srv.batcher is not None:
                srv.batcher.close()


def ensemble_overlap_benchmark(n_agents: int = 2, questions: int = 3) -> dict[str, Any]:
    """Concurrent-vs-serial wall time for ensemble QA agents on disjoint
    submeshes — the measured version of the claim that edgemesh fixes the
    reference's sequential agent calls (combiner_fp.py:436-439).

    Reports ``concurrent_over_serial`` (< 1.0 = real overlap) and the raw
    per-agent work intervals. On a 1-core host (this CI) compute physically
    serializes, so the honest signal there is interval overlap, not
    speedup; on a multi-chip slice each agent owns its own devices and the
    ratio drops toward 1/n."""
    from edgemesh.agents.orchestrator import Agent, Ensemble, build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
    from edgemesh.parallel.mesh import submeshes

    try:
        meshes = submeshes(n_agents)
    except ValueError:
        meshes = [None] * n_agents  # fewer devices than agents: share
    spec = AgentSpec(
        role="qa",
        model=ModelSpec(),  # synthetic tiny model
        sampling=SamplingParams(max_new_tokens=16, do_sample=False, repetition_penalty=1.0),
    )
    agents = [build_agent(spec, mesh=m) for m in meshes[:n_agents]]
    ensemble = Ensemble(qa_agents=agents)
    q = "Where is the Eiffel Tower located?"

    # Warmup compiles per agent.
    for a in agents:
        a.answer(q)

    serial = 0.0
    for _ in range(questions):
        t0 = time.perf_counter()
        for a in agents:
            a.answer(q)
        serial += time.perf_counter() - t0

    concurrent = 0.0
    overlapped = 0
    for _ in range(questions):
        t0 = time.perf_counter()
        out = ensemble.answer(q)
        concurrent += time.perf_counter() - t0
        d = out["drafts"]
        starts = [x["t_start"] for x in d]
        ends = [x["t_end"] for x in d]
        if max(starts) < min(ends):  # all intervals share a common instant
            overlapped += 1

    return {
        "n_agents": n_agents,
        "serial_s": round(serial, 4),
        "concurrent_s": round(concurrent, 4),
        "concurrent_over_serial": round(concurrent / serial, 3) if serial else 1.0,
        "intervals_overlapped": overlapped,
        "questions": questions,
    }


def fleet_ensemble_benchmark(
    n_requests: int = 12, max_new: int = 8, eval_limit: int = 8
) -> dict[str, Any]:
    """Ensemble-over-the-fleet vs single-model serving on the same tiny
    in-process replicas: 2 QA pools (qa-a/qa-b) + a refiner pool behind one
    ``FleetRouter``, ``POST /ensemble`` against ``POST /generate`` through
    the same frontend. The headline is ``ensemble_latency_p99_ratio``
    (ensemble p99 / single p99 — the latency price of fan-out + refine);
    the per-outcome degradation counts and the eval-scored quality delta
    ride beside it. Random synthetic weights ⇒ the quality delta is a
    machinery check (both arms score near-noise), not a model claim —
    trained checkpoints give the real tradeoff; the schema is what this
    stage pins. Questions (and rouge references) come from the eval
    dataset when the CSV is present; otherwise one synthetic question and
    null quality keys — the latency ratio never depends on the dataset."""
    from edgemesh.agents.orchestrator import Ensemble, build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
    from edgemesh.fleet import FleetRouter, HttpTransport, ReplicaRegistry, serve_fleet
    from edgemesh.obs import Registry
    from edgemesh.serve import serve_rest

    import numpy as np

    sampling = SamplingParams(max_new_tokens=max_new, do_sample=False,
                              repetition_penalty=1.0)

    def replica(template: str = ""):
        agent = build_agent(AgentSpec(role="qa", model=ModelSpec(),
                                      sampling=sampling,
                                      prompt_template=template))
        return serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1",
                          port=0, block=False)

    # The refiner pool serves the passthrough template: the coordinator
    # composes the full refiner prompt fleet-side (agents/prompts.py) and
    # the replica must not wrap it again.
    servers = [
        ("qa-a-0", replica(), {"pool": "qa-a", "role": "qa"}),
        ("qa-b-0", replica(), {"pool": "qa-b", "role": "qa"}),
        ("refiner-0", replica("{question}"),
         {"pool": "refiner", "role": "refiner"}),
    ]
    obs = Registry()
    registry = ReplicaRegistry()
    for rid, srv, model in servers:
        registry.register(rid, f"http://127.0.0.1:{srv.server_address[1]}",
                          model=model)
    router = FleetRouter(registry, balancer="least_outstanding",
                         obs_registry=obs, trace_sample=0.0)
    front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
    transport = HttpTransport()
    base = f"http://127.0.0.1:{front.server_address[1]}"

    try:
        from edgemesh.eval.data import load_qa, resolve_dataset_path

        samples = load_qa(resolve_dataset_path(), limit=eval_limit)
    except (FileNotFoundError, ValueError):
        samples = []
    qa_pairs = (
        [(s.question, s.answer) for s in samples]
        if samples else [("Where is the Eiffel Tower located?", None)]
    )

    def drive(path: str, label: str) -> tuple[list[float], list[tuple]]:
        _progress(f"fleet-ensemble: warmup via {label}")
        status, _ = transport.post_json(
            base + path, {"question": qa_pairs[0][0]}, timeout_s=600.0)
        if status != 200:
            raise RuntimeError(f"{label} warmup answered {status}")
        lats, scored = [], []
        for i in range(n_requests):
            q, ref = qa_pairs[i % len(qa_pairs)]
            t0 = time.perf_counter()
            status, body = transport.post_json(
                base + path, {"question": q}, timeout_s=600.0)
            if status != 200:
                raise RuntimeError(f"{label} request answered {status}")
            lats.append(time.perf_counter() - t0)
            if ref is not None:
                scored.append((body.get("answer") or "", ref))
        return lats, scored

    def quality(scored: list[tuple]) -> float | None:
        if not scored:
            return None
        from edgemesh.eval.harness import score_sample

        rows = [score_sample(pred, ref, metrics=["avg_rouge"])
                for pred, ref in scored]
        return round(sum(r["avg_rouge"] for r in rows) / len(rows), 4)

    try:
        # The single arm routes pool-less through the same frontend, so
        # both arms pay the identical router hop and the ratio isolates
        # the fan-out + refine work.
        single_lats, single_scored = drive("/generate", "single")
        ens_lats, ens_scored = drive("/ensemble", "ensemble")

        def pct(xs, q):
            return round(float(np.percentile(xs, q)), 6)

        stats = router.ensemble.stats()
        ens_q, single_q = quality(ens_scored), quality(single_scored)
        ratio = (round(pct(ens_lats, 99) / pct(single_lats, 99), 3)
                 if pct(single_lats, 99) else None)
        _progress(
            f"fleet-ensemble: p99 {pct(ens_lats, 99) * 1e3:.1f}ms ensemble "
            f"vs {pct(single_lats, 99) * 1e3:.1f}ms single (ratio {ratio}), "
            f"outcomes {stats['outcomes']}"
        )
        return {
            "metric": "ensemble_latency_p99_ratio",
            "value": ratio,
            "unit": "ratio",
            "n_requests": n_requests,
            "ensemble_p50_s": pct(ens_lats, 50),
            "ensemble_p99_s": pct(ens_lats, 99),
            "single_p50_s": pct(single_lats, 50),
            "single_p99_s": pct(single_lats, 99),
            "outcomes": stats["outcomes"],
            "qa_pools": stats["qa_pools"],
            "refiner_pool": stats["refiner_pool"],
            "ensemble_quality": ens_q,
            "single_quality": single_q,
            "quality_delta": (
                round(ens_q - single_q, 4)
                if ens_q is not None and single_q is not None else None
            ),
            "eval_samples": len(samples),
            # The coordinator's cross-branch agreement EWMA (obs/quality.py
            # pairwise token-F1): the replicas here are non-continuous (no
            # engine tracker), so the block carries the ensemble signal
            # only. None with EDGEMESH_BENCH_QUALITY=0.
            "quality": bench_quality_block(
                None, agreement=stats.get("agreement_ewma")),
            "obs": obs.summary(prefix="edgemesh_ensemble_"),
        }
    finally:
        front.shutdown()
        for _, srv, _ in servers:
            srv.shutdown()
            if srv.batcher is not None:
                srv.batcher.close()


def speculative_benchmark(
    preset: str | None = None,
    batch: int = 1,
    decode_steps: int = 128,
    gamma: int = 4,
    draft_layers_frac: float = 0.25,
    kv_backend: str = "dense",
    built: tuple | None = None,
) -> dict[str, Any]:
    """Speculative vs plain decode at batch 1 (the latency regime speculative
    decoding targets). On by default in the headline since round 4
    (EDGEMESH_BENCH_SPEC=0 skips).

    Draft construction (the BENCH_r05 ``spec_accept_rate: 0.0`` fix): the
    draft is the TARGET truncated to its first ``d_layers`` layers —
    embeddings, norms, and LM head SHARED. The r05 arm built the draft as
    an UNRELATED random init; at a 128k vocab two independent random
    models' top-k candidate sets are essentially disjoint, so the Leviathan
    accept test (target prob of the draft token on the target's candidate
    support) was 0 for every proposal and the arm measured pure
    draft-overhead — the accept-path wiring itself was never wrong
    (draft==target accepts 100%, pinned in tests/test_spec_accept.py).
    Truncation keeps draft and target in one representation space (the
    early-exit-draft construction trained pairs approximate), so the
    measured speedup is a meaningful lower bound; a ``selfcheck`` arm runs
    draft==target for a few steps and reports its acceptance so the
    artifact itself distinguishes "machinery broken" (selfcheck < 1) from
    "draft weak" (accept low, selfcheck 1.0).

    ``kv_backend="paged_int8"`` runs BOTH arms over int8 page pools (plain =
    generate_paged kv_quant; spec = int8 target+draft pools) — the memory
    backend composed with the marquee latency feature (SERVING.md matrix)."""
    from edgemesh.runtime.paged_generate import generate_paged
    from edgemesh.runtime.speculative import generate_speculative

    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    cfg, params = built if built is not None else _build(preset, "bf16", "w8a16")
    d_layers = max(1, int(cfg.num_layers * draft_layers_frac))
    d_cfg = cfg.replace(num_layers=d_layers)
    d_params = {
        **params,
        "layers": jax.tree.map(lambda x: x[:d_layers], params["layers"]),
    }
    sampling = SamplingParams(
        max_new_tokens=decode_steps, temperature=0.7, top_k=50, top_p=0.9,
        repetition_penalty=1.2, do_sample=True,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, 32), 0, cfg.vocab_size, jnp.int32
    )
    lengths = jnp.full((batch,), 32, jnp.int32)

    def spec_once():
        return generate_speculative(
            cfg, params, d_cfg, d_params, tokens, lengths, sampling, gamma,
            kv_backend=kv_backend,
        )

    def plain_once():
        if kv_backend == "dense":
            return generate(cfg, params, tokens, lengths, sampling)
        return generate_paged(cfg, params, tokens, lengths, sampling,
                              kv_quant=kv_backend == "paged_int8")

    _progress(f"spec b{batch} gamma={gamma} kv={kv_backend}: warmup")
    spec_once()
    plain = plain_once()
    # Ambient compute ledger over the timed spec arms: the runtime spec
    # path launches its fused round loop as the "spec_rounds" boundary, so
    # the artifact carries a measured round time — the spec round ledger
    # below decomposes it into draft/verify by the analytic flops split
    # (obs/compute.py SpecRoundLedger; the instrument for the BENCH_r05
    # 2.8x spec loss).
    from edgemesh.obs import (
        ComputeLedger, Registry, SpecRoundLedger, ledger_scope,
        spec_draft_frac,
    )

    ledger = ComputeLedger(registry=Registry(), engine="bench-spec",
                           sample=1)
    rounds_ledger = SpecRoundLedger(
        ledger=ledger, engine="bench-spec",
        draft_frac=spec_draft_frac(params, d_params, gamma))
    best_spec, stats = 0.0, None
    with ledger_scope(ledger):
        for _ in range(2):
            r, s = spec_once()
            rounds_ledger.on_segment(
                s.rounds, s.accepted, s.proposed,
                measured_s=ledger.consume_measured("spec_rounds"))
            if r.decode_tok_s > best_spec:
                best_spec, stats = r.decode_tok_s, s
    plain_best = plain.decode_tok_s
    for _ in range(2):
        plain_best = max(plain_best, plain_once().decode_tok_s)
    # Selfcheck arm: draft==target for a few rounds. Acceptance here is the
    # accept-path's own health (must be ~1.0); the throughput is discarded.
    _, self_stats = generate_speculative(
        cfg, params, cfg, params, tokens, lengths,
        SamplingParams(
            max_new_tokens=min(16, decode_steps), temperature=0.7, top_k=50,
            top_p=0.9, repetition_penalty=1.2, do_sample=True,
        ),
        gamma, kv_backend=kv_backend,
    )
    _progress(f"spec/{kv_backend} {best_spec:.1f} vs plain {plain_best:.1f} "
              f"tok/s, accept {stats.accept_rate:.2f} "
              f"(selfcheck {self_stats.accept_rate:.2f})")
    return {
        "spec_tok_s": round(best_spec, 2),
        "plain_tok_s": round(plain_best, 2),
        "spec_speedup": round(best_spec / plain_best, 3) if plain_best else 0.0,
        "accept_rate": round(stats.accept_rate, 3),
        "selfcheck_accept_rate": round(self_stats.accept_rate, 3),
        "gamma": gamma,
        "draft_layers": d_layers,
        "draft_mode": "truncate",
        "kv_backend": kv_backend,
        # Round-structure attribution over the timed arms: measured round
        # time split draft-vs-verify by the analytic flops ratio (labeled
        # in the block itself), plus accept/reject accounting — the
        # decomposition of WHERE a spec slowdown goes (draft overhead vs
        # verify vs rejected work).
        "spec_round_ledger": rounds_ledger.summary(),
        "compute": ledger.rollup() or None,
    }


def headline_benchmark(
    preset: str | None = None,
    batch: int = 8,
    decode_steps: int = 128,
    sweep_batches: tuple[int, ...] = (1, 32),
) -> dict[str, Any]:
    """The driver's bench: bf16 vs every int8 path at the same preset/batch,
    primary metric = fastest int8 path, plus a batch sweep on that path.

    Proves (or disproves) the int8 >= bf16 claim by measurement — the
    reference's Table 3 shows the opposite on A100 (67.2 -> 26.39 tok/s).

    Stall-ordered: the headline int8 stage runs FIRST and every completed
    stage re-emits the refreshed result line (``emit_partial``), so a tunnel
    wedge N stages in costs stages N+1.. only — round 2 lost a full bench to
    the opposite ordering. Non-headline stages are individually fenced: a
    failure records ``<stage>_error`` instead of discarding finished work."""
    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")

    # ---- Stage 1 (headline): int8 w8a16 decode — the number the driver
    # records against the reference's 25.83 tok/s. Nothing runs before it.
    int8_built = _build(preset, "int8", "w8a16")
    int8_runs = {
        "w8a16": decode_benchmark(preset, "int8", quant_mode="w8a16", batch=batch,
                                  decode_steps=decode_steps, built=int8_built)
    }
    out = dict(int8_runs["w8a16"])
    out["metric"] = f"decode_tok_s_llama3.2-1b_int8_b{batch}"
    out["int8_mode"] = "w8a16"
    out["int8_w8a16_tok_s"] = int8_runs["w8a16"]["value"]
    emit_partial(out)

    def _rebest() -> None:
        """Re-point the top-level metric at the fastest int8 path measured
        so far, keeping per-path keys intact."""
        best_mode = max(int8_runs, key=lambda m: int8_runs[m]["value"])
        best = int8_runs[best_mode]
        for k in ("value", "vs_baseline", "ttft_s", "hbm_eff_gbs", "hbm_util",
                  "weight_gb", "batch", "decode_steps"):
            out[k] = best[k]
        out["int8_mode"] = best_mode
        if out.get("bf16_tok_s"):
            out["int8_vs_bf16"] = round(best["value"] / out["bf16_tok_s"], 3)

    def _stage(name: str, fn) -> None:
        """Run one non-headline stage; a failure becomes ``<name>_error``
        rather than the loss of everything already measured."""
        try:
            fn()
        except Exception as e:  # pragma: no cover - device-capacity dependent
            _progress(f"{name} stage failed: {e}")
            out[f"{name}_error"] = str(e)[:200]
        emit_partial(out)

    # ---- Stage 2: bf16 comparison (the int8>=bf16 claim). The int8 tree
    # stays resident (~1.3 GB at 1B) — rebuilt quantization would cost more
    # than the HBM it saves.
    def _bf16():
        bf16_built = _build(preset, "bf16", "w8a16")
        r = decode_benchmark(preset, "bf16", batch=batch, decode_steps=decode_steps,
                             built=bf16_built)
        out["bf16_tok_s"] = r["value"]
        out["bf16_ttft_s"] = r["ttft_s"]
        out["int8_vs_bf16"] = round(out["value"] / r["value"], 3) if r["value"] else 0.0

    _stage("bf16", _bf16)

    # ---- Stage 3: remaining int8 activation paths (XLA w8a8, fused Pallas
    # w8a8, pre-quantized Pallas); the headline re-points itself if one
    # beats w8a16.
    for mode in ("w8a8", "w8a8_pallas", "w8a8_pallas_pre"):
        def _mode(mode=mode):
            int8_runs[mode] = decode_benchmark(
                preset, "int8", quant_mode=mode, batch=batch,
                decode_steps=decode_steps, built=int8_built)
            out[f"int8_{mode}_tok_s"] = int8_runs[mode]["value"]
            # Per-mode TTFT: the per-PHASE selection evidence (prefill can
            # run a different path than decode — prefill_quant_mode).
            out[f"int8_{mode}_ttft_s"] = int8_runs[mode]["ttft_s"]
            _rebest()

        _stage(f"int8_{mode}", _mode)

    # ---- Stage 4: paged KV backend on the fastest dense mode (the
    # HeadInfer-analog serving path; page-table-walking Pallas kernel).
    def _paged():
        dense_best = max(int8_runs, key=lambda m: int8_runs[m]["value"])
        r = decode_benchmark(preset, "int8", quant_mode=dense_best, batch=batch,
                             decode_steps=decode_steps, built=int8_built,
                             kv_backend="paged")
        int8_runs[dense_best + "+paged"] = r
        out[f"int8_{dense_best}+paged_tok_s"] = r["value"]
        _rebest()

    _stage("paged", _paged)

    # ---- Stage 4b: sampler A/B — exact lax.top_k vs approx_max_k on the
    # headline config. Tests the 49%-HBM-util hypothesis directly: if the
    # per-step gap is the vocab-wide sort, this key jumps while everything
    # else is held fixed (profile_1b_decode.py probe C isolates the same
    # cost outside the loop).
    def _sampler():
        # Same repeats as the stage-1 exact arm: best-of-N is monotone in
        # N, so unequal repeats would bias the A/B.
        r = decode_benchmark(preset, "int8", quant_mode="w8a16", batch=batch,
                             decode_steps=decode_steps,
                             built=int8_built, approx_top_k=True)
        out["int8_w8a16_approx_topk_tok_s"] = r["value"]

    _stage("sampler", _sampler)

    # ---- Stage 5: batch sweep on the best path.
    def _sweep():
        best_mode = out["int8_mode"]
        for b in sweep_batches:
            if b == batch:
                continue
            r = decode_benchmark(
                preset, "int8", quant_mode=best_mode.removesuffix("+paged"), batch=b,
                decode_steps=decode_steps, repeats=2, built=int8_built,
                kv_backend="paged" if best_mode.endswith("+paged") else "dense",
            )
            out[f"int8_b{b}_tok_s"] = r["value"]
            emit_partial(out)

    _stage("sweep", _sweep)

    # ---- Stage 6: long-context decode (prompt ~1.8k of the 2k window): the
    # KV stream now rivals the weight set, which is where the int8 KV cache
    # (runtime/quant_kv.py) earns its bytes — both caches on the same model.
    def _longctx():
        lc_prompt = min(1792, int8_built[0].max_seq_len - decode_steps)
        lc_kw = dict(prompt_len=lc_prompt, decode_steps=decode_steps, batch=batch,
                     repeats=2, built=int8_built)
        lc_dense = decode_benchmark(preset, "int8", quant_mode="w8a16",
                                    kv_backend="dense", **lc_kw)
        out[f"longctx{lc_prompt}_tok_s"] = lc_dense["value"]
        out[f"longctx{lc_prompt}_ttft_s"] = lc_dense["ttft_s"]
        emit_partial(out)
        lc_quant = decode_benchmark(preset, "int8", quant_mode="w8a16",
                                    kv_backend="quant", **lc_kw)
        out[f"longctx{lc_prompt}_int8kv_tok_s"] = lc_quant["value"]
        emit_partial(out)
        # Windowed paged decode: the page-table kernel's grid only visits
        # pages intersecting the window, so long-context decode stops paying
        # for the whole table (sliding-window serving à la Mistral/Gemma-2).
        win_cfg = int8_built[0].replace(sliding_window=1024)
        lc_win = decode_benchmark(preset, "int8", quant_mode="w8a16",
                                  kv_backend="paged",
                                  **{**lc_kw, "built": (win_cfg, int8_built[1])})
        out[f"longctx{lc_prompt}_paged_win1024_tok_s"] = lc_win["value"]
        emit_partial(out)
        # Int8 page pool: the two long-context levers composed — paged table
        # walk AND half the KV bytes (runtime/paged_kv.QuantPagedKVCache).
        lc_pq = decode_benchmark(preset, "int8", quant_mode="w8a16",
                                 kv_backend="paged_int8", **lc_kw)
        out[f"longctx{lc_prompt}_paged_int8_tok_s"] = lc_pq["value"]

    _stage("longctx", _longctx)

    # ---- Stage 7: continuous-batching serving throughput over the paged
    # pool — the serving-path headline (requests stream through the resident
    # decode loop; zero-copy paged admission). Skippable via
    # EDGEMESH_BENCH_SERVE=0.
    def _serving():
        r = serving_benchmark(preset, built=int8_built, kv_backend="paged")
        out["serving_paged_tok_s"] = r["value"]
        # The engine default is ragged boundary launches now, so the paged
        # headline IS the ragged number; the explicit key is what
        # PERFORMANCE.md and the ablation stage reference.
        out["serving_ragged_tok_s"] = r["value"]
        out["serving_ragged_boundaries"] = r["stats"].get("ragged_boundaries", 0)
        out["serving_ragged_prefill_tokens"] = r["stats"].get("ragged_prefill_tokens", 0)
        out["serving_ragged_decode_tokens"] = r["stats"].get("ragged_decode_tokens", 0)
        out["serving_wave_tok_s"] = r["wave_tok_s"]
        out["serving_spread_pct"] = r["spread_pct"]
        out["serving_paged_req_s"] = r["req_s"]
        out["serving_latency_s_p50"] = r["latency_s_p50"]
        out["serving_latency_s_p95"] = r["latency_s_p95"]
        # The compute observatory's view of the headline serving run:
        # per-boundary device time + roofline (docs/OBSERVABILITY.md).
        out["serving_compute"] = r.get("compute")
        # The memory observatory's view of the same run: peak pool
        # occupancy, per-tenant split, leak/conservation counters.
        out["serving_mem"] = r.get("mem")
        # The quality observatory's view: confidence/entropy EWMAs +
        # low-confidence counts (None when EDGEMESH_BENCH_QUALITY=0).
        out["serving_quality"] = r.get("quality")
        emit_partial(out)
        # Segmented baseline at the same shape: the headline's own
        # ragged-vs-segmented pin (the full shape sweep is stage 7c).
        r_seg = serving_benchmark(preset, built=int8_built, kv_backend="paged",
                                  ragged=False)
        out["serving_segmented_tok_s"] = r_seg["value"]
        # Diagnosis keys: segments/concurrency separate engine anomalies
        # from device slowness without rerunning (r3's first measurement
        # was 15x slow from per-token host readbacks in the retire path —
        # found only by profiling; these keys make the segment math
        # checkable from the artifact alone).
        out["serving_segments"] = r["stats"]["segments"]
        out["serving_max_concurrent"] = r["stats"]["max_concurrent"]
        if preset == "llama1b" and r["value"] < 900:
            # Contingency arm, measured in the SAME health window: the
            # r4 design ceiling is 1992 tok/s at 128.5 ms segments; if the
            # default chunk lands under the >=900 gate, the suspected cost
            # is per-segment admission/bookkeeping — chunk=48 amortizes it
            # over 1.5x the tokens. Recording both makes the adjudication
            # one artifact, not two windows.
            emit_partial(out)
            r48 = serving_benchmark(preset, built=int8_built,
                                    kv_backend="paged", chunk=48)
            out["serving_paged_chunk48_tok_s"] = r48["value"]
            out["serving_chunk48_spread_pct"] = r48["spread_pct"]
            out["serving_chunk48_latency_s_p50"] = r48["latency_s_p50"]

    if os.environ.get("EDGEMESH_BENCH_SERVE", "1") == "1":
        _stage("serving", _serving)

    # ---- Stage 7c: ragged-vs-segmented batch-shape sweep (decode-heavy /
    # prefill-heavy / 50-50) — the ablation pinning paged >= dense at every
    # batch shape via the ragged boundary launch. EDGEMESH_BENCH_RAGGED=0
    # skips.
    def _ragged():
        r = ragged_ablation_benchmark(preset, built=int8_built)
        for k, v in r.items():
            if k.startswith(("serving_", "ragged_over_")):
                out[k] = v

    if (
        os.environ.get("EDGEMESH_BENCH_RAGGED", "1") == "1"
        and os.environ.get("EDGEMESH_BENCH_SERVE", "1") == "1"
    ):
        _stage("ragged_ablation", _ragged)

    # ---- Stage 7f: tensor-parallel serving at tp8 — the multi-chip serving
    # headline (quantized, overlapped collectives; parallel/collectives.py)
    # plus the collective ablation: bf16-psum vs int8-qpsum vs qpsum+overlap
    # at b8/b32 with tok/s ratios and the greedy-agreement quality delta.
    # Needs >= 8 devices (a pod-slice window); EDGEMESH_BENCH_TP8=0 skips.
    def _tp8_serving():
        r = tp_serving_benchmark(preset, built=int8_built)
        out["serving_tp8_tok_s"] = r["value"]
        out["serving_tp8_latency_s_p50"] = r["latency_s_p50"]
        out["serving_tp8_collective_mode"] = r["collective_mode"]
        out["serving_tp8_collective_dtype"] = r["collective_dtype"]
        out["serving_tp8_collective_bytes"] = r["collective_bytes"]

    def _collective_ablation():
        r = collective_ablation_benchmark(preset, built=int8_built)
        for k, v in r.items():
            if k.startswith(("collective_", "qpsum_", "qpsum_overlap_",
                             "overlap_")):
                out[k] = v

    if os.environ.get("EDGEMESH_BENCH_TP8", "1") == "1":
        _stage("tp8_serving", _tp8_serving)
        _stage("collective_ablation", _collective_ablation)

    # ---- Stage 7b: admission-policy A/B on a mixed-budget wave — FIFO vs
    # SJF end-to-end latency at matched throughput (docs/SERVING.md SLO
    # table). EDGEMESH_BENCH_ADMIT=0 skips.
    def _admission():
        r = admission_policy_benchmark(preset, built=int8_built)
        for k, v in r.items():
            out[f"admit_{k}"] = v

    if (
        os.environ.get("EDGEMESH_BENCH_ADMIT", "1") == "1"
        and os.environ.get("EDGEMESH_BENCH_SERVE", "1") == "1"
    ):
        _stage("admission", _admission)

    # ---- Stage 7d: telemetry-driven adaptive routing vs least-outstanding
    # on a skewed 3-replica fleet (tiny in-process replicas — the routing
    # layer is under test, not the kernels). Pins the telemetry-loop win:
    # adaptive_over_least_outstanding_p99 > 1 with zero tuning config.
    # EDGEMESH_BENCH_FLEET=0 skips.
    def _adaptive_router():
        r = adaptive_router_benchmark()
        out["adaptive_over_least_outstanding_p99"] = r["value"]
        for k, v in r.items():
            if k.startswith(("adaptive_", "least_outstanding_", "slo_target")):
                out[k] = v

    if os.environ.get("EDGEMESH_BENCH_FLEET", "1") == "1":
        _stage("adaptive_router", _adaptive_router)

    # ---- Stage 7g: router/tracing/flight-recorder overhead — the per-hop
    # tax every fleet request pays, including the always-on flight ring
    # (recorder_overhead_* pins the "cheap enough to never turn off"
    # claim: recorder p50 within 2% of the recorder-off arm). Rides the
    # same EDGEMESH_BENCH_FLEET gate as the other in-process fleet stage.
    def _router_overhead():
        r = router_overhead_benchmark()
        out["router_overhead_p50_s"] = r["value"]
        out["router_overhead_p99_s"] = r["overhead_p99_s"]
        for k in ("direct_p50_s", "routed_p50_s", "traced_p50_s",
                  "tracing_overhead_p50_s", "tracing_overhead_p99_s",
                  "recorder_p50_s", "recorder_p99_s",
                  "recorder_overhead_p50_s", "recorder_overhead_p99_s",
                  "recorder_ring_records"):
            out[k] = r[k]
        # The compute-ledger overhead arm (ledger on vs off): the <=1.02
        # ratio gate PERFORMANCE.md pins. .get(): a faked stage from an
        # older schema must not fail the whole headline.
        for k in ("ledgeroff_p50_s", "ledger_overhead_p50_s",
                  "ledger_overhead_ratio"):
            out[k] = r.get(k)
        # The pool-ledger overhead arm (mem ledger on vs off): the same
        # <=1.02 ratio gate, for the memory observatory.
        for k in ("memledgeroff_p50_s", "mem_ledger_overhead_p50_s",
                  "mem_ledger_overhead_ratio"):
            out[k] = r.get(k)
        # The quality-tracker overhead arm (tracker on vs off): the same
        # <=1.02 ratio gate, for the quality observatory.
        for k in ("qualityoff_p50_s", "quality_overhead_p50_s",
                  "quality_overhead_ratio"):
            out[k] = r.get(k)

    if os.environ.get("EDGEMESH_BENCH_FLEET", "1") == "1":
        _stage("router_overhead", _router_overhead)

    # ---- Stage 7e: the load observatory — open-loop goodput-vs-offered-
    # load curve over an in-process fleet (edgemesh/loadgen/). The knee is
    # the headline: the offered load this stack should be run at; the
    # per-point tenants split makes noisy-neighbor effects visible in the
    # artifact. EDGEMESH_BENCH_LOADGEN=0 skips.
    def _load_curve():
        r = load_curve_benchmark()
        out["load_curve_knee_rps"] = r["value"]
        out["load_curve_knee_goodput_rps"] = r["knee_goodput_rps"]
        out["load_curve_collapsed"] = r["collapsed"]
        out["load_curve_slo_latency_s"] = r["slo_latency_s"]
        out["load_curve_capacity_rps"] = r["estimated_capacity_rps"]
        out["load_curve_points"] = r["points"]
        # The memory observatory beside the curve: pool snapshot per
        # point + the exhaustion forecast at the knee. .get(): a faked
        # stage from an older schema must not fail the headline.
        for k in ("mem_points", "mem_forecast_at_knee_s",
                  "mem_peak_resident_pages"):
            out[f"load_curve_{k}"] = r.get(k)

    if os.environ.get("EDGEMESH_BENCH_LOADGEN", "1") == "1":
        _stage("load_curve", _load_curve)

    # ---- Stage 7f: prefill/decode disaggregation A/B — homogeneous vs
    # tiered routing (KV streamed prefill→decode tier, shared prefix
    # cache) on a mixed long-prefill/chatty workload. The headline is
    # disagg_ttft_p99_ratio: how much tiering protects the chatty
    # tenant's TTFT p99. EDGEMESH_BENCH_DISAGG=0 skips.
    def _disagg():
        r = disagg_benchmark()
        out["disagg_ttft_p99_ratio"] = r["value"]
        out["disagg_kv_transfer_bytes"] = r["kv_transfer_bytes"]
        for k in ("homogeneous_chat_p99_s", "tiered_chat_p99_s",
                  "homogeneous_goodput_ratio", "tiered_goodput_ratio",
                  "homogeneous_tenants", "tiered_tenants",
                  "tiered_outcomes", "slo_latency_s",
                  "prefill_threshold_chars"):
            out[f"disagg_{k}"] = r[k]
        out["disagg_tiers"] = r["tiers"]
        # Per-replica pool-ledger rollups (KV import splices land as
        # 'import'-cause events in the receiving replica's ledger).
        out["disagg_mem"] = r.get("mem")

    if os.environ.get("EDGEMESH_BENCH_DISAGG", "1") == "1":
        _stage("disagg", _disagg)

    # ---- Stage 7i: ensemble-over-the-fleet — 2 QA pools + the refiner
    # pipeline vs single-model serving through the same frontend (tiny
    # in-process replicas; the coordinator is under test, not the
    # kernels). The headline is the latency price of fan-out + refine;
    # the degradation-outcome counts and the eval quality delta ride
    # beside it. EDGEMESH_BENCH_ENSEMBLE=0 skips.
    def _ensemble():
        r = fleet_ensemble_benchmark()
        out["ensemble_latency_p99_ratio"] = r["value"]
        out["ensemble_p50_s"] = r["ensemble_p50_s"]
        out["ensemble_p99_s"] = r["ensemble_p99_s"]
        out["ensemble_single_p50_s"] = r["single_p50_s"]
        out["ensemble_single_p99_s"] = r["single_p99_s"]
        out["ensemble_outcomes"] = r["outcomes"]
        out["ensemble_quality_delta"] = r["quality_delta"]
        out["ensemble_eval_samples"] = r["eval_samples"]
        # The quality observatory's online view of the same run — the
        # coordinator's cross-branch agreement EWMA ("ensemble_quality"
        # above is the offline eval score, a different animal).
        out["ensemble_quality_signals"] = r.get("quality")

    # Rides the fleet gate too: EDGEMESH_BENCH_FLEET=0 means "spin no
    # in-process fleet", and this stage spins three replicas + a frontend.
    if (
        os.environ.get("EDGEMESH_BENCH_ENSEMBLE", "1") == "1"
        and os.environ.get("EDGEMESH_BENCH_FLEET", "1") == "1"
    ):
        _stage("ensemble", _ensemble)

    # ---- Stage 7h: the capacity observatory's control loop —
    # cold-start-to-first-token with a shared compilation cache (warm vs
    # cold subprocess spawn) and the autoscale loop turning observed
    # overload into a second replica. EDGEMESH_BENCH_AUTOSCALE=0 skips.
    def _cold_start():
        r = cold_start_benchmark()
        out["cold_start_first_token_s"] = r["value"]
        for k in ("cold_start_cold_s", "cold_start_warm_s",
                  "cold_start_warm_over_cold", "cold_start_cache_entries"):
            out[k] = r[k]

    def _autoscale():
        r = autoscale_benchmark()
        out["autoscale_time_to_scale_s"] = r["value"]
        for k in ("autoscale_scaled", "autoscale_replicas",
                  "autoscale_offered_rps", "autoscale_capacity_rps",
                  "autoscale_goodput_ratio", "tuner_limit", "tuner_knee",
                  "tuner_windows"):
            out[k] = r[k]

    if os.environ.get("EDGEMESH_BENCH_AUTOSCALE", "1") == "1":
        _stage("cold_start", _cold_start)
        _stage("autoscale", _autoscale)

    # ---- Stage 8: speculative decoding at b1 (the latency regime) — on by
    # default since round 4 (EDGEMESH_BENCH_SPEC=0 skips): the reference
    # published a number for every shipped config (Table 3), so the marquee
    # decode feature carries an on-chip number too. Random-weight draft ⇒
    # the acceptance rate (reported) is near-chance and the speedup is a
    # LOWER bound; trained pairs accept far more.
    def _spec():
        # One bf16 target build serves BOTH arms (the int8_built tree the
        # other stages share is the wrong precision for the spec target).
        bf16_built = _build(preset, "bf16", "w8a16")
        r = speculative_benchmark(preset, built=bf16_built)
        out["spec_b1_tok_s"] = r["spec_tok_s"]
        out["spec_plain_b1_tok_s"] = r["plain_tok_s"]
        out["spec_speedup"] = r["spec_speedup"]
        out["spec_accept_rate"] = r["accept_rate"]
        out["spec_selfcheck_accept_rate"] = r["selfcheck_accept_rate"]
        out["spec_draft_mode"] = r["draft_mode"]
        out["spec_gamma"] = r["gamma"]
        # Round-structure attribution (obs/compute.py SpecRoundLedger):
        # measured round time, draft/verify split (analytic flops,
        # labeled), accept/reject accounting — the decomposition of the
        # spec arm's win or loss.
        out["spec_round_ledger"] = r.get("spec_round_ledger")
        emit_partial(out)
        # Composed cell: speculative over int8 page pools (both arms int8).
        r2 = speculative_benchmark(preset, kv_backend="paged_int8",
                                   built=bf16_built)
        out["spec_paged_int8_b1_tok_s"] = r2["spec_tok_s"]
        out["spec_paged_int8_plain_b1_tok_s"] = r2["plain_tok_s"]
        out["spec_paged_int8_speedup"] = r2["spec_speedup"]

    if os.environ.get("EDGEMESH_BENCH_SPEC", "1") == "1" and preset == "llama1b":
        _stage("spec", _spec)

    # ---- Stage 9: int4 (w4a16): half int8's weight bytes — the memory
    # headline beyond the reference's 38% int8 cut. Both scale granularities:
    # per-channel (fastest) and the grouped product default.
    def _int4():
        nonlocal int8_built
        del int8_built  # release before building the int4 trees
        int4 = decode_benchmark(preset, "int4", batch=batch, decode_steps=decode_steps,
                                built=_build(preset, "int4", "w8a16"))
        out["int4_w4a16_tok_s"] = int4["value"]
        out["int4_weight_gb"] = int4["weight_gb"]
        emit_partial(out)
        int4_g = decode_benchmark(preset, "int4_g64", batch=batch,
                                  decode_steps=decode_steps, repeats=2,
                                  built=_build(preset, "int4_g64", "w8a16"))
        out["int4_g64_tok_s"] = int4_g["value"]

    _stage("int4", _int4)

    # ---- Stage 10: north-star scale — Llama-3-8B int8 decode on ONE chip
    # (~8.9 GB weights, fabricated directly at int8). EDGEMESH_BENCH_8B=0 skips.
    if os.environ.get("EDGEMESH_BENCH_8B", "1") == "1" and preset == "llama1b":
        def _big():
            from edgemesh.utils.platform import tree_sync

            cfg8 = config_for_family("llama", **PRESETS["llama8b"]).replace(dtype="bfloat16")
            _progress("fabricate llama8b int8 params")
            p8 = fabricate_int8_params(cfg8)
            tree_sync(p8)
            r8 = decode_benchmark("llama8b", "int8", batch=batch,
                                  decode_steps=decode_steps, repeats=2,
                                  built=(cfg8, p8))
            out["llama8b_int8_tok_s"] = r8["value"]
            out["llama8b_weight_gb"] = r8["weight_gb"]
            out["llama8b_ttft_s"] = r8["ttft_s"]
            out["llama8b_hbm_util"] = r8["hbm_util"]

        _stage("llama8b", _big)

    # Phase breakdown + obs-registry snapshot ride the final artifact: the
    # prefill/decode split from trace() regions and every serving aggregate
    # the run produced, so a BENCH json is diagnosable without re-running.
    from edgemesh.obs import get_registry
    from edgemesh.utils.tracing import phase_report

    out["phases"] = {
        k: {kk: round(vv, 6) for kk, vv in v.items()}
        for k, v in phase_report().items()
    }
    out["obs"] = get_registry().summary(prefix="edgemesh_")
    emit_partial(out)
    return out
